"""The fleet aggregator service: collect loop + serving planes.

Promotion of ``tpumon smi``'s merged fleet view from a CLI loop to a
shard of a service, built almost entirely from planes that already
exist one layer down:

- the scrape path is the exporter's own pattern — families are built
  once per collect cycle, pre-rendered into a
  :class:`~tpumon.exporter.collector.SampleCache`, and a scrape serves
  cached bytes plus an off-path-refreshed self-telemetry render — so
  the /metrics p99 is independent of fleet size;
- admission control is the guard plane's :class:`IngressGuard` wrapped
  around the same ``_make_app`` WSGI app (request deadlines, 503
  shedding, the works) — the tier protects itself exactly like the
  exporters it watches;
- the collect loop runs under a trace-plane :class:`Tracer` cycle
  (``/debug/traces``, ``/debug/vars``), and slice rollups are recorded
  into a :class:`~tpumon.history.History` ring (``/history``) for
  downsampled retention.

``GET /fleet`` serves the JSON form — per-node states plus the
slice/pool/fleet rollup — that ``tpumon smi --aggregator`` renders.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from prometheus_client import Counter, Gauge, Histogram
from prometheus_client.registry import CollectorRegistry

from tpumon.exporter.server import ExporterServer, _json_dump, _make_app
from tpumon.exporter.telemetry import POLL_BUCKETS, SCRAPE_BUCKETS
from tpumon.fleet.config import FleetConfig
from tpumon.fleet.ingest import NodeFeed
from tpumon.fleet.rollup import (
    DARK,
    IncrementalRollup,
    classify,
    fleet_families,
    jsonable,
    merge_buckets,
    visibility_of,
)

log = logging.getLogger(__name__)

#: /healthz fails when no collect cycle completed within this many
#: intervals (the exporter's HEALTH_STALE_INTERVALS stance).
HEALTH_STALE_INTERVALS = 5.0


class FleetTelemetry:
    """Aggregator-about-itself metrics, bound to one registry (the
    second, registry-rendered half of the /metrics page)."""

    def __init__(self, registry: CollectorRegistry) -> None:
        self.scrape_duration = Histogram(
            "tpu_fleet_scrape_duration_seconds",
            "Wall time to serve one aggregator /metrics exposition "
            "(pre-aggregated page — the fleet-dashboard p99).",
            buckets=SCRAPE_BUCKETS,
            registry=registry,
        )
        self.collect_duration = Histogram(
            "tpu_fleet_collect_duration_seconds",
            "Wall time of one collect cycle (ingest scheduling + rollup "
            "+ render).",
            buckets=POLL_BUCKETS,
            registry=registry,
        )
        self.fetches = Counter(
            "tpu_fleet_node_fetches",
            "Upstream fetch outcomes by transport mode (watch/poll) and "
            "result (ok, error, parse_error, breaker_open).",
            labelnames=("mode", "result"),
            registry=registry,
        )
        self.up = Gauge(
            "tpu_fleet_up",
            "1 while the aggregator's collect loop completes cycles; 0 "
            "after a wholesale-failed cycle.",
            registry=registry,
        )
        self.shard_targets = Gauge(
            "tpu_fleet_shard_targets",
            "Upstream exporter targets owned by this shard after "
            "rendezvous-hash assignment (tpumon/fleet/shard.py).",
            registry=registry,
        )  # publish-on: fleet-collect — page-atomic, set after cache.publish
        self.watch_streams = Gauge(
            "tpu_fleet_watch_streams",
            "Upstream gRPC Watch fan-in streams by state (streaming / "
            "down / off; off = target rides HTTP polling).",
            labelnames=("state",),
            registry=registry,
        )
        self.fanin_bytes = Counter(
            "tpu_fleet_fanin_bytes",
            "Accepted fan-in payload bytes by transport mode "
            "(watch/poll) and representation kind (delta frame / full "
            "snapshot frame / text page) — the wire-cost ledger the "
            "delta protocol exists to shrink.",
            labelnames=("mode", "kind"),
            registry=registry,
        )
        self.fanin_frames = Counter(
            "tpu_fleet_fanin_frames",
            "Accepted fan-in payloads by transport mode and "
            "representation kind; frames/bytes together give "
            "bytes-per-frame per kind.",
            labelnames=("mode", "kind"),
            registry=registry,
        )
        self.fanin_resyncs = Counter(
            "tpu_fleet_fanin_resyncs",
            "Full-snapshot frames that REPLACED live delta base state, "
            "by cause (gap = sequence mismatch forced it, epoch = "
            "upstream restarted, full = upstream chose a resync: "
            "pruned base, periodic Watch resync, or patch outgrew the "
            "snapshot). A fleet-wide rate spike here is a resync storm "
            "(docs/OPERATIONS.md).",
            labelnames=("reason",),
            registry=registry,
        )
        self.rollup_dirty_nodes = Gauge(
            "tpu_fleet_rollup_dirty_nodes",
            "Feeds whose rollup-relevant content or ingest state "
            "changed last collect cycle — the observed churn the "
            "incremental rollup's work is proportional to.",
            registry=registry,
        )
        self.rollup_dirty_buckets = Gauge(
            "tpu_fleet_rollup_dirty_buckets",
            "Slice buckets re-aggregated last collect cycle; every "
            "other bucket's rollup was reused unchanged.",
            registry=registry,
        )
        self.rollup_shards = Gauge(
            "tpu_fleet_rollup_shards",
            "Striped-ingest accumulator shard count "
            "(TPUMON_FLEET_ROLLUP_STRIPES): fan-in writes land in "
            "per-slice shards keyed by rendezvous of the slice "
            "identity, so concurrent apply-delta calls never share a "
            "lock.",
            registry=registry,
        )
        self.rollup_shard_entries = Gauge(
            "tpu_fleet_rollup_shard_entries",
            "Feeds held per striped-ingest shard — a skewed "
            "distribution means one slice dominates the fleet and its "
            "shard's lock sees most of the write traffic.",
            labelnames=("shard",),
            registry=registry,
        )
        self.rollup_shard_writes = Counter(
            "tpu_fleet_rollup_shard_writes",
            "Snapshot stores landed per striped-ingest shard (the "
            "writer-contention spread; rate it to see where fan-in "
            "write traffic concentrates).",
            labelnames=("shard",),
            registry=registry,
        )
        self.rollup_dirty_stripes = Gauge(
            "tpu_fleet_rollup_dirty_stripes",
            "Striped-ingest shards actually drained last publish; "
            "clean shards replayed their cached rows, so idle-fleet "
            "publish cost is proportional to this, not to the shard "
            "count.",
            registry=registry,
        )
        self.external_metrics_requests = Counter(
            "tpu_fleet_external_metrics_requests",
            "External Metrics API requests served by the actuation "
            "adapter, by metric name and result (ok / stale / "
            "not_found / bad_request).",
            labelnames=("metric", "result"),
            registry=registry,
        )
        self.shed = Counter(
            "tpumon_shed_requests",
            "Requests refused by the aggregator's ingress guard "
            "(503 + Retry-After), by endpoint class and reason.",
            labelnames=("endpoint", "reason"),
            registry=registry,
        )
        self.membership_targets = Gauge(
            "tpu_fleet_membership_targets",
            "Target universe size by discovery source (static CSV/file "
            "read once, file re-read live, or k8s Endpoints-derived).",
            labelnames=("source",),
            registry=registry,
        )
        self.membership_changes = Counter(
            "tpu_fleet_membership_changes",
            "Live membership churn applied after debounce, by op "
            "(add / remove of universe targets).",
            labelnames=("op",),
            registry=registry,
        )
        self.peer_up = Gauge(
            "tpu_fleet_peer_up",
            "Peer aggregator shard liveness from /fleet/summary probes "
            "(1 answering, 0 past the takeover deadline), by peer "
            "shard index.",
            labelnames=("peer",),
            registry=registry,
        )
        self.takeovers = Counter(
            "tpu_fleet_takeovers",
            "Orphaned targets this shard adopted because their owning "
            "peer shard died (rendezvous over the survivors).",
            registry=registry,
        )
        self.ingest_rejects = Counter(
            "tpu_fleet_ingest_rejects",
            "Upstream payloads refused before parsing, by reason "
            "(oversized body, hostile snapshot length prefix, "
            "undecodable/unparseable page) — a corrupt feed costs a "
            "counter tick, never aggregator memory.",
            labelnames=("reason",),
            registry=registry,
        )
        self.spool_restored = Gauge(
            "tpu_fleet_spool_restored_nodes",
            "Node snapshots served from the warm-restart spool since "
            "startup (flagged by ordinary age classification).",
            registry=registry,
        )
        self.spool_errors = Counter(
            "tpu_fleet_spool_errors",
            "Warm-restart spool failures by op (load / write, plus "
            "enospc counted once per degradation transition); the "
            "aggregator runs on, cold.",
            labelnames=("op",),
            registry=registry,
        )
        self.spool_degraded = Gauge(
            "tpu_fleet_spool_degraded",
            "1 while the warm-restart spool runs memory-only because "
            "the volume is full / read-only (ENOSPC/EROFS/EDQUOT); "
            "clears on the first retry probe that writes clean.",
            registry=registry,
        )
        self.peer_seeded = Counter(
            "tpu_fleet_peer_seeded",
            "Feeds adopted on takeover/hand-back seeded warm from an "
            "alive peer shard's last-good snapshot instead of starting "
            "cold (stale-flagged by ordinary age classification until "
            "the first live fetch).",
            registry=registry,
        )


class FleetAggregator:
    """Fully wired aggregator shard: feeds + collect loop + HTTP server.

    ``ingress_overrides`` (tests) replaces individual
    :class:`IngressGuard` constructor arguments — e.g. a tiny
    ``metrics_rps`` to make shedding deterministic.
    """

    def __init__(
        self, cfg: FleetConfig, ingress_overrides: dict | None = None
    ) -> None:
        self.cfg = cfg
        self._started_at = time.time()
        self.registry = CollectorRegistry()
        self.telemetry = FleetTelemetry(self.registry)

        def observe_fetch(mode: str, result: str) -> None:
            self.telemetry.fetches.labels(mode=mode, result=result).inc()

        def observe_reject(reason: str) -> None:
            self.telemetry.ingest_rejects.labels(reason=reason).inc()

        def observe_frame(mode: str, kind: str, nbytes: int) -> None:
            self.telemetry.fanin_bytes.labels(mode=mode, kind=kind).inc(
                nbytes
            )
            self.telemetry.fanin_frames.labels(mode=mode, kind=kind).inc()

        def observe_resync(reason: str) -> None:
            self.telemetry.fanin_resyncs.labels(reason=reason).inc()

        self._observe_fetch = observe_fetch
        self._observe_reject = observe_reject
        self._observe_frame = observe_frame
        self._observe_resync = observe_resync

        # Warm-restart spool: loaded BEFORE membership so a restarted
        # shard's first feeds carry last-good snapshots (flagged by
        # ordinary age classification) and a failed first discovery
        # resolution can fall back to the journaled universe.
        self.spool = None
        self._spool_nodes: dict[str, dict] = {}
        self._spool_last_save = 0.0
        #: True while a journal write is in flight (collect thread sets,
        #: executor worker clears — a bool flip, no lock needed; worst
        #: case one deferred save).
        self._spool_saving = False
        self._restored_count = 0
        #: Adopted feeds seeded warm from a peer's /fleet snapshot
        #: (membership thread only).
        self._peer_seeded_count = 0
        spool_universe: list[str] = []
        #: Journaled actuation state ({"bands", "epoch_seq",
        #: "target_epochs"}) from the spool — seeds the membership
        #: plane's ownership epochs (a restart re-claims targets at a
        #: HIGHER epoch than it ever held, which is what makes
        #: newest-epoch-wins resolve split brain toward the restart)
        #: and the hint hysteresis (warm restarts resume held bands).
        spool_actuate: dict = {}
        if cfg.spool_dir:
            from tpumon.fleet.spool import SnapshotSpool

            self.spool = SnapshotSpool(
                cfg.spool_dir, max_bytes=cfg.spool_max_bytes
            )
            loaded = self.spool.load()
            self._spool_nodes = loaded["nodes"]
            spool_universe = loaded["universe"]
            spool_actuate = loaded.get("actuate") or {}
            if self.spool.last_load_error is not None:
                self.telemetry.spool_errors.labels(op="load").inc()

        #: Live feeds keyed by target. The dict object is REPLACED
        #: wholesale on membership change (never mutated in place), so
        #: the collect loop and poll scheduler read a consistent set by
        #: grabbing one reference — no reader locking. _apply_lock
        #: serializes the writers (membership thread + close()).
        self.feeds: dict[str, NodeFeed] = {}
        self.targets: list[str] = []
        self._apply_lock = threading.Lock()
        self._watching = False  # start_watch() deferred until start()

        from tpumon.fleet.stripes import StripedIngest

        #: Striped ingest shards (ISSUE 15): fan-in writers push stored
        #: snapshots here from their OWN threads; the collect cycle
        #: drains per-stripe state instead of taking one feed lock per
        #: feed per second.
        self.stripes = StripedIngest(cfg.rollup_stripes)
        #: Last harvested per-shard write totals (collect thread only)
        #: — the counter metric increments by delta.
        self._shard_writes_seen = [0] * self.stripes.stripe_count
        self.telemetry.rollup_shards.set(float(self.stripes.stripe_count))
        for idx in range(self.stripes.stripe_count):
            # Pre-created at 0 so the shard-distribution panel shows
            # every stripe from the first scrape, quiet ones included.
            self.telemetry.rollup_shard_writes.labels(shard=str(idx))
            self.telemetry.rollup_shard_entries.labels(shard=str(idx)).set(
                0.0
            )

        #: Fan-in budget: at most `concurrency` upstream HTTP fetches in
        #: flight per shard, whatever the fleet size. Deliberately NOT
        #: niced below the serving threads: a demoted thread that holds
        #: the GIL while preempted starves every serving thread waiting
        #: on it (priority inversion — measured: fleet-soak p50 went
        #: 3 ms → 102 ms with +15 ingest workers on a loaded 2-core
        #: box). Thread priorities do not compose with the GIL; the
        #: scrape path is protected by being cached-bytes-cheap instead.
        self._executor = ThreadPoolExecutor(
            max_workers=max(1, cfg.concurrency),
            thread_name_prefix="tpumon-fleet-fetch",
        )

        def observe_event(kind: str, n: int) -> None:
            if kind == "takeover":
                self.telemetry.takeovers.inc(n)
            else:
                self.telemetry.membership_changes.labels(op=kind).inc(n)

        from tpumon.fleet.failover import MembershipPlane

        #: Set before the membership plane exists: _apply_membership
        #: runs synchronously during its construction and consults
        #: self.actuate for peer band seeding.
        self.actuate = None

        initial_epochs = None
        if spool_actuate:
            initial_epochs = (
                spool_actuate.get("epoch_seq") or 0,
                spool_actuate.get("target_epochs") or {},
            )

        #: The membership-and-failover plane: discovery (static / file /
        #: k8s Endpoints), churn debounce, peer liveness, and rendezvous
        #: ownership over the SURVIVING shards. Constructing it applies
        #: the initial membership synchronously (feeds exist before the
        #: first collect cycle).
        self.membership = MembershipPlane(
            cfg,
            on_membership=self._apply_membership,
            observe_event=observe_event,
            initial_universe=spool_universe,
            initial_epochs=initial_epochs,
        )
        if self.spool is not None:
            self.telemetry.spool_restored.set(float(self._restored_count))

        from tpumon.exporter.collector import SampleCache

        self.cache = SampleCache(delta=cfg.render_delta)
        self.tracer = None
        if cfg.trace:
            from tpumon.trace import Tracer

            self.tracer = Tracer()
        self.history = None
        if cfg.history_window > 0:
            from tpumon.history import History

            max_samples = cfg.history_max_samples
            if max_samples <= 0:
                max_samples = type(cfg)().history_max_samples
            # native=False: rollup volume is tiny (O(slices) series at
            # collect cadence) — not worth a C++ build in this pod.
            self.history = History(
                max_age=cfg.history_window, max_samples=max_samples,
                native=False,
            )

        self._doc_lock = threading.Lock()
        self._fleet_doc: dict = {"fleet": {}, "slices": [], "pools": []}  # guarded-by: self._doc_lock
        self._cycles = 0  # guarded-by: self._doc_lock
        #: Churn-proportional rollup state (collect thread only).
        self._rollup = IncrementalRollup()

        #: Fleet efficiency ledger (tpumon/ledger): long-horizon tiered
        #: storage + per-job goodput accounting over the same rollup
        #: doc and feed entries the cycle already built — zero extra
        #: feed locks, disk/network on the fetch executor.
        self.ledger = None
        if cfg.ledger:
            from tpumon.ledger import LedgerPlane
            from tpumon.ledger.store import default_tiers

            self.ledger = LedgerPlane(
                tiers=default_tiers(
                    cfg.ledger_retention_s, cfg.ledger_max_bytes
                ),
                spool_dir=cfg.ledger_spool_dir,
                spool_every_s=cfg.ledger_spool_every_s,
                remote_write_url=cfg.ledger_remote_write_url,
                remote_write_every_s=cfg.ledger_remote_write_every_s,
                remote_write_timeout=cfg.timeout,
                dollars_per_kwh=cfg.ledger_dollars_per_kwh,
                forecast_min_history_s=cfg.ledger_forecast_min_history_s,
                forecast_every_s=cfg.ledger_forecast_every_s,
            )

        #: Actuation plane (tpumon/actuate, ISSUE 16): per-slice serving
        #: rollups + placement hints + the External Metrics adapter,
        #: riding the same rollup doc and feed entries the ledger gets.
        #: Every query it serves reads the pre-computed model — no raw
        #: per-node series on any actuation path. (self.actuate was
        #: initialized to None before membership construction above.)
        if cfg.actuate:
            from tpumon.actuate import ActuatePlane
            from tpumon.actuate.trust import min_trust_from_env

            self.actuate = ActuatePlane(
                hint_prefer=cfg.hint_prefer,
                hint_avoid=cfg.hint_avoid,
                hint_hold_cycles=cfg.hint_hold_cycles,
                # Values older than the staleness budget are served
                # flagged, same clock the rollup's own stale class uses.
                stale_after_s=max(cfg.stale_s, 3.0 * cfg.interval),
                # TPUMON_ACTUATE_MIN_TRUST (literal) wins over the
                # FleetConfig field — the trust floor is an operator
                # knob first.
                min_trust=min_trust_from_env(cfg.actuate_min_trust),
                hint_decay_s=cfg.hint_decay_s,
                # Pool-scope tpumon_days_to_saturation answers off the
                # ledger's capacity forecast; without a ledger the
                # metric serves an empty item list (absent-not-zero).
                forecast_provider=(
                    self.ledger.forecast_snapshot if self.ledger else None
                ),
            )
            bands = spool_actuate.get("bands")
            if bands:
                # Warm-restart band resume: journaled published bands
                # queue into the hysteresis (drained at the first
                # cycle), so a restart holds its bands instead of
                # re-deriving them band-by-band through the hold window.
                self.actuate.seed_bands(bands)

        from tpumon.exporter.server import _SelfTelemetryPage

        self._selfpage = _SelfTelemetryPage(self.registry)

        from tpumon.exporter.encodings import EncodedPageCache, gzip_page

        # Version-keyed gzip reuse: between collect cycles the
        # pre-aggregated page (the largest page in the system at fleet
        # scale) is unchanged, so HA Prometheus pairs re-scraping it
        # cost a dict lookup, not a deflate each.
        encoded = EncodedPageCache()

        def render(want_gzip: bool) -> bytes:
            dev, dev_version = self.cache.rendered_with_version()
            selfb, self_version = self._selfpage.latest_with_version()
            key = (dev_version, self_version)
            # Concat inside the builder: an unchanged-page scrape is a
            # pure dict lookup, no O(page) copy.
            body = encoded.get(
                ("fleet", "identity"), key, lambda: dev + selfb
            )
            if not want_gzip:
                return body
            return encoded.get(
                ("fleet", "gzip"), key, lambda: gzip_page(body)
            )

        self.guard = None
        if cfg.guard:
            from tpumon.guard import IngressGuard

            shed_counter = self.telemetry.shed

            def observe_shed(endpoint: str, reason: str) -> None:
                shed_counter.labels(endpoint=endpoint, reason=reason).inc()

            kwargs: dict = {"observe_shed": observe_shed}
            kwargs.update(ingress_overrides or {})
            self.guard = IngressGuard(**kwargs)

        app = _make_app(
            render, self.telemetry, self._health, history=self.history,
            post_scrape=self._selfpage.poke, tracer=self.tracer,
            debug_vars=self._debug_vars,
        )
        app = self._with_fleet_endpoint(app)
        if self.guard is not None:
            app = self.guard.wsgi(app)
        # serve_niceness=-5: the exporter demotes serving to protect its
        # 1 Hz poll loop, but the aggregator's headline IS serving
        # latency — its elastic side is ingest. Promoting (never
        # demoting) serving threads is GIL-safe: a boosted thread
        # waiting on the GIL wins the handoff when the holder yields,
        # while a demoted HOLDER would starve everyone (measured, the
        # hard way). Needs CAP_SYS_NICE; silently stays at 0 without it.
        self.server = ExporterServer(
            app, cfg.addr, cfg.port, guard=self.guard, serve_niceness=-5
        )

        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tpumon-fleet-collect", daemon=True
        )
        self._poll_thread = threading.Thread(
            target=self._poll_scheduler, name="tpumon-fleet-poll", daemon=True
        )

    # -- membership --------------------------------------------------------

    def _peer_seed(self, targets: list[str]) -> dict[str, dict]:
        """target -> {"snap", "fetched_at"} harvested from alive peers'
        /fleet docs — the takeover/hand-back warm start (ROADMAP item 1
        remnant): a shard adopting targets it has no spool data for asks
        the peers that were just watching them for their last-good
        snapshots, so adopted feeds serve (stale-flagged) data
        immediately instead of starting dark while every Watch stream
        redials cold. Bounded: one /fleet fetch per alive peer, each on
        the configured timeout; any failure degrades to a cold adopt."""
        watcher = self.membership.watcher if self.membership else None
        if watcher is None or not targets:
            return {}
        import json as _json
        import urllib.request

        from tpumon.fleet.failover import PROBE_ERRORS

        want = set(targets)
        out: dict[str, dict] = {}
        alive = self.membership.alive_shards()
        for index, url in watcher.peers.items():
            if index not in alive:
                continue
            if not (want - set(out)):
                break  # every adopted target already seeded
            try:
                with urllib.request.urlopen(
                    url + "/fleet", timeout=self.cfg.timeout
                ) as resp:
                    doc = _json.loads(resp.read().decode())
            except PROBE_ERRORS as exc:
                log.debug("peer %s /fleet seed fetch failed: %s", url, exc)
                continue
            if not isinstance(doc, dict):
                continue
            now = doc.get("now") or 0.0
            for node in doc.get("nodes", []):
                if not isinstance(node, dict):
                    continue
                target = node.get("target")
                snap = node.get("snap")
                age = node.get("age_s")
                if (
                    target in want
                    and isinstance(snap, dict)
                    and isinstance(age, (int, float))
                ):
                    fetched_at = now - max(0.0, float(age))
                    prev = out.get(target)
                    if prev is None or fetched_at > prev["fetched_at"]:
                        out[target] = {
                            "snap": snap, "fetched_at": fetched_at,
                        }
        return out

    def _apply_membership(self, owned: list[str], info: dict) -> None:  # thread: fleet-membership — on_membership callback, invisible to the call graph
        """Apply one ownership change from the membership plane: build
        feeds for adopted targets (seeded from the spool when we have
        their last-good data, else warm-seeded from an alive peer's
        /fleet snapshot), hand back feeds for targets a returning peer
        reclaimed. Runs on the membership thread (and once,
        synchronously, during construction)."""
        cfg = self.cfg
        # Peer warm-seed fetch happens BEFORE the apply lock (it blocks
        # on peer HTTP); self.feeds is only ever written on this thread,
        # so the pre-lock read is consistent.
        peer_seeds: dict[str, dict] = {}
        if not info.get("first"):
            current_feeds = self.feeds
            new_targets = [t for t in owned if t not in current_feeds]
            adopted = [
                t for t in new_targets if t not in self._spool_nodes
            ]
            if adopted:
                peer_seeds = self._peer_seed(adopted)
            if new_targets and self.actuate is not None:
                # Band adoption, same idea as the snapshot warm-seed:
                # the peers that were just publishing hints for these
                # targets' scopes advertise their bands on
                # /fleet/summary — seeding them means a takeover holds
                # the previous owner's bands instead of re-deriving
                # them through the hysteresis hold window. seed() only
                # fills MISSING keys, so our own live bands never
                # regress.
                bands: list[list] = []
                for summary in self.membership.peer_summaries().values():
                    peer_bands = summary.get("hint_bands")
                    if isinstance(peer_bands, list):
                        bands.extend(peer_bands)
                self.actuate.seed_bands(bands)
        with self._apply_lock:
            current = self.feeds
            next_feeds: dict[str, NodeFeed] = {}
            removed: list[NodeFeed] = []
            for target in owned:
                feed = current.get(target)
                if feed is None:
                    # Stripe admission FIRST: the restore below fires
                    # on_update into the stripes, and a never-reporting
                    # feed must still be counted (dark) from adoption.
                    self.stripes.register(target)
                    feed = NodeFeed(
                        target,
                        timeout=cfg.timeout,
                        default_grpc_port=cfg.grpc_port,
                        observe_fetch=self._observe_fetch,
                        observe_reject=self._observe_reject,
                        observe_frame=self._observe_frame,
                        observe_resync=self._observe_resync,
                        on_update=self.stripes.put,
                        delta=cfg.delta,
                        max_snapshot_bytes=cfg.max_snapshot_bytes,
                        fresh_s=cfg.stale_s,
                        poll_backoff_base_s=cfg.interval,
                        poll_backoff_max_s=cfg.poll_backoff_max_s,
                        # The breaker's open window scales with the
                        # staleness budget: a node must get its probe
                        # chance before sitting needlessly stale behind
                        # a breaker sized for a different tier (the
                        # adaptive poll backoff owns long-haul spacing).
                        breaker_open_s=min(
                            15.0, max(2.0 * cfg.interval, cfg.stale_s / 2.0)
                        ),
                    )
                    spooled = self._spool_nodes.get(target)
                    if spooled is not None:
                        feed.restore(spooled["snap"], spooled["fetched_at"])
                        self._restored_count += 1
                    else:
                        seeded = peer_seeds.get(target)
                        if seeded is not None:
                            feed.restore(
                                seeded["snap"], seeded["fetched_at"]
                            )
                            self._peer_seeded_count += 1
                            self.telemetry.peer_seeded.inc()
                    if self._watching:
                        feed.start_watch()
                next_feeds[target] = feed
            for target, feed in current.items():
                if target not in next_feeds:
                    removed.append(feed)
            self.feeds = next_feeds
            self.targets = list(owned)
            # tpu_fleet_shard_targets is deliberately NOT set here: the
            # gauge updates at collect-publish from the entries the
            # published rollup covers, so one /metrics page never claims
            # more targets than its host counts account for (a takeover
            # adopting N targets here, a cycle before the rollup folds
            # them as dark, read as "N hosts missing, unflagged").
            if self.spool is not None:
                self.telemetry.spool_restored.set(
                    float(self._restored_count)
                )
        for feed in removed:
            # Stripe eviction BEFORE stop: a hand-back must leave the
            # rollup the same cycle it leaves the shard (the peer now
            # counts it — lingering here would double-count), and a
            # late in-flight store hits the route check and is dropped.
            self.stripes.remove(feed.target)
            # Outside the apply lock: stop() joins the watch thread.
            try:
                feed.stop()
            except Exception:
                log.exception("feed stop failed for %s", feed.target)
        if not info.get("first"):
            log.info(
                "membership applied: %d owned (+%d/-%d), alive shards %s",
                len(owned), len(info.get("added", ())),
                len(info.get("removed", ())), info.get("alive"),
            )

    # -- serving -----------------------------------------------------------

    @property
    def url(self) -> str:
        return self.server.url

    def _with_fleet_endpoint(self, inner):
        """The /fleet JSON API (plus the tiny /fleet/summary peers
        probe) in front of the shared exporter app. /fleet/summary is
        DELIBERATELY outside the guard's endpoint classes, like the
        health probes: shedding peer probes under load would read as
        shard death and trigger spurious takeovers."""

        def app(environ, start_response):
            path = environ.get("PATH_INFO", "/")
            if path == "/fleet":
                with self._doc_lock:
                    doc = self._fleet_doc
                # Per-node entries build HERE, on demand: the collect
                # cycle stopped paying O(fleet) dict construction per
                # second for a document that is read a few times a
                # minute. "now" matches the node ages so peer warm-seed
                # math (now - age_s) stays exact.
                now = time.time()
                doc = {**doc, "now": now, "nodes": self._node_entries(now)}
                if self.actuate is not None:
                    # The smi --aggregator trust line reads this.
                    doc["actuate"] = self.actuate.debug_block()
                body = _json_dump(doc)
            elif path == "/fleet/summary":
                body = _json_dump(self._summary_doc())
            elif path == "/ledger" and self.ledger is not None:
                body, status = self.ledger.query_response(
                    environ.get("QUERY_STRING", "")
                )
                start_response(
                    status,
                    [
                        ("Content-Type",
                         "application/json; charset=utf-8"),
                        ("Content-Length", str(len(body))),
                    ],
                )
                return [body]
            elif path == "/hints" and self.actuate is not None:
                body, status = self.actuate.hints_response(
                    environ.get("QUERY_STRING", "")
                )
                start_response(
                    status,
                    [
                        ("Content-Type",
                         "application/json; charset=utf-8"),
                        ("Content-Length", str(len(body))),
                    ],
                )
                return [body]
            elif (
                path.startswith("/apis/external.metrics.k8s.io")
                and self.actuate is not None
            ):
                status, body, metric, result = self.actuate.adapter.handle(
                    path, environ.get("QUERY_STRING", "")
                )
                self.telemetry.external_metrics_requests.labels(
                    metric=metric or "_discovery", result=result
                ).inc()
                start_response(
                    status,
                    [
                        ("Content-Type", "application/json"),
                        ("Content-Length", str(len(body))),
                    ],
                )
                return [body]
            else:
                return inner(environ, start_response)
            start_response(
                "200 OK",
                [
                    ("Content-Type", "application/json; charset=utf-8"),
                    ("Content-Length", str(len(body))),
                ],
            )
            return [body]

        return app

    def _summary_doc(self) -> dict:
        """What a peer shard needs from us, in a few hundred bytes:
        liveness (the 200 itself), our fleet-scope bucket for its
        scope="global" merge, and our cycle/identity counters."""
        with self._doc_lock:
            doc = self._fleet_doc
            cycles = self._cycles
        out = {
            "shard": doc.get("shard", {
                "index": self.cfg.shard_index,
                "count": self.cfg.shard_count,
                "targets": len(self.targets),
            }),
            "now": doc.get("now", 0.0),
            "cycles": cycles,
            "fleet": doc.get("fleet", {}),
            "universe": len(self.membership.universe()),
            # Lamport fold input for peers minting ownership epochs: a
            # peer re-claiming targets mints above the highest epoch_seq
            # any alive shard advertises.
            "epoch_seq": self.membership.epoch_seq(),
        }
        if self.actuate is not None:
            scope_epochs: dict[str, dict[str, int]] = {}
            for (pool, slc), epoch in self.actuate.scope_epochs().items():
                scope_epochs.setdefault(pool, {})[slc] = epoch
            # Per-scope ownership claims (split-brain detection) and
            # published hint bands (peers seed adopted scopes warm).
            out["scope_epochs"] = scope_epochs
            out["hint_bands"] = self.actuate.published_bands()
        return out

    def _health(self) -> tuple[bool, str]:
        with self._doc_lock:
            cycles = self._cycles
            last = self._fleet_doc.get("now", 0.0)
        if cycles == 0:
            return False, "no collect cycle completed yet\n"
        age = time.time() - last
        budget = self.cfg.interval * HEALTH_STALE_INTERVALS
        if age > budget:
            return False, f"collect loop stale: last cycle {age:.1f}s ago\n"
        return True, "ok\n"

    def _debug_vars(self) -> dict:
        import dataclasses

        with self._doc_lock:
            cycles = self._cycles
        nodes = self._node_entries(time.time(), with_snap=False)
        doc: dict = {
            "now": time.time(),
            "uptime_seconds": time.time() - self._started_at,
            "config": dataclasses.asdict(self.cfg),
            "shard": {
                "index": self.cfg.shard_index,
                "count": self.cfg.shard_count,
                "targets": len(self.targets),
            },
            "cycles": cycles,
            "nodes": nodes,
            "membership": self.membership.snapshot(),
            "peer_seeded_nodes": self._peer_seeded_count,
            "cache_version": self.cache.rendered_with_version()[1],
            "rollup": {
                "dirty_nodes": self._rollup.last_dirty_nodes,
                "dirty_buckets": self._rollup.last_dirty_buckets,
                "stripes": self.stripes.stripe_count,
                "dirty_stripes": self.stripes.last_dirty_stripes,
                "shards": self.stripes.stats(),
            },
        }
        if self.spool is not None:
            doc["spool"] = {
                "path": self.spool.path,
                "restored_nodes": self._restored_count,
                "last_write_ts": self.spool.last_write_ts,
                "dropped_last_save": self.spool.dropped_last_save,
            }
        if self.ledger is not None:
            doc["ledger"] = self.ledger.debug_block()
        if self.actuate is not None:
            doc["actuate"] = self.actuate.debug_block()
        if self.guard is not None:
            doc["guard"] = {"ingress": self.guard.snapshot()}
        if self.tracer is not None:
            doc["trace"] = self.tracer.counts()
        if self.history is not None:
            series, samples = self.history.stats()
            doc["history"] = {"series": series, "samples": samples}
        return doc

    # -- collect loop ------------------------------------------------------

    def collect_once(self) -> dict:
        """One collect cycle: schedule stale fetches, roll up whatever
        is current, publish the pre-rendered page. Never blocks on an
        upstream — fetches complete on the executor (fan-in budget) or
        the Watch threads, and this cycle serves the snapshots that
        have already landed."""
        if self.tracer is None:
            return self._collect_cycle()
        with self.tracer.cycle() as cycle:
            doc = self._collect_cycle()
            if cycle is not None:
                cycle.stats = {"nodes": len(self.feeds)}
            return doc

    def _poll_scheduler(self) -> None:
        """Phase-spread, ADAPTIVE HTTP polling: each feed polls at a
        stable per-target phase offset, so a 64-node shard issues ~one
        fetch every interval/64 instead of a 64-fetch thundering herd
        at every tick (measured: the herd put a ~250 ms pile-up tail on
        the aggregator's own scrape p99; spread, the parse load is a
        steady trickle). Watch-fed feeds are skipped while their stream
        delivers — polling is the fallback, not a duplicate.

        The schedule is a due-time HEAP, not a per-wake scan: the old
        dict scan cost O(fleet) per wake with one wake per fetch —
        O(fleet²/interval) dict reads per second, which at the 640-node
        soak (10k-feed target regime) burned more aggregator CPU than
        the fetches themselves. Each wake now pops only what is due
        (O(log fleet) per fetch); departed targets are discarded lazily
        on pop, and adopted targets are scheduled when the feeds dict
        object identity changes (membership REPLACES the dict).

        Cadence is per-feed (``NodeFeed.next_poll_delay``): fresh feeds
        re-poll at the full interval, stale/dark/failing ones space out
        on a jittered backoff capped at TPUMON_FLEET_POLL_BACKOFF_MAX_S,
        and the first fresh page restores full cadence — so a dead
        slice costs its shard a trickle, and a 1000-node mass return
        recovers jitter-spread instead of as a poll storm."""
        import hashlib
        import heapq

        interval = self.cfg.interval
        heap: list[tuple[float, str]] = []
        #: Authoritative due time per owned target; a popped heap entry
        #: counts only when it matches (stale entries — departed
        #: targets, or a departed-then-readopted target whose OLD entry
        #: still carried a backed-off due time — discard lazily, so a
        #: re-adopted target always starts from a fresh phase).
        next_due: dict[str, float] = {}
        last_feeds: dict | None = None
        while not self._stop.is_set():
            feeds = self.feeds  # one consistent membership snapshot
            now = time.monotonic()
            if feeds is not last_feeds:
                last_feeds = feeds
                for target in list(next_due):
                    if target not in feeds:
                        del next_due[target]  # heap entry dies on pop
                for target in feeds:
                    if target not in next_due:
                        digest = hashlib.md5(target.encode()).digest()
                        phase = int.from_bytes(digest[:4], "big") / 2**32
                        due = now + phase * interval
                        next_due[target] = due
                        heapq.heappush(heap, (due, target))
            while heap and heap[0][0] <= now:
                due, target = heapq.heappop(heap)
                if next_due.get(target) != due:
                    continue  # stale entry: departed or superseded
                feed = feeds.get(target)
                if feed is None:
                    del next_due[target]
                    continue
                if (
                    feed.watch_state_now() != "streaming"
                    or feed.age() > self.cfg.stale_s
                ):
                    self._executor.submit(feed.poll)  # thread: fleet-fetch
                    next_at = now + feed.next_poll_delay(interval)
                else:
                    # Streaming and fresh: check back next interval.
                    next_at = now + interval
                next_due[target] = next_at
                heapq.heappush(heap, (next_at, target))
            sleep = interval
            if heap:
                sleep = max(0.005, heap[0][0] - time.monotonic())
            if self._stop.wait(min(sleep, interval)):
                return

    def _node_entries(self, now: float, with_snap: bool = True) -> list[dict]:
        """The /fleet per-node entries, built on demand (serving threads
        and the spool/debug paths — no longer a per-collect-cycle cost)."""
        nodes = []
        for feed in self.feeds.values():
            snap, fetched_at, error = feed.current()
            age = (
                float("inf") if fetched_at == 0.0
                else max(0.0, now - fetched_at)
            )
            entry = {
                "target": feed.target,
                "url": feed.url,
                "state": classify(age, self.cfg.stale_s, self.cfg.evict_s),
                "age_s": None if age == float("inf") else round(age, 3),
                "error": error or None,
            }
            if with_snap:
                entry["snap"] = snap
            nodes.append(entry)
        return nodes

    def _collect_cycle(self) -> dict:
        from tpumon.trace import trace_span

        t0 = time.monotonic()
        now = time.time()
        feeds = list(self.feeds.values())  # one membership snapshot
        with trace_span("ingest_schedule"):
            watch_states = {"streaming": 0, "down": 0, "off": 0}
            for feed in feeds:
                state = feed.watch_state_now()
                watch_states[state] = watch_states.get(state, 0) + 1
        with trace_span("rollup"):
            # Churn-proportional cycle over the STRIPED shards: fan-in
            # writers already pushed every stored snapshot into its
            # slice's stripe, so the publish step drains N stripe locks
            # (zero feed locks) and classifies ages — the unavoidable
            # O(fleet) floor, since fresh→stale→dark transitions happen
            # with no write arriving. Everything heavier — bucket
            # re-aggregation (native kernel), family construction,
            # render — tracks how many feeds actually CHANGED
            # (content_seq) or crossed an ingest state boundary.
            entries = self.stripes.entries(
                now, self.cfg.stale_s, self.cfg.evict_s
            )
            doc = self._rollup.update(entries)
            membership = self.membership.snapshot()
            self._merge_peers(doc, membership)
        if self.ledger is not None:
            with trace_span("ledger"):
                try:
                    self.ledger.cycle(
                        now, doc, entries, submit=self._executor.submit
                    )
                except Exception:
                    # The ledger must never take the collect loop down;
                    # a failed cycle costs one cycle of history.
                    log.exception("ledger cycle failed")
        if self.actuate is not None:
            with trace_span("actuate"):
                try:
                    self.actuate.cycle(
                        now, doc, entries,
                        goodput_jobs=(
                            self.ledger.goodput.jobs()
                            if self.ledger is not None
                            else None
                        ),
                        target_epochs=self.membership.epochs(),
                        peer_scope_epochs=self._peer_scope_epochs(),
                        restored_targets={
                            t for t, f in self.feeds.items() if f.restored
                        },
                        contested=bool(
                            (doc.get("global") or {}).get("contested")
                        ),
                    )
                except Exception:
                    # Same stance as the ledger: actuation must never
                    # take observation down — a failed cycle leaves the
                    # previous hints serving, honestly aged.
                    log.exception("actuate cycle failed")
        with trace_span("render"):
            families = fleet_families(doc)
            if self.ledger is not None:
                families = families + self.ledger.families()
            if self.actuate is not None:
                families = families + self.actuate.families()
        if self.history is not None:
            with trace_span("history_record"):
                try:
                    self.history.record_families(now, families)
                except Exception:
                    log.exception("fleet history record failed")
        with trace_span("publish"):
            self.cache.publish(families)
        fleet_doc = {
            "now": now,
            "shard": {
                "index": self.cfg.shard_index,
                "count": self.cfg.shard_count,
                "targets": len(self.targets),
            },
            "membership": membership,
            **jsonable(doc),
        }
        with self._doc_lock:
            self._fleet_doc = fleet_doc
            self._cycles += 1
        t = self.telemetry
        t.collect_duration.observe(time.monotonic() - t0)
        t.up.set(1.0)
        # Page-atomic with the rollup just published (and set AFTER the
        # publish, so an interleaved scrape can only read the honest
        # direction: new host counts against the old, smaller target
        # count). Membership changes reach the gauge one cycle later,
        # when the rollup covers the adopted targets too.
        t.shard_targets.set(float(len(entries)))
        t.rollup_dirty_nodes.set(float(self._rollup.last_dirty_nodes))
        t.rollup_dirty_buckets.set(float(self._rollup.last_dirty_buckets))
        t.rollup_shards.set(float(self.stripes.stripe_count))
        t.rollup_dirty_stripes.set(float(self.stripes.last_dirty_stripes))
        for idx, shard in enumerate(self.stripes.stats()):
            t.rollup_shard_entries.labels(shard=str(idx)).set(
                float(shard["entries"])
            )
            delta_writes = shard["writes"] - self._shard_writes_seen[idx]
            if delta_writes > 0:
                t.rollup_shard_writes.labels(shard=str(idx)).inc(
                    delta_writes
                )
                self._shard_writes_seen[idx] = shard["writes"]
        for state, n in watch_states.items():
            t.watch_streams.labels(state=state).set(float(n))
        t.membership_targets.labels(source=membership["source"]).set(
            float(membership["universe"])
        )
        for index, peer in membership.get("peers", {}).items():
            t.peer_up.labels(peer=str(index)).set(
                1.0 if peer["alive"] else 0.0
            )
        self._maybe_spool(now)
        self._selfpage.refresh()
        return fleet_doc

    def _peer_scope_epochs(self) -> dict[tuple[str, str], int]:
        """(pool, slice) -> highest ownership epoch any ALIVE peer
        advertises for the scope (off the cached /fleet/summary docs —
        no extra probes). The actuation plane withholds scopes a peer
        claims at a NEWER epoch than ours: newest-epoch-wins."""
        out: dict[tuple[str, str], int] = {}
        if self.membership.watcher is None:
            return out
        for summary in self.membership.peer_summaries().values():
            scopes = summary.get("scope_epochs")
            if not isinstance(scopes, dict):
                continue
            for pool, slices in scopes.items():
                if not isinstance(slices, dict):
                    continue
                for slc, epoch in slices.items():
                    if not isinstance(epoch, (int, float)):
                        continue
                    key = (str(pool), str(slc))
                    out[key] = max(out.get(key, 0), int(epoch))
        return out

    def _merge_peers(self, doc: dict, membership: dict) -> None:
        """Attach the cross-shard ``scope="global"`` bucket: this
        shard's fleet totals merged with every ALIVE peer's last
        /fleet/summary, with universe targets nobody currently reports
        counted DARK — so the global row reads partial (visibility < 1)
        during a peer outage or a takeover in progress, never
        silently smaller."""
        if self.membership.watcher is None:
            return
        peer_docs = self.membership.peer_summaries()
        buckets = [doc["fleet"]]
        for summary in peer_docs.values():
            fleet = summary.get("fleet")
            if isinstance(fleet, dict):
                buckets.append(fleet)
        merged = merge_buckets(buckets)
        universe_n = membership["universe"]
        seen = sum(merged["hosts"].values())
        if universe_n > seen:
            merged["hosts"][DARK] += universe_n - seen
            merged["visibility"] = visibility_of(merged["hosts"])
        elif seen > universe_n:
            # More hosts reported than the universe holds: a takeover /
            # hand-back window where two shards briefly own the same
            # targets (asymmetric partition, or a returning peer
            # re-claiming before we relinquish). The overlap is counted
            # twice in these totals for up to a probe round — FLAG it
            # (contested + stale) rather than renormalize; the flag is
            # the honesty, the window is self-healing.
            merged["contested"] = seen - universe_n
            merged["stale"] = True
        merged["shards_alive"] = len(membership["alive_shards"])
        merged["shards"] = self.cfg.shard_count
        doc["global"] = merged

    def _maybe_spool(self, now: float) -> None:
        """Journal last-good snapshots on the spool cadence (off the
        collect thread — the executor absorbs the serialize+fsync).
        One save in flight at a time: overlapping saves could land
        their os.replace out of order and regress the journal to older
        data (SnapshotSpool is single-writer by contract). A save still
        running at the next cadence tick just defers it — the retry
        happens on the following cycle. Entries build here, once per
        spool cadence, not once per collect cycle."""
        if self.spool is None or now - self._spool_last_save < self.cfg.spool_every_s:
            return
        if self._spool_saving:
            return  # last save still running; cadence clock not reset
        self._spool_saving = True
        self._spool_last_save = now
        universe = self.membership.universe()
        entries = {}
        for target, feed in self.feeds.items():
            snap, fetched_at, _error = feed.current()
            if snap is not None and fetched_at > 0.0:
                entries[target] = {"snap": snap, "fetched_at": fetched_at}
        # Actuation state captured HERE, on the collect thread (the
        # band state reads the collect-thread-only hysteresis), before
        # the save hands off to the executor.
        actuate_state = self._actuate_spool_state()

        def save() -> None:
            try:
                was_degraded = self.spool.degraded
                ok = self.spool.save(universe, entries, actuate=actuate_state)
                if self.spool.degraded and not was_degraded:
                    # Degradation transition counts ONCE — while
                    # memory-only the skipped saves are policy, not
                    # per-tick write failures.
                    self.telemetry.spool_errors.labels(op="enospc").inc()
                elif not ok and not self.spool.degraded:
                    self.telemetry.spool_errors.labels(op="write").inc()
                self.telemetry.spool_degraded.set(
                    1.0 if self.spool.degraded else 0.0
                )
            except Exception:
                log.exception("fleet spool save failed")
                self.telemetry.spool_errors.labels(op="write").inc()
            finally:
                self._spool_saving = False

        self._executor.submit(save)  # thread: fleet-spool

    def _actuate_spool_state(self) -> dict | None:
        """The spool's "actuate" section: published hint bands plus the
        ownership-epoch state a restart re-claims ABOVE. Collect thread
        (or post-shutdown close) only — band_state reads the
        hysteresis."""
        if self.actuate is None:
            return None
        return {
            "bands": self.actuate.band_state(),
            "epoch_seq": self.membership.epoch_seq(),
            "target_epochs": self.membership.epochs(),
        }

    def _run(self) -> None:
        interval = self.cfg.interval
        next_tick = time.monotonic() + interval
        while not self._stop.is_set():
            delay = next_tick - time.monotonic()
            if delay > 0 and self._stop.wait(timeout=delay):
                break
            next_tick += interval
            try:
                self.collect_once()
            except Exception:
                # The collect thread must never die; the page keeps
                # serving the last published rollup, flagged via
                # tpu_fleet_up == 0.
                log.exception("collect cycle failed")
                self.telemetry.up.set(0.0)
                try:
                    self._selfpage.refresh()
                except Exception:
                    log.exception("self-telemetry refresh failed")
            now = time.monotonic()
            if next_tick < now:
                next_tick = now + interval

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._apply_lock:
            self._watching = True
            feeds = list(self.feeds.values())
        for feed in feeds:
            feed.start_watch()
        self.collect_once()  # prime: the first scrape is never empty
        self.membership.start()
        self._poll_thread.start()
        self._thread.start()
        self.server.start()
        log.info(
            "fleet aggregator serving %s/metrics (shard %d/%d, %d targets)",
            self.server.url, self.cfg.shard_index, self.cfg.shard_count,
            len(self.targets),
        )

    def close(self) -> None:
        self._stop.set()
        self.membership.stop()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if self._poll_thread.is_alive():
            self._poll_thread.join(timeout=5.0)
        self.server.close()
        for feed in self.feeds.values():
            feed.stop()
        # cancel_futures: drain only IN-FLIGHT work. A backlog of queued
        # dark-feed polls (each worth a fetch timeout) must not push
        # shutdown past the pod's termination grace — being SIGKILLed
        # mid-close would skip the final journal below and defeat the
        # warm restart it exists for.
        self._executor.shutdown(wait=True, cancel_futures=True)
        if self.spool is not None:
            # Final journal so the restart picks up the freshest
            # last-good state (executor already drained above).
            now = time.time()
            entries = {}
            for target, feed in self.feeds.items():
                snap, fetched_at, _error = feed.current()
                if snap is not None and fetched_at > 0.0:
                    entries[target] = {
                        "snap": snap, "fetched_at": fetched_at,
                    }
            try:
                self.spool.save(
                    self.membership.universe(), entries,
                    actuate=self._actuate_spool_state(),
                )
            except Exception:
                log.exception("final fleet spool save failed")
        if self.ledger is not None:
            # Final ledger journal (executor already drained): the
            # restart resumes every tier from here, gap ledgered.
            self.ledger.close()
        self._selfpage.close()


def build_aggregator(
    cfg: FleetConfig | None = None, ingress_overrides: dict | None = None
) -> FleetAggregator:
    if cfg is None:
        cfg = FleetConfig.from_env()
    return FleetAggregator(cfg, ingress_overrides=ingress_overrides)


__all__ = ["FleetAggregator", "FleetTelemetry", "build_aggregator"]
