"""Warm-restart spool: last-good node snapshots on disk.

A restarted or rescheduled aggregator used to come up BLIND: every feed
empty, every rollup absent until the first full fan-in round — on a
1000-node shard with adaptive cadence that is a real visibility gap,
and exactly the window a crash-looping aggregator spends all its time
in. The spool closes it: the collect loop journals each feed's
last-good snapshot (plus the target universe — the rollup's identity)
to one bounded JSON file, and a fresh aggregator loads it before its
first cycle, serving STALE-FLAGGED last-good rollups within one fan-in
cycle of startup. Honesty is preserved by construction: restored
snapshots keep their original data timestamps, so the ordinary
age-classification (up/stale/dark) flags them for exactly as long as
they deserve.

Write discipline (the journald/prometheus-WAL genre, scaled way down):

- **atomic** — temp file in the same directory + ``os.replace``; a
  crash mid-write leaves the previous spool intact, never a torn one.
- **versioned** — a format byte in the document; an unknown version
  loads as empty instead of exploding on a downgrade.
- **bounded** — serialized size capped at ``max_bytes``; the OLDEST
  node entries drop first (they were closest to dark anyway).
- **corrupt-tolerant** — any load failure (truncation, garbage, bad
  JSON shapes) quarantines the file aside as ``.corrupt`` and returns
  empty: a bad spool costs the warm start, never the process.
- **degrades on a full disk** — a write failing with ENOSPC / EROFS /
  EDQUOT flips the spool to MEMORY-ONLY (:attr:`degraded`): saves are
  skipped (not attempted-and-failed every cadence tick, which is what
  a full shared emptyDir used to cost) until a retry probe every
  :data:`DEGRADED_RETRY_S` finds the disk writable again. The caller
  counts the TRANSITION (``tpu_fleet_spool_errors_total{op="enospc"}``
  once, not per tick) and exposes :attr:`degraded` as a gauge the
  TPUMonSpoolDegraded alert watches.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import tempfile
import time

log = logging.getLogger(__name__)

SPOOL_VERSION = 1
SPOOL_NAME = "fleet-spool.json"

#: While degraded (disk full / read-only), attempt a real write again
#: this often — cheap enough to notice recovery, rare enough that a
#: persistently full volume costs one failed syscall a minute, not one
#: per save cadence.
DEGRADED_RETRY_S = 30.0

#: Errnos that mean "the volume, not this write": degrade to
#: memory-only instead of re-raising the same failure every cadence.
DEGRADE_ERRNOS = frozenset({errno.ENOSPC, errno.EROFS, errno.EDQUOT})


class SnapshotSpool:
    """One shard's on-disk last-good journal. Single-writer (the
    collect loop / its executor serializes saves through one submit at
    a time); loads happen before the writer starts."""

    def __init__(
        self, directory: str, max_bytes: int = 16777216, clock=time.time
    ) -> None:
        self.directory = directory
        self.path = os.path.join(directory, SPOOL_NAME)
        self.max_bytes = max(4096, int(max_bytes))
        self._clock = clock
        self.last_write_ts = 0.0
        self.dropped_last_save = 0
        #: Set by :meth:`load`: why the last load came back empty-handed
        #: (None = clean load or a simply-absent file). The caller's
        #: error counter keys off THIS, never off quarantine files left
        #: on disk by earlier incarnations.
        self.last_load_error: str | None = None
        #: True while the spool runs memory-only because the volume is
        #: full / read-only (DEGRADE_ERRNOS). Callers count the
        #: False->True transition and gauge the state; the spool clears
        #: it on the first retry probe that writes clean.
        self.degraded = False
        self.degraded_reason: str | None = None
        self._next_retry_ts = 0.0
        #: Test/chaos hook: when set, every save attempt fails with
        #: this errno before touching the filesystem (the chaos
        #: engine's spool_enospc / spool_eio faults).
        self.inject_errno: int | None = None

    # -- write -------------------------------------------------------------

    def save(
        self,
        universe: list[str],
        nodes: dict[str, dict],
        actuate: dict | None = None,
    ) -> bool:
        """Journal ``{target: {"snap":..., "fetched_at":...}}`` plus the
        universe and, when given, the actuation plane's warm-restart
        state (published hint bands + ownership epochs). Returns False
        (and logs) on any failure — a full disk degrades warm restart,
        never the aggregator. While :attr:`degraded`, saves are
        SKIPPED memory-only (returning False without a syscall) except
        for a retry probe every DEGRADED_RETRY_S."""
        now = self._clock()
        if self.degraded and now < self._next_retry_ts:
            return False  # memory-only: skipped, not attempted
        doc = {
            "version": SPOOL_VERSION,
            "saved_at": now,
            "universe": list(universe),
            "nodes": dict(nodes),
        }
        if actuate:
            # Optional section, same version: an older reader ignores
            # the key; an older spool simply lacks it (tolerant load).
            doc["actuate"] = dict(actuate)
        try:
            body, self.dropped_last_save = self._bounded(doc)
            os.makedirs(self.directory, exist_ok=True)
            if self.inject_errno is not None:
                raise OSError(
                    self.inject_errno, os.strerror(self.inject_errno)
                )
            fd, tmp = tempfile.mkstemp(
                dir=self.directory, prefix=".spool-", suffix=".tmp"
            )
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(body)
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    log.debug("spool temp cleanup failed", exc_info=True)
                raise
            self.last_write_ts = doc["saved_at"]
            if self.degraded:
                log.info(
                    "fleet spool recovered from %s; journaling resumed",
                    self.degraded_reason,
                )
                self.degraded = False
                self.degraded_reason = None
            return True
        except (OSError, TypeError, ValueError) as exc:
            self._note_write_failure(exc, now)
            return False

    def _note_write_failure(self, exc: Exception, now: float) -> None:
        """Classify a failed save: volume-level errnos flip the spool
        to memory-only with a retry backoff; anything else stays a
        plain per-attempt failure (the next cadence tick retries)."""
        code = getattr(exc, "errno", None)
        if code in DEGRADE_ERRNOS:
            self._next_retry_ts = now + DEGRADED_RETRY_S
            if not self.degraded:
                self.degraded = True
                self.degraded_reason = errno.errorcode.get(code, str(code))
                log.warning(
                    "fleet spool degraded to memory-only (%s): %s",
                    self.degraded_reason, exc,
                )
            return
        log.warning("fleet spool write failed: %s", exc)

    def _bounded(self, doc: dict) -> tuple[bytes, int]:
        """Serialize under ``max_bytes``, dropping oldest nodes first."""
        body = json.dumps(doc, sort_keys=True).encode()
        dropped = 0
        while len(body) > self.max_bytes and doc["nodes"]:
            by_age = sorted(
                doc["nodes"],
                key=lambda t: doc["nodes"][t].get("fetched_at", 0.0),
            )
            # Drop in batches proportional to the overshoot so a very
            # over-budget spool doesn't re-serialize per entry.
            overshoot = len(body) / self.max_bytes
            batch = max(1, int(len(doc["nodes"]) * (1.0 - 1.0 / overshoot)))
            for target in by_age[:batch]:
                del doc["nodes"][target]
                dropped += 1
            body = json.dumps(doc, sort_keys=True).encode()
        if dropped:
            log.warning(
                "fleet spool over %d bytes: dropped %d oldest node "
                "entries", self.max_bytes, dropped,
            )
        return body, dropped

    # -- read --------------------------------------------------------------

    def load(self) -> dict:
        """The journaled state: ``{"universe": [...], "nodes": {target:
        {"snap":..., "fetched_at":...}}, "actuate": {...}, "saved_at":
        ts}`` — empty on absence, corruption, or version mismatch
        (quarantined aside). ``actuate`` is ``{}`` for spools written
        before the section existed."""
        empty = {"universe": [], "nodes": {}, "actuate": {}, "saved_at": 0.0}
        self.last_load_error = None
        try:
            with open(self.path, "rb") as fh:
                raw = fh.read(self.max_bytes + 1)
        except FileNotFoundError:
            return empty  # cold start, not an error
        except OSError as exc:
            log.warning("fleet spool unreadable: %s", exc)
            self.last_load_error = str(exc)
            return empty
        try:
            if len(raw) > self.max_bytes:
                raise ValueError("spool exceeds max_bytes")
            doc = json.loads(raw.decode())
            if not isinstance(doc, dict):
                raise ValueError("spool root is not an object")
            if doc.get("version") != SPOOL_VERSION:
                log.warning(
                    "fleet spool version %r != %d; ignoring",
                    doc.get("version"), SPOOL_VERSION,
                )
                return empty
            universe = doc.get("universe")
            nodes = doc.get("nodes")
            if not isinstance(universe, list) or not isinstance(nodes, dict):
                raise ValueError("spool fields have wrong shapes")
            out_nodes: dict[str, dict] = {}
            for target, entry in nodes.items():
                if (
                    isinstance(target, str)
                    and isinstance(entry, dict)
                    and isinstance(entry.get("snap"), dict)
                    and isinstance(entry.get("fetched_at"), (int, float))
                ):
                    out_nodes[target] = entry
            actuate = doc.get("actuate")
            return {
                "universe": [t for t in universe if isinstance(t, str)],
                "nodes": out_nodes,
                "actuate": actuate if isinstance(actuate, dict) else {},
                "saved_at": float(doc.get("saved_at") or 0.0),
            }
        except (ValueError, UnicodeDecodeError) as exc:
            quarantine = self.path + ".corrupt"
            log.warning(
                "fleet spool corrupt (%s); quarantining to %s",
                exc, quarantine,
            )
            self.last_load_error = str(exc)
            try:
                os.replace(self.path, quarantine)
            except OSError:
                log.debug("spool quarantine failed", exc_info=True)
            return empty


__all__ = [
    "DEGRADE_ERRNOS",
    "DEGRADED_RETRY_S",
    "SnapshotSpool",
    "SPOOL_NAME",
    "SPOOL_VERSION",
]
