"""Fleet aggregation tier: from one exporter to a million-series fleet.

Everything below this package watches ONE node. Dashboards and alerting
for a whole org cannot fan a million raw per-chip series through
Prometheus at interactive latency (PAPERS.md "Instant GPU Efficiency
Visibility at Fleet Scale", arxiv 2605.20799) — they need a
pre-aggregated tier. This package is that tier:

- :mod:`tpumon.fleet.config` — ``TPUMON_FLEET_*`` knobs
  (:class:`FleetConfig`), resolved the same env-first way as
  tpumon.config.
- :mod:`tpumon.fleet.shard` — deterministic rendezvous-hash target
  ownership so N aggregator shards split a fleet with minimal movement
  on resize (:func:`owned_targets`).
- :mod:`tpumon.fleet.ingest` — the fan-in: one :class:`NodeFeed` per
  exporter, preferring the exporter's gRPC Watch stream (1 Hz push)
  and falling back to bounded HTTP /metrics polling, with the
  resilience plane's per-upstream circuit breaker + reconnect backoff
  and stale-but-served last-good snapshots.
- :mod:`tpumon.fleet.stripes` — striped ingest shards: fan-in writers
  push stored snapshots into per-slice accumulator shards (locks keyed
  by rendezvous of the slice identity), so concurrent apply-delta
  calls never share a lock and the collect cycle drains N shards
  instead of taking one feed lock per feed per second.
- :mod:`tpumon.fleet.rollup` — hierarchical node→slice→pool→fleet
  merge (duty, HBM headroom, ICI health scored per slice, MFU,
  degraded/stale/dark host counts) and the ``tpu_fleet_*``
  recording-rule-style families built from it; the bucket folds run
  through the native kernel (``tpumon/_native/_rollup.c``) with pinned
  byte-identical Python fallbacks.
- :mod:`tpumon.fleet.server` — :class:`FleetAggregator`: the collect
  loop, the pre-rendered /metrics page (SampleCache reuse), the
  ``/fleet`` JSON API ``tpumon smi --aggregator`` consumes, guard-plane
  admission control on its own ingress, trace spans + /debug/vars, and
  downsampled rollup retention via tpumon.history.

Per-node series are deliberately NOT re-exported: the tier serves
slice-granularity rollups (a v5p-64 × N-pool fleet is a few dozen
series, not a million) — drill-down goes to the node exporter the
rollup names.
"""

from __future__ import annotations

from tpumon.fleet.config import FleetConfig
from tpumon.fleet.shard import owned_targets, shard_of

__all__ = ["FleetConfig", "owned_targets", "shard_of"]
