"""Fan-in: one :class:`NodeFeed` per upstream exporter.

Transport preference mirrors the DCGM-hostengine genre: the exporter's
own gRPC ``tpumon.v1.Metrics/Watch`` stream when reachable (one push per
poll cycle — the aggregator sees every 1 Hz sample, where HTTP polling
sees one per collect interval), falling back to bounded HTTP /metrics
polling. Both paths land in the same place: the feed's last-good parsed
snapshot with a fetched-at timestamp, from which staleness is *derived*
(tpumon/fleet/rollup.py) rather than tracked as mutable state.

Resilience reuse (tpumon/resilience): HTTP fetches ride a per-upstream
:class:`~tpumon.resilience.breaker.CircuitBreaker` (a dark node costs
one probe per open window, not a timeout per collect cycle), and Watch
reconnects ride a jittered :class:`~tpumon.resilience.policy.Backoff`
(a slice-wide exporter restart must not synchronize every shard's
reconnect storm). A failed fetch never clears the last-good snapshot:
stale-but-served with explicit age beats a silent gap, exactly the
degrade.py stance one layer down.
"""

from __future__ import annotations

import http.client
import logging
import re
import threading
import time
import urllib.error

from tpumon.resilience import Backoff, CircuitBreaker

log = logging.getLogger(__name__)

#: A Watch stream is given this overall deadline, then redialed: a
#: half-dead HTTP/2 peer can park a stream forever without it, and one
#: reconnect per window per node is noise.
WATCH_STREAM_DEADLINE_S = 300.0

#: Everything an upstream exporter (or whatever squats on its port) can
#: throw at the HTTP fetch path: connect failures, torn reads, and
#: non-exposition response text — the same curated set tpumon.smi uses.
FETCH_ERRORS: tuple[type[BaseException], ...] = (
    urllib.error.URLError,
    OSError,
    http.client.HTTPException,
    ValueError,
)


def parse_target(entry: str, default_grpc_port: int = -1):
    """``http://node:9400[|grpc=node:9401]`` -> (base_url, grpc_addr|None).

    A bare ``node:9400`` gets ``http://``. With no per-target override,
    ``default_grpc_port >= 0`` derives the Watch address from the URL's
    host (the DaemonSet serves one TPUMON_GRPC_SERVE_PORT fleet-wide).
    """
    url = entry
    grpc_addr = None
    if "|" in entry:
        url, _, opts = entry.partition("|")
        for opt in opts.split("|"):
            key, _, value = opt.partition("=")
            if key.strip() == "grpc" and value.strip():
                grpc_addr = value.strip()
    url = url.strip()
    if not url.startswith(("http://", "https://")):
        url = "http://" + url
    url = url.rstrip("/")
    if grpc_addr is None and default_grpc_port >= 0:
        host = url.split("//", 1)[1].rsplit(":", 1)[0]
        grpc_addr = f"{host}:{default_grpc_port}"
    return url, grpc_addr


#: Label pairs inside one sample line. Values in this schema never
#: contain escaped quotes, so a flat scan is exact (coords like "0,0,0"
#: are why splitting on commas would NOT be).
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')

#: Per-chip gauge families -> snapshot field (the tpumon.smi vocabulary).
_CHIP_FIELDS = {
    "accelerator_duty_cycle_percent": "duty_pct",
    "accelerator_memory_used_bytes": "hbm_used",
    "accelerator_memory_total_bytes": "hbm_total",
    "accelerator_throttle_score": "throttle",
    "accelerator_power_watts": "power_w",
}

#: Identity labels lifted off the first accelerator_info sample.
_IDENTITY_KEYS = ("slice", "host", "accelerator", "worker")


def node_snapshot_from_text(text: str) -> dict:
    """Parse one exporter /metrics page into the fleet's node snapshot
    (the tpumon.smi structured form, plus workload MFU when present).

    This is a TARGETED line parser, not a general exposition parser:
    the rollup consumes ~10 families of a page whose bulk is histogram
    buckets, and ``prometheus_client``'s parser materializes all of it
    (measured: 78 ms per 43 KB page — at fleet fan-in rates that is
    most of a core spent inside the aggregator's GIL, starving its own
    scrape serving). Scanning lines and regex-parsing labels only for
    wanted families costs ~1-2 ms. Equivalence with the full parser on
    the shared fields is pinned by tests/test_fleet.py; ROADMAP item 2
    (negotiated protobuf exposition) is the next step down this path.
    """
    snap: dict = {
        "identity": {},
        "chips": {},
        "cores": {},
        "ici": {"healthy": 0, "total": 0, "worst": None},
        "coverage": None,
        "device_count": None,
    }
    chips = snap["chips"]
    queues: dict[str, float] = {}
    links: dict[str, float] = {}
    stale_families: dict[str, float] = {}
    degraded_active = None
    healthy = total = 0
    worst = None
    for line in text.splitlines():
        if not line or line[0] == "#":
            continue
        brace = line.find("{")
        space = line.find(" ") if brace < 0 else -1
        name = line[:brace] if brace >= 0 else line[:space]
        if name in _CHIP_FIELDS:
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            value = float(line.rsplit(" ", 1)[1])
            chips.setdefault(labels.get("chip", "?"), {})[
                _CHIP_FIELDS[name]
            ] = value
        elif name == "accelerator_info":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            # Keyed on a label this branch owns, NOT dict truthiness:
            # the slice-host-count lift below lands in identity first
            # (it precedes accelerator_info on the page) and must not
            # suppress the base-label lift.
            if "host" not in snap["identity"]:
                for key in _IDENTITY_KEYS:
                    if key in labels:
                        snap["identity"][key] = labels[key]
            chips.setdefault(labels.get("chip", "?"), {})["coords"] = (
                labels.get("coords", "")
            )
        elif name == "accelerator_interconnect_link_health":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            value = float(line.rsplit(" ", 1)[1])
            link = labels.get("link", "?")
            links[link] = value
            total += 1
            if value == 0:
                healthy += 1
            if worst is None or value > worst[1]:
                # A list, not a tuple: the snapshot must survive the
                # compact binary encoding's JSON round-trip unchanged
                # (decode == parse, tests/test_render_delta.py).
                worst = [link, value]
        elif name == "accelerator_core_utilization_percent":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            snap["cores"][labels.get("core", "?")] = float(
                line.rsplit(" ", 1)[1]
            )
        elif name == "accelerator_queue_size":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            queues[labels.get("core", "?")] = float(line.rsplit(" ", 1)[1])
        elif name == "accelerator_device_count":
            snap["device_count"] = int(float(line.rsplit(" ", 1)[1]))
        elif name == "accelerator_slice_host_count":
            # Mirrors the full parser's identity lift (smi) — the
            # equivalence test pins the two snapshots field-for-field.
            snap["identity"]["hosts"] = int(float(line.rsplit(" ", 1)[1]))
        elif name == "collector_last_poll_timestamp_seconds":
            snap["last_poll_ts"] = float(line.rsplit(" ", 1)[1])
        elif name == "exporter_metric_coverage_ratio":
            snap["coverage"] = float(line.rsplit(" ", 1)[1])
        elif name == "tpumon_degraded":
            degraded_active = float(line.rsplit(" ", 1)[1]) > 0
        elif name == "tpumon_family_staleness_seconds":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            stale_families[labels.get("family", "?")] = float(
                line.rsplit(" ", 1)[1]
            )
        elif name == "workload_mfu_ratio":
            snap["mfu"] = float(line.rsplit(" ", 1)[1])
        elif name == "tpu_straggler_skew_pct":
            snap.setdefault("straggler", {}).setdefault("active", False)
            snap["straggler"]["skew_pct"] = float(line.rsplit(" ", 1)[1])
        elif name == "tpu_straggler_step_skew_ratio":
            snap.setdefault("straggler", {}).setdefault("active", False)
            snap["straggler"]["step_skew_ratio"] = float(
                line.rsplit(" ", 1)[1]
            )
        elif name == "tpu_straggler_verdict":
            # Active straggler with its attributed cause (tpumon/hostcorr)
            # — the fleet tier counts and ranks these across pools.
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            st = snap.setdefault("straggler", {})
            st["active"] = True
            st["cause"] = labels.get("cause", "unknown")
            st["chip"] = labels.get("chip", "")
        elif name == "tpu_hostcorr_available":
            snap["hostcorr_available"] = float(line.rsplit(" ", 1)[1]) > 0
        elif name == "tpu_lifecycle_step_rate":
            # Workload training progress (tpumon/lifecycle) — rolled up
            # per slice as tpu_fleet_step_rate.
            snap["step_rate"] = float(line.rsplit(" ", 1)[1])
        elif name == "tpu_lifecycle_state":
            snap["lifecycle_transition"] = float(line.rsplit(" ", 1)[1]) > 0
        elif name == "tpu_lifecycle_events_total":
            # Transition counters by kind — what lets the goodput
            # ledger (tpumon/ledger) attribute an active transition
            # window to preempted vs restore vs resize.
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            snap.setdefault("lifecycle_events", {})[
                labels.get("kind", "?")
            ] = float(line.rsplit(" ", 1)[1])
        elif name == "tpu_lifecycle_checkpoints_total":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            snap.setdefault("checkpoints", {})[
                labels.get("op", "?")
            ] = float(line.rsplit(" ", 1)[1])
        elif name == "tpu_lifecycle_collective_wait_fraction":
            snap["collective_wait"] = float(line.rsplit(" ", 1)[1])
        elif name == "tpu_lifecycle_serve_requests_per_second":
            # Serving-scenario join (tpumon/lifecycle ← tpumon/workload/
            # serve.py) — the actuation plane (tpumon/actuate) rolls the
            # serve block up per slice for External Metrics queries.
            snap.setdefault("serve", {})["requests_per_second"] = float(
                line.rsplit(" ", 1)[1]
            )
        elif name == "tpu_lifecycle_serve_queue_depth":
            snap.setdefault("serve", {})["queue_depth"] = float(
                line.rsplit(" ", 1)[1]
            )
        elif name == "tpu_lifecycle_serve_ttft_seconds":
            snap.setdefault("serve", {})["ttft_seconds"] = float(
                line.rsplit(" ", 1)[1]
            )
        elif name == "tpu_lifecycle_serve_slo_attainment_ratio":
            snap.setdefault("serve", {})["slo_attainment_ratio"] = float(
                line.rsplit(" ", 1)[1]
            )
        elif name == "tpu_lifecycle_serve_batch_size":
            snap.setdefault("serve", {})["batch_size"] = float(
                line.rsplit(" ", 1)[1]
            )
        elif name == "tpu_energy_power_watts":
            # Energy plane (tpumon/energy) — summed to node watts for
            # the tpu_fleet_energy_watts rollup; one modeled chip makes
            # the node (and so the scope) read modeled.
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            row = snap.setdefault(
                "energy", {"watts": 0.0, "source": "measured"}
            )
            row["watts"] = row.get("watts", 0.0) + float(
                line.rsplit(" ", 1)[1]
            )
            if labels.get("source") != "measured":
                row["source"] = "modeled"
        elif name == "tpu_step_tokens_per_joule":
            labels = dict(_LABEL_RE.findall(line[brace:line.rfind("}") + 1]))
            row = snap.setdefault(
                "energy", {"watts": 0.0, "source": "measured"}
            )
            row["tokens_per_joule"] = float(line.rsplit(" ", 1)[1])
            if labels.get("source") != "measured":
                row["source"] = "modeled"
    if queues:
        snap["queues"] = queues
    if total:
        snap["ici"] = {
            "healthy": healthy,
            "total": total,
            "worst": worst if worst and worst[1] > 0 else None,
            "links": links,
        }
    if degraded_active is not None:
        snap["degraded"] = {
            "active": degraded_active,
            "families": stale_families,
        }
    return snap


class NodeFeed:
    """One upstream exporter's ingest state.

    Mutated from the Watch thread and the fetch executor; read from the
    collect loop and HTTP threads (via the aggregator's /fleet doc) —
    one small lock guards the snapshot triple.
    """

    def __init__(
        self,
        target: str,
        *,
        timeout: float = 2.0,
        default_grpc_port: int = -1,
        breaker_failures: int = 3,
        breaker_open_s: float = 15.0,
        observe_fetch=None,
        observe_reject=None,
        observe_frame=None,
        observe_resync=None,
        on_update=None,
        max_snapshot_bytes: int = 8388608,
        fresh_s: float = float("inf"),
        poll_backoff_base_s: float = 1.0,
        poll_backoff_max_s: float = 60.0,
        delta: bool = True,
        clock=time.time,
    ) -> None:
        self.target = target
        self.url, self.grpc_addr = parse_target(target, default_grpc_port)
        self.timeout = timeout
        self._clock = clock
        self._observe_fetch = observe_fetch
        self._observe_reject = observe_reject
        #: observe_frame(mode, kind, nbytes): fan-in wire accounting —
        #: every accepted payload counted by transport mode (watch/poll)
        #: and representation kind (delta/snapshot/text); feeds the
        #: tpu_fleet_fanin_{bytes,frames}_total self-metrics.
        self._observe_frame = observe_frame
        #: observe_resync(reason): full-snapshot frames that REPLACED
        #: live delta state, by cause (gap / epoch / full / reconnect) —
        #: the resync-storm triage signal (docs/OPERATIONS.md).
        self._observe_resync = observe_resync
        #: on_update(target, snap, data_ts, content_seq): striped-ingest
        #: push (tpumon/fleet/stripes.py) — every stored snapshot lands
        #: in its slice's accumulator shard from the WRITER's thread, so
        #: the collect cycle stops taking one feed lock per feed per
        #: second. Values are the ones captured under this feed's lock.
        self._on_update = on_update
        #: Negotiate the delta encoding (ROADMAP item 3). Off, the feed
        #: asks for snapshot/text only — the full-payload-per-fetch
        #: baseline the soak A/Bs against.
        self.delta = delta
        #: Payload hard cap: HTTP bodies read at most this far, and a
        #: snapshot frame DECLARING more is rejected pre-allocation.
        self.max_snapshot_bytes = max(4096, int(max_snapshot_bytes))
        #: Data younger than this counts as fresh — the adaptive-cadence
        #: reset condition. A zombie page (fetch ok, frozen data) never
        #: resets the backoff, so dark-but-answering nodes back off too.
        self.fresh_s = fresh_s
        #: HTTP-path breaker: a dark node costs one probe per open
        #: window instead of a fetch timeout per collect cycle.
        self.breaker = CircuitBreaker(
            failures=breaker_failures, open_s=breaker_open_s
        )
        #: Watch reconnect schedule (jittered, capped).
        self.backoff = Backoff(base_s=1.0, max_s=60.0)
        #: Adaptive HTTP poll cadence (ROADMAP item 1 follow-up): a
        #: stale/dark/failing feed's polls space out on this jittered
        #: schedule; the first FRESH page resets it to full cadence.
        #: Jitter is what makes mass recovery storm-free — 1000 nodes
        #: returning at once re-poll spread over the backoff window,
        #: then settle back to the phase-spread steady state.
        self.poll_backoff = Backoff(
            base_s=max(0.1, poll_backoff_base_s),
            max_s=max(poll_backoff_base_s, poll_backoff_max_s),
        )
        self._lock = threading.Lock()
        self._snap: dict | None = None  # guarded-by: self._lock
        self._fetched_at: float = 0.0  # guarded-by: self._lock
        self._last_error: str = ""  # guarded-by: self._lock
        #: Delta-protocol base state: the snapshot the next patch
        #: applies to, its sequence number, and (HTTP path only) the
        #: server's stream epoch. One state for both transports — the
        #: exporter serves one sequence space, so a feed can fail over
        #: watch→poll without resyncing.
        self._delta_state: dict | None = None  # guarded-by: self._lock
        self._delta_seq: int | None = None  # guarded-by: self._lock
        self._delta_epoch: int | None = None  # guarded-by: self._lock
        #: Bumped only when a stored snapshot's ROLLUP-RELEVANT content
        #: changed (everything except the heartbeat timestamp): the
        #: incremental rollup's dirtiness signal. An idle node heartbeats
        #: every cycle without dirtying its buckets.
        self.content_seq = 0  # guarded-by: self._lock
        self._content_cmp: dict | None = None  # guarded-by: self._lock
        #: "streaming" while the Watch stream delivers, "down" between
        #: reconnects, "off" when Watch is not configured.
        self.watch_state = "off" if self.grpc_addr is None else "down"  # guarded-by: self._lock
        #: True while the last stored snapshot arrived as a decoded
        #: compact frame rather than a parsed text page (evidence that
        #: the negotiated encoding is actually in use).
        self.snapshot_decoded = False  # guarded-by: self._lock
        #: True while the served snapshot came from the warm-restart
        #: spool (or a peer warm-seed) rather than a live fetch — a
        #: trust input for the actuation plane (spool-restore warmth);
        #: the first live store clears it.
        self.restored = False  # guarded-by: self._lock
        self._inflight = False  # guarded-by: self._lock
        #: Persistent poll connection; touched only inside poll()
        #: (serialized by _inflight), never concurrently.
        self._conn: http.client.HTTPConnection | None = None
        self._stop = threading.Event()
        self._watch_thread: threading.Thread | None = None
        self._watch_call = None  # guarded-by: self._lock

    # -- snapshot access ---------------------------------------------------

    def store_page(
        self, body: bytes, mode: str, *,
        delta_seq: int | None = None, delta_epoch: int | None = None,
    ) -> str:
        """Publish one fetched payload, whichever representation arrived:
        a delta frame patches this feed's base state (sequence-checked —
        a gap forces a resync, NEVER a silent merge), a compact snapshot
        frame decodes directly and becomes the new base, anything else
        is a text exposition page for the line parser — which is exactly
        what an old, non-negotiating exporter serves no matter what we
        asked for. ``delta_seq``/``delta_epoch`` carry the transport's
        sequence metadata (HTTP response header / gRPC PageResponse
        version). Returns "ok", "text" (stored ok via the text parser —
        the upstream is not speaking the binary protocol), "rejected",
        "stale" (a late in-flight frame older than the held base:
        discarded, state kept), or "gap" (delta base mismatch: the
        caller should treat the stream as broken)."""
        from tpumon.exporter.encodings import (
            apply_delta,
            decode_delta,
            decode_snapshot,
            is_delta,
            is_snapshot,
        )

        if len(body) > self.max_snapshot_bytes:
            # The transport reads were already capped; a body at the cap
            # is a truncation, and truncated data must not be trusted.
            log.warning(
                "%s: payload via %s exceeds %d-byte cap; rejected",
                self.url, mode, self.max_snapshot_bytes,
            )
            self._reject(mode, "oversized")
            return "rejected"
        if is_delta(body):
            try:
                delta = decode_delta(body, max_bytes=self.max_snapshot_bytes)
            except ValueError as exc:
                log.warning(
                    "%s: bad delta frame via %s: %s", self.url, mode, exc
                )
                self._reject(mode, "bad_frame")
                return "rejected"
            with self._lock:
                state = self._delta_state
                seq = self._delta_seq
            if state is None or seq != delta["base"]:
                if (
                    state is not None
                    and seq is not None
                    and delta["seq"] <= seq
                ):
                    # A LATE frame, not a gap: an in-flight poll
                    # response can land after a Watch reconnect already
                    # resynced the base forward (both transports share
                    # one seq space, so the compare is meaningful).
                    # Discard the frame, keep the live state — dropping
                    # it here would cascade into a spurious gap on the
                    # healthy stream's next push.
                    log.debug(
                        "%s: discarding stale delta frame seq %s (held "
                        "%s) via %s", self.url, delta["seq"], seq, mode,
                    )
                    return "stale"
                # Sequence gap (or no base at all): applying would be
                # silent drift — drop the base so the next fetch carries
                # no base and lands a full resync frame instead.
                log.warning(
                    "%s: delta base %s does not match held seq %s via %s; "
                    "forcing resync", self.url, delta["base"], seq, mode,
                )
                self._drop_delta_state()
                self._count_resync("gap")
                return "gap"
            merged = apply_delta(state, delta)
            with self._lock:
                self._delta_state = merged
                self._delta_seq = delta["seq"]
                if delta_epoch is not None:
                    self._delta_epoch = delta_epoch
            self._count_frame(mode, "delta", len(body))
            self.store_snapshot(merged, mode, decoded=True)
            return "ok"
        if is_snapshot(body):
            try:
                snap = decode_snapshot(
                    body, max_bytes=self.max_snapshot_bytes
                )
            except ValueError as exc:
                log.warning(
                    "%s: bad snapshot frame via %s: %s", self.url, mode, exc
                )
                self._reject(mode, "bad_frame")
                return "rejected"
            if self.delta:
                # A full frame while holding live base state is a resync
                # (server restart = epoch change; pruned base, periodic
                # Watch resync, or patch-outgrew-snapshot = full).
                with self._lock:
                    had_state = self._delta_state is not None
                    prev_epoch = self._delta_epoch
                    self._delta_state = snap
                    self._delta_seq = delta_seq
                    self._delta_epoch = delta_epoch
                if had_state and delta_seq is not None:
                    if (
                        delta_epoch is not None
                        and prev_epoch is not None
                        and delta_epoch != prev_epoch
                    ):
                        self._count_resync("epoch")
                    else:
                        self._count_resync("full")
            self._count_frame(mode, "snapshot", len(body))
            self.store_snapshot(snap, mode, decoded=True)
            return "ok"
        try:
            text = body.decode()
        except UnicodeDecodeError as exc:
            log.warning("%s: undecodable page via %s: %s", self.url, mode, exc)
            self._reject(mode, "undecodable")
            return "rejected"
        # A text page means the upstream does not speak the binary
        # protocol (or negotiation fell back): any held base state is
        # from a different world — drop it rather than risk a later
        # stale-base apply. The distinct return value lets the Watch
        # loop downgrade its requested format for old exporters.
        self._drop_delta_state()
        self._count_frame(mode, "text", len(body))
        self.store_text(text, mode)
        return "text"

    def _drop_delta_state(self) -> None:
        with self._lock:
            self._delta_state = None
            self._delta_seq = None
            self._delta_epoch = None

    def _count_frame(self, mode: str, kind: str, nbytes: int) -> None:
        if self._observe_frame is not None:
            try:
                self._observe_frame(mode, kind, nbytes)
            except Exception:
                # A metrics hiccup must never fail the ingest path.
                log.debug("frame observer failed", exc_info=True)

    def _count_resync(self, reason: str) -> None:
        if self._observe_resync is not None:
            try:
                self._observe_resync(reason)
            except Exception:
                log.debug("resync observer failed", exc_info=True)

    def store_text(self, text: str, mode: str) -> None:
        """Parse + publish one exposition page."""
        try:
            snap = node_snapshot_from_text(text)
        except Exception as exc:
            # A garbage page is an upstream bug, not a feed crash — the
            # last-good snapshot keeps serving, aged.
            log.warning("%s: unparseable page via %s: %s", self.url, mode, exc)
            self._reject(mode, "unparseable")
            return
        self.store_snapshot(snap, mode)

    def store_snapshot(self, snap: dict, mode: str, decoded: bool = False) -> None:
        """Publish one parsed/decoded node snapshot (all transports and
        representations land here)."""
        now = self._clock()
        # Effective data timestamp: the fetch time MINUS how stale the
        # node's own poll loop already was when it served this page
        # (collector_last_poll_timestamp_seconds). A zombie exporter —
        # HTTP plane answering, poll loop dead — must age toward
        # stale/dark exactly like a node that stopped answering; fetch
        # success alone is not freshness. Skew-clamped: a node with a
        # broken clock reads as very stale (operators see it), never as
        # fresher than the fetch.
        data_ts = now
        last_poll = snap.get("last_poll_ts")
        if last_poll:
            data_ts = now - min(max(0.0, now - last_poll), 3600.0)
        # Rollup-relevant content fingerprint: everything except the
        # heartbeat timestamp. One shallow dict build + C-speed deep
        # equality per store — what lets the incremental rollup skip
        # idle nodes entirely.
        cmp = {k: v for k, v in snap.items() if k != "last_poll_ts"}
        with self._lock:
            self._snap = snap
            self._fetched_at = data_ts
            self._last_error = ""
            self.snapshot_decoded = decoded
            self.restored = False
            if self._content_cmp != cmp:
                self._content_cmp = cmp
                self.content_seq += 1
            # The stripe push happens UNDER this feed's lock: the Watch
            # thread and a poll-executor fetch can store concurrently
            # during a transport transition, and dispatching after
            # release could publish an older snapshot over a newer one
            # (the stripe would then serve regressed data and a stale
            # data_ts until the next store). Lock order feed→stripe is
            # acyclic — nothing takes a feed lock while holding a
            # stripe or route lock.
            if self._on_update is not None:
                try:
                    self._on_update(
                        self.target, snap, data_ts, self.content_seq
                    )
                except Exception:
                    # A striping hiccup must never fail the ingest
                    # path; the next store re-lands the state.
                    log.exception(
                        "%s: ingest stripe update failed", self.url
                    )
        if now - data_ts <= self.fresh_s:
            # FRESH data restores full poll cadence; a zombie's frozen
            # timestamps do not (the fetch succeeded, the data is dead).
            self.poll_backoff.reset()
        self._count(mode, "ok")

    def restore(self, snap: dict, fetched_at: float) -> None:
        """Seed the last-good snapshot from the warm-restart spool —
        original data timestamp preserved, so ordinary age
        classification stale-flags it honestly. Never overwrites data a
        live fetch already landed."""
        with self._lock:
            if self._snap is not None:
                return
            self._snap = snap
            self._fetched_at = fetched_at
            self.restored = True
            self._content_cmp = {
                k: v for k, v in snap.items() if k != "last_poll_ts"
            }
            self.content_seq += 1
            # Under the lock for the same store-ordering guarantee as
            # store_snapshot (a live fetch racing the restore must not
            # be overwritten by the spooled snapshot in the stripe).
            if self._on_update is not None:
                try:
                    self._on_update(
                        self.target, snap, fetched_at, self.content_seq
                    )
                except Exception:
                    log.exception(
                        "%s: ingest stripe restore failed", self.url
                    )

    def current(self) -> tuple[dict | None, float, str]:
        """(last-good snapshot, fetched-at ts, last error) — atomically."""
        with self._lock:
            return self._snap, self._fetched_at, self._last_error

    def current_entry(self) -> tuple[dict | None, float, str, int]:
        """current() plus the content sequence — one lock acquisition
        per feed per collect cycle (the incremental rollup's read)."""
        with self._lock:
            return (
                self._snap, self._fetched_at, self._last_error,
                self.content_seq,
            )

    def watch_state_now(self) -> str:
        with self._lock:
            return self.watch_state

    def age(self, now: float | None = None) -> float:
        with self._lock:
            fetched_at = self._fetched_at
        if fetched_at == 0.0:
            return float("inf")
        return max(0.0, (now if now is not None else self._clock()) - fetched_at)

    def _count(self, mode: str, result: str) -> None:
        if self._observe_fetch is not None:
            try:
                self._observe_fetch(mode, result)
            except Exception:
                # A metrics hiccup must never fail the ingest path.
                log.debug("fetch observer failed", exc_info=True)

    def _reject(self, mode: str, reason: str) -> None:
        """One rejected payload: rides the fetch counter as parse_error
        (the transport view) AND the ingest-rejects counter by reason
        (the corrupt-feed forensics view)."""
        self._count(mode, "parse_error")
        if self._observe_reject is not None:
            try:
                self._observe_reject(reason)
            except Exception:
                log.debug("reject observer failed", exc_info=True)

    def next_poll_delay(self, interval: float) -> float:
        """Seconds until this feed's next HTTP poll (adaptive cadence).

        Fresh feeds poll at full ``interval``; one that is failing,
        breaker-open, or serving only stale/dark data spaces out on the
        jittered backoff — each consultation escalates it, the first
        fresh page resets it. Darkness is judged by DATA age, so
        zombie exporters back off exactly like closed ports (and a
        never-seen target escalates from its very first miss)."""
        if self.age() <= self.fresh_s:
            return interval
        return max(interval, self.poll_backoff.next_delay())

    def _note_error(self, message: str) -> None:
        with self._lock:
            self._last_error = message[:200]

    # -- HTTP polling fallback ---------------------------------------------

    def _fetch_page(self) -> tuple[bytes, int | None, int | None]:
        """GET /metrics over a persistent per-feed connection; returns
        (body, delta seq, delta epoch) — the sequence metadata from the
        response's X-Tpumon-Delta-Seq header when the upstream speaks
        the delta protocol, else (body, None, None).

        Keep-alive matters at fleet scale: a fresh TCP connect per poll
        per node is O(fleet) connection churn per second on the shard
        AND a new handler thread per poll on every exporter. The
        connection is rebuilt on any error; ``poll`` is serialized per
        feed (``_inflight``), so one connection needs no locking.

        The Accept header asks for the delta encoding first (with the
        held base named in X-Tpumon-Delta-Base — the conditional-GET
        form of the protocol: an idle node answers with a heartbeat
        patch of a few dozen bytes), then the compact snapshot (one dict
        decode instead of a 0.37 ms text parse per page); an old
        exporter ignores Accept and serves text — ``store_page`` tells
        the three apart by the payload's magic prefix, so the fallback
        needs no version handshake."""
        from tpumon.exporter.encodings import (
            DELTA_BASE_HEADER,
            DELTA_CONTENT_TYPE,
            DELTA_SEQ_HEADER,
            SNAPSHOT_CONTENT_TYPE,
        )

        host = self.url.split("//", 1)[1]
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                host, timeout=self.timeout
            )
        headers = {
            "Accept": f"{SNAPSHOT_CONTENT_TYPE}, text/plain;q=0.5"
        }
        if self.delta:
            # ;sub=1 advertises sub-segment (per-chip) delta capability
            # — a media-type parameter old servers' negotiate() ignores,
            # so the ask is backward-inert (PR 13 follow-up).
            headers["Accept"] = (
                f"{DELTA_CONTENT_TYPE};sub=1, "
                f"{SNAPSHOT_CONTENT_TYPE};q=0.9, "
                "text/plain;q=0.5"
            )
            with self._lock:
                seq, epoch = self._delta_seq, self._delta_epoch
            if seq is not None and epoch is not None:
                headers[DELTA_BASE_HEADER] = f"{epoch}:{seq}"
        try:
            self._conn.request("GET", "/metrics", headers=headers)
            resp = self._conn.getresponse()
            # Bounded read: one byte past the cap proves oversize
            # without buffering whatever a hostile feed would stream.
            body = resp.read(self.max_snapshot_bytes + 1)
            if resp.status != 200:
                raise http.client.HTTPException(f"status {resp.status}")
            if len(body) > self.max_snapshot_bytes:
                # Tail left unread on purpose: drop the connection (its
                # framing is now unusable) and let store_page count the
                # reject — the caller still records a completed fetch,
                # which is true: the TRANSPORT worked, the payload is
                # what's hostile.
                try:
                    self._conn.close()
                finally:
                    self._conn = None
            seq = epoch = None
            raw = resp.getheader(DELTA_SEQ_HEADER)
            if raw:
                epoch_s, _, seq_s = raw.partition(":")
                try:
                    epoch, seq = int(epoch_s), int(seq_s)
                except ValueError:
                    seq = epoch = None  # garbage header: treat as absent
            return body, seq, epoch
        except BaseException:
            # Whatever happened, this connection's framing is suspect.
            try:
                self._conn.close()
            finally:
                self._conn = None
            raise

    def poll(self) -> None:  # thread: fleet-fetch — submitted as `feed.poll`, untyped at the spawn site
        """One bounded HTTP /metrics fetch (runs on the fetch executor).
        Breaker-gated: while open, the fetch is refused locally."""
        with self._lock:
            if self._inflight:
                return
            self._inflight = True
        try:
            if not self.breaker.allow():
                self._count("poll", "breaker_open")
                return
            try:
                body, seq, epoch = self._fetch_page()
            except FETCH_ERRORS as exc:
                self.breaker.record(False)
                self._note_error(str(exc))
                self._count("poll", "error")
                log.debug("%s: poll failed: %s", self.url, exc)
                return
            self.breaker.record(True)
            self.store_page(body, "poll", delta_seq=seq, delta_epoch=epoch)
        finally:
            with self._lock:
                self._inflight = False

    # -- gRPC Watch stream --------------------------------------------------

    def start_watch(self) -> None:
        """Start the Watch fan-in thread when the target has a gRPC
        address and grpcio is importable; otherwise the feed stays on
        HTTP polling (watch_state == "off")."""
        if self.grpc_addr is None or self._watch_thread is not None:
            return
        try:
            import grpc  # noqa: F401
        except ImportError:
            with self._lock:
                self.watch_state = "off"
            return
        self._watch_thread = threading.Thread(
            target=self._watch_loop,
            name=f"tpumon-fleet-watch-{self.grpc_addr}",
            daemon=True,
        )
        self._watch_thread.start()

    def _watch_loop(self) -> None:
        import grpc

        from tpumon.exporter.encodings import snapshot_request
        from tpumon.exporter.grpc_service import (
            METHOD_WATCH,
            decode_page_response_meta,
        )

        # Ask every push to be a delta frame (the exporter streams the
        # full snapshot first, then changed-segment patches — fan-in
        # bytes proportional to change rate), falling back to plain
        # snapshot frames when delta fan-in is disabled. A delta-aware
        # exporter with delta DISABLED degrades the ask to snapshot
        # frames server-side; a genuinely old exporter streams text
        # pages — observed below, the ask downgrades to "snapshot"
        # (which PR 8-era exporters speak) and the stream redials, so a
        # version-skewed fleet never sits on full text pages per push.
        watch_fmt = "delta" if self.delta else "snapshot"
        while not self._stop.is_set():
            # sub=True rides the delta ask only: PageRequest field 2 is
            # skipped by pre-PR 14 exporters (whole-segment deltas keep
            # flowing), honored by new ones (per-chip patches).
            request = snapshot_request(watch_fmt, sub=watch_fmt == "delta")
            # Receive cap mirrors the HTTP body cap: a hostile or
            # corrupt push stream errors out instead of ballooning RSS.
            channel = grpc.insecure_channel(
                self.grpc_addr,
                options=[
                    ("grpc.max_receive_message_length",
                     self.max_snapshot_bytes),
                ],
            )
            try:
                call = channel.unary_stream(
                    METHOD_WATCH,
                    request_serializer=None,
                    response_deserializer=None,
                )
                # Overall stream deadline: the stream ends (and redials)
                # after the window even against a half-dead peer.
                stream = call(request, timeout=WATCH_STREAM_DEADLINE_S)
                with self._lock:
                    self._watch_call = stream
                for raw in stream:
                    page, version, epoch = decode_page_response_meta(raw)
                    outcome = self.store_page(
                        page, "watch", delta_seq=version, delta_epoch=epoch,
                    )
                    if outcome == "gap":
                        # Sequence gap mid-stream: the stream's framing
                        # can no longer be trusted — redial; the fresh
                        # stream's first frame is a full resync.
                        try:
                            stream.cancel()
                        except Exception:
                            log.debug(
                                "gap-cancel failed", exc_info=True
                            )
                        break
                    if outcome == "text" and watch_fmt == "delta":
                        # Old exporter: it answered the delta ask with
                        # full text pages. Downgrade this feed's ask to
                        # the snapshot frame it does speak and redial.
                        watch_fmt = "snapshot"
                        log.info(
                            "%s: upstream does not speak the delta "
                            "protocol; downgrading watch to snapshot "
                            "frames", self.grpc_addr,
                        )
                        try:
                            stream.cancel()
                        except Exception:
                            log.debug(
                                "downgrade-cancel failed", exc_info=True
                            )
                        break
                    with self._lock:
                        self.watch_state = "streaming"
                    self.backoff.reset()
                    if self._stop.is_set():
                        break
            except grpc.RpcError as exc:
                code = getattr(exc, "code", lambda: None)()
                if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                    # Routine stream-window expiry: redial immediately.
                    self.backoff.reset()
                else:
                    self._note_error(f"watch: {code}")
                    self._count("watch", "error")
                    log.debug("%s: watch stream failed: %s", self.grpc_addr, code)
            except Exception:
                self._count("watch", "error")
                log.exception("%s: watch loop error", self.grpc_addr)
            finally:
                with self._lock:
                    self._watch_call = None
                    if not self._stop.is_set():
                        self.watch_state = "down"
                channel.close()
            if self._stop.wait(self.backoff.next_delay()):
                break

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            call = self._watch_call
        if call is not None:
            try:
                call.cancel()
            except Exception:
                log.debug("watch cancel failed", exc_info=True)
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2.0)
        conn = self._conn
        if conn is not None:
            self._conn = None
            conn.close()


__all__ = [
    "FETCH_ERRORS",
    "NodeFeed",
    "node_snapshot_from_text",
    "parse_target",
    "WATCH_STREAM_DEADLINE_S",
]
