"""Deterministic target→shard assignment (rendezvous hashing).

Every aggregator shard runs this same pure function over the same
target list and keeps exactly the targets it wins — no coordinator, no
shared state, no ordering sensitivity. Rendezvous (highest-random-
weight) hashing gives the property that matters operationally: growing
the shard set from N to N+1 moves ONLY the targets the new shard wins
(~1/(N+1) of the fleet); every other target keeps its watcher, so a
scale-up does not reconnect the whole fleet's Watch streams at once.

Hashing is md5 over ``"<shard>:<target>"`` — stable across processes,
machines, and Python versions (``hash()`` is salted per process and
would assign differently on every restart).
"""

from __future__ import annotations

import hashlib


def _weight(shard: int, target: str) -> int:
    digest = hashlib.md5(f"{shard}:{target}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


def shard_of(target: str, shard_count: int) -> int:
    """The shard index that owns ``target`` among ``shard_count`` shards."""
    if shard_count <= 1:
        return 0
    return max(range(shard_count), key=lambda i: _weight(i, target))


def owned_targets(
    targets: list[str], shard_index: int, shard_count: int
) -> list[str]:
    """The subset of ``targets`` this shard owns, input order preserved."""
    if shard_count <= 1:
        return list(targets)
    return [t for t in targets if shard_of(t, shard_count) == shard_index]


def shard_of_among(target: str, alive: tuple[int, ...]) -> int:
    """The shard index that owns ``target`` among the ``alive`` subset
    of the configured shard set (failover reassignment)."""
    if len(alive) == 1:
        return alive[0]
    return max(alive, key=lambda i: _weight(i, target))


def owned_targets_among(
    targets: list[str],
    shard_index: int,
    alive: set[int] | frozenset[int],
    shard_count: int,
) -> list[str]:
    """The subset of ``targets`` this shard owns when only the ``alive``
    shards participate in the rendezvous — the failover form.

    HRW over a SUBSET keeps the minimal-movement property in the
    direction that matters here: removing a dead shard j moves ONLY j's
    targets (each to its next-highest-weight surviving shard) — every
    target whose winner is still alive keeps its owner, so a takeover
    never re-deals the whole fleet's feeds. When the full set is alive
    this is exactly :func:`owned_targets`.
    """
    live = tuple(sorted(set(alive) & set(range(shard_count))))
    if not live:
        # A shard that believes everyone (itself included) is dead is
        # confused, not empty: own your static assignment.
        return owned_targets(targets, shard_index, shard_count)
    if shard_index not in live:
        return []
    if len(live) == shard_count:
        return owned_targets(targets, shard_index, shard_count)
    return [t for t in targets if shard_of_among(t, live) == shard_index]


__all__ = ["owned_targets", "owned_targets_among", "shard_of", "shard_of_among"]
