"""Deterministic target→shard assignment (rendezvous hashing).

Every aggregator shard runs this same pure function over the same
target list and keeps exactly the targets it wins — no coordinator, no
shared state, no ordering sensitivity. Rendezvous (highest-random-
weight) hashing gives the property that matters operationally: growing
the shard set from N to N+1 moves ONLY the targets the new shard wins
(~1/(N+1) of the fleet); every other target keeps its watcher, so a
scale-up does not reconnect the whole fleet's Watch streams at once.

Hashing is md5 over ``"<shard>:<target>"`` — stable across processes,
machines, and Python versions (``hash()`` is salted per process and
would assign differently on every restart).
"""

from __future__ import annotations

import hashlib


def _weight(shard: int, target: str) -> int:
    digest = hashlib.md5(f"{shard}:{target}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


def shard_of(target: str, shard_count: int) -> int:
    """The shard index that owns ``target`` among ``shard_count`` shards."""
    if shard_count <= 1:
        return 0
    return max(range(shard_count), key=lambda i: _weight(i, target))


def owned_targets(
    targets: list[str], shard_index: int, shard_count: int
) -> list[str]:
    """The subset of ``targets`` this shard owns, input order preserved."""
    if shard_count <= 1:
        return list(targets)
    return [t for t in targets if shard_of(t, shard_count) == shard_index]


__all__ = ["owned_targets", "shard_of"]
