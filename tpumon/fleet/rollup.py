"""Hierarchical node→slice→pool→fleet merge and the ``tpu_fleet_*``
families built from it.

The hierarchy comes from the identity labels every exporter already
stamps: a node's **slice** is its ``slice`` label, its **pool** is its
``accelerator`` type label (one pool per accelerator generation —
v5p-64 pods, v5e-16 pods — the granularity a capacity dashboard ranks).

Exposition is recording-rule style: ONE family per signal with a
``scope`` label (``slice`` / ``pool`` / ``fleet``), so a Grafana panel
over the whole org is a single O(#slices) selector —
``tpu_fleet_duty_cycle_percent{scope="fleet",stat="mean"}`` — and
per-node series are never re-exported through the tier. Staleness is a
first-class output, not a side channel: a slice whose rollup includes
stale node data carries ``tpu_fleet_stale_rollup == 1``, and host
counts split by state (``up`` / ``stale`` / ``dark``) so a dark node is
visible in the same family that counts live ones.

Pure functions over parsed snapshots — no I/O, no clocks — so the
rollup math is unit-testable sample-for-sample (tests/test_fleet.py).
"""

from __future__ import annotations

import logging
import os

from prometheus_client.core import GaugeMetricFamily

from tpumon._native import load_extension

log = logging.getLogger(__name__)

#: Node ingest states (tpumon/fleet/ingest.py feeds, classified by age).
UP = "up"
STALE = "stale"
DARK = "dark"

#: Identity fallbacks for a node that went dark before ever delivering
#: a snapshot (no labels to bucket it by).
UNKNOWN_POOL = "unknown"
UNKNOWN_SLICE = "?"


def classify(age: float, stale_s: float, evict_s: float) -> str:
    """Feed age → ingest state. ``stale`` snapshots still roll up
    (flagged); ``dark`` ones are evicted from the math but counted."""
    if age <= stale_s:
        return UP
    if age <= evict_s:
        return STALE
    return DARK


def visibility_of(hosts: dict) -> float:
    """Fraction of a scope's known hosts contributing FRESH data — the
    partition-honesty number. Stale hosts still roll up (flagged via
    tpu_fleet_stale_rollup) but no longer count as visible: during a
    partition the totals hold flagged-steady while this ratio drops,
    which is exactly the "flagged-partial, never confidently-wrong"
    contract. A scope with no known hosts reads 1.0 (nothing is
    missing from nothing)."""
    total = sum(hosts.values())
    if total <= 0:
        return 1.0
    return hosts.get(UP, 0) / total


class _Agg:
    """One accumulation bucket (a slice, a pool, or the fleet)."""

    def __init__(self) -> None:
        self.hosts = {UP: 0, STALE: 0, DARK: 0}
        self.chips = 0
        self.duty_sum = 0.0
        self.duty_n = 0
        self.duty_min: float | None = None
        self.duty_max: float | None = None
        self.hbm_used = 0.0
        self.hbm_total = 0.0
        self.ici_healthy = 0
        self.ici_links = 0
        self.mfu_sum = 0.0
        self.mfu_n = 0
        self.step_rate_sum = 0.0
        self.step_rate_n = 0
        #: Energy rollup (tpumon/energy): summed node watts + the
        #: worst-of provenance (one modeled host makes the scope
        #: modeled), and the tokens/joule mean with its merge weight.
        self.energy_watts = 0.0
        self.energy_n = 0
        self.energy_modeled = False
        self.tpj_sum = 0.0
        self.tpj_n = 0
        self.lifecycle_transitions = 0
        self.degraded_hosts = 0
        #: Active straggler hosts by attributed cause (tpumon/hostcorr).
        self.stragglers: dict[str, int] = {}
        self.straggler_skew_max: float | None = None
        #: Worst step-skew ratio (the straggler-HOST magnitude) across
        #: the scope's hosts — the ranking signal for episodes duty
        #: skew cannot see.
        self.straggler_step_skew_max: float | None = None

    def add_node(self, snap: dict, state: str) -> None:
        self.hosts[state] += 1
        if state == DARK:
            return  # counted, never merged — dark data is no data
        self.chips += len(snap.get("chips", {}))
        for row in snap.get("chips", {}).values():
            duty = row.get("duty_pct")
            if duty is not None:
                self.duty_sum += duty
                self.duty_n += 1
                if self.duty_min is None or duty < self.duty_min:
                    self.duty_min = duty
                if self.duty_max is None or duty > self.duty_max:
                    self.duty_max = duty
            used, total = row.get("hbm_used"), row.get("hbm_total")
            if used is not None and total is not None:
                self.hbm_used += used
                self.hbm_total += total
        ici = snap.get("ici") or {}
        self.ici_healthy += ici.get("healthy", 0)
        self.ici_links += ici.get("total", 0)
        mfu = snap.get("mfu")
        if mfu is not None:
            self.mfu_sum += mfu
            self.mfu_n += 1
        step_rate = snap.get("step_rate")
        if step_rate is not None:
            # Mean, not sum: hosts of one data-parallel job each report
            # the JOB's steps/s — summing would overcount by the host
            # count. "n" carried for the cross-shard weighted merge.
            self.step_rate_sum += step_rate
            self.step_rate_n += 1
        energy = snap.get("energy")
        if energy and energy.get("watts"):
            # Truthiness gate on watts: a tokens/J-only page initializes
            # the dict at 0.0 W, and a real node can never draw 0 (the
            # model has an idle floor) — so 0 means "no power series".
            self.energy_watts += energy["watts"]
            self.energy_n += 1
            if energy.get("source") != "measured":
                self.energy_modeled = True
        if energy and energy.get("tokens_per_joule") is not None:
            self.tpj_sum += energy["tokens_per_joule"]
            self.tpj_n += 1
            if energy.get("source") != "measured":
                self.energy_modeled = True
        if snap.get("lifecycle_transition"):
            self.lifecycle_transitions += 1
        degraded = snap.get("degraded")
        if degraded and degraded.get("active"):
            self.degraded_hosts += 1
        straggler = snap.get("straggler")
        if straggler:
            skew = straggler.get("skew_pct")
            if skew is not None and (
                self.straggler_skew_max is None
                or skew > self.straggler_skew_max
            ):
                self.straggler_skew_max = skew
            step_skew = straggler.get("step_skew_ratio")
            if step_skew is not None and (
                self.straggler_step_skew_max is None
                or step_skew > self.straggler_step_skew_max
            ):
                self.straggler_step_skew_max = step_skew
            if straggler.get("active"):
                cause = straggler.get("cause", "unknown")
                self.stragglers[cause] = self.stragglers.get(cause, 0) + 1

    def to_dict(self) -> dict:
        doc: dict = {
            "hosts": dict(self.hosts),
            "chips": self.chips,
            "degraded_hosts": self.degraded_hosts,
            "stale": self.hosts[STALE] > 0,
            "visibility": visibility_of(self.hosts),
        }
        if self.duty_n:
            # "n" (contributing chips) makes the mean mergeable across
            # shards (merge_buckets) — weights, not a re-average.
            doc["duty"] = {
                "mean": self.duty_sum / self.duty_n,
                "min": self.duty_min,
                "max": self.duty_max,
                "n": self.duty_n,
            }
        if self.hbm_total > 0:
            doc["hbm_used"] = self.hbm_used
            doc["hbm_total"] = self.hbm_total
            doc["hbm_headroom_ratio"] = 1.0 - self.hbm_used / self.hbm_total
        if self.ici_links:
            doc["ici"] = {
                "healthy": self.ici_healthy,
                "links": self.ici_links,
                "score": self.ici_healthy / self.ici_links,
            }
        if self.mfu_n:
            doc["mfu"] = self.mfu_sum / self.mfu_n
            doc["mfu_n"] = self.mfu_n
        if self.step_rate_n:
            doc["step_rate"] = self.step_rate_sum / self.step_rate_n
            doc["step_rate_n"] = self.step_rate_n
        if self.energy_n or self.tpj_n:
            doc["energy_source"] = (
                "modeled" if self.energy_modeled else "measured"
            )
        if self.energy_n:
            doc["energy_watts"] = self.energy_watts
            doc["energy_n"] = self.energy_n
        if self.tpj_n:
            doc["tokens_per_joule"] = self.tpj_sum / self.tpj_n
            doc["tokens_per_joule_n"] = self.tpj_n
        if self.lifecycle_transitions:
            doc["lifecycle_transitions"] = self.lifecycle_transitions
        if self.stragglers:
            doc["stragglers"] = dict(self.stragglers)
        if self.straggler_skew_max is not None:
            doc["straggler_skew_max_pct"] = self.straggler_skew_max
        if self.straggler_step_skew_max is not None:
            doc["straggler_step_skew_max_ratio"] = (
                self.straggler_step_skew_max
            )
        return doc


def native_kernel():
    """The native bucket-math kernel (tpumon/_native/_rollup.c), or
    None when the pure-Python fold is in use — the bench and tests
    record which path produced their numbers."""
    return load_extension("_rollup")


def _agg_from_state(state: tuple) -> _Agg:
    """Rehydrate an :class:`_Agg` from the native kernel's state tuple
    (field order is the kernel's output contract)."""
    agg = _Agg()
    (
        agg.hosts[UP], agg.hosts[STALE], agg.hosts[DARK], agg.chips,
        agg.duty_sum, agg.duty_n, agg.duty_min, agg.duty_max,
        agg.hbm_used, agg.hbm_total,
        agg.ici_healthy, agg.ici_links,
        agg.mfu_sum, agg.mfu_n,
        agg.step_rate_sum, agg.step_rate_n,
        agg.energy_watts, agg.energy_n, agg.energy_modeled,
        agg.tpj_sum, agg.tpj_n,
        agg.lifecycle_transitions, agg.degraded_hosts,
        agg.stragglers, agg.straggler_skew_max,
        agg.straggler_step_skew_max,
    ) = state
    return agg


def aggregate_members(members: list[tuple[dict, str]]) -> _Agg:
    """Fold ``(snap, state)`` members into one bucket accumulator —
    through the native kernel when it is available, else the pinned
    pure-Python :meth:`_Agg.add_node` loop. The two paths are
    value-identical by contract (tests/test_fleet_stripes.py pins it on
    randomized buckets); a shape the kernel refuses falls back to the
    Python loop, which is the arbiter of semantics either way."""
    ext = load_extension("_rollup")
    if ext is not None:
        try:
            return _agg_from_state(ext.aggregate(members))
        except Exception:
            # A shape outside the kernel's model: the Python loop
            # either handles it or raises the genuine input error.
            log.debug(
                "native rollup kernel fell back to python", exc_info=True
            )
    agg = _Agg()
    for snap, state in members:
        agg.add_node(snap, state)
    return agg


def members_doc(members: list[tuple[dict, str]]) -> dict:
    """One bucket's :meth:`_Agg.to_dict` doc from its ``(snap, state)``
    members — straight to the doc in C when the kernel is available
    (fold + doc construction without touching the interpreter), else
    the pinned :func:`aggregate_members` + ``to_dict`` path. The hot
    call of :class:`IncrementalRollup`."""
    ext = load_extension("_rollup")
    if ext is not None:
        try:
            return ext.aggregate_doc(members)
        except Exception:
            log.debug(
                "native doc fold fell back to python", exc_info=True
            )
    agg = _Agg()
    for snap, state in members:
        agg.add_node(snap, state)
    return agg.to_dict()


def rollup(nodes: list[dict]) -> dict:
    """Merge node entries into the slice/pool/fleet hierarchy.

    ``nodes``: ``[{"snap": <smi snapshot>|None, "state": up|stale|dark,
    ...}, ...]`` (ingest feeds, pre-classified). Returns::

        {"slices": {(pool, slice): {...}},   # _Agg.to_dict shapes
         "pools":  {pool: {...}},
         "fleet":  {...,"slices": n, "pools": n}}
    """
    slices: dict[tuple[str, str], _Agg] = {}
    pools: dict[str, _Agg] = {}
    fleet = _Agg()
    for node in nodes:
        snap = node.get("snap") or {}
        ident = snap.get("identity") or {}
        pool = ident.get("accelerator") or UNKNOWN_POOL
        slc = ident.get("slice") or UNKNOWN_SLICE
        state = node["state"]
        slices.setdefault((pool, slc), _Agg()).add_node(snap, state)
        pools.setdefault(pool, _Agg()).add_node(snap, state)
        fleet.add_node(snap, state)
    fleet_doc = fleet.to_dict()
    fleet_doc["slices"] = len(slices)
    fleet_doc["pools"] = len(pools)
    return {
        "slices": {key: agg.to_dict() for key, agg in slices.items()},
        "pools": {pool: agg.to_dict() for pool, agg in pools.items()},
        "fleet": fleet_doc,
    }


def merge_buckets(buckets: list[dict]) -> dict:
    """Merge :meth:`_Agg.to_dict` shapes (pool/fleet folds every
    collect cycle, plus the cross-shard ``scope="global"`` row) —
    through the native kernel's ``merge`` when available, else the
    pinned pure-Python fold. Value-identical by contract; a shape the
    kernel refuses (exotic coercions) falls back to the Python fold,
    which is the arbiter either way."""
    ext = load_extension("_rollup")
    if ext is not None:
        try:
            state = ext.merge(buckets)
        except Exception:
            log.debug(
                "native merge kernel fell back to python", exc_info=True
            )
        else:
            out = _agg_from_state(state[:26])
            duty_missing, mfu_missing, any_stale = state[26:]
            doc = out.to_dict()
            doc["stale"] = doc["stale"] or any_stale
            if duty_missing:
                doc.pop("duty", None)
            if mfu_missing:
                doc.pop("mfu", None)
                doc.pop("mfu_n", None)
            return doc
    return merge_buckets_py(buckets)


def merge_buckets_py(buckets: list[dict]) -> dict:
    """Merge :meth:`_Agg.to_dict` shapes across shards (the cross-shard
    ``scope="global"`` row): host/chip/HBM/ICI/straggler totals are
    additive, duty/MFU means merge by their carried ``n`` weights,
    min/max and stale flags combine the obvious way, and visibility is
    recomputed from the merged host counts. Pure — peer summaries are
    plain JSON dicts by the time they reach this. THE pinned reference
    for the native kernel's ``merge`` (value-identical by contract).

    Accumulation happens in locals (assigned into the :class:`_Agg`
    once at the end): this merge runs per dirty pool per collect cycle
    over every slice doc in the pool, and instance-attribute traffic
    was a measured share of the full-rollup cost at 1024 nodes. The
    arithmetic — coercions, order, min/max object identity — is
    unchanged."""
    out = _Agg()
    duty_missing = mfu_missing = False
    hosts_up = hosts_stale = hosts_dark = 0
    chips = degraded_hosts = 0
    duty_sum = 0.0
    duty_n = 0
    duty_min = duty_max = None
    hbm_used = hbm_total = 0.0
    ici_healthy = ici_links = 0
    mfu_sum = 0.0
    mfu_n = 0
    step_rate_sum = 0.0
    step_rate_n = 0
    energy_watts = 0.0
    energy_n = 0
    energy_modeled = False
    tpj_sum = 0.0
    tpj_n = 0
    lifecycle_transitions = 0
    stragglers = out.stragglers
    skew_max = step_skew_max = None
    for bucket in buckets:
        if not bucket:
            continue
        get = bucket.get
        hosts = get("hosts", {})
        hosts_up += int(hosts.get(UP, 0))
        hosts_stale += int(hosts.get(STALE, 0))
        hosts_dark += int(hosts.get(DARK, 0))
        chips += int(get("chips", 0))
        degraded_hosts += int(get("degraded_hosts", 0))
        duty = get("duty")
        if duty and duty.get("n"):
            n = int(duty["n"])
            duty_sum += float(duty["mean"]) * n
            duty_n += n
            if duty.get("min") is not None:
                duty_min = (
                    duty["min"] if duty_min is None
                    else min(duty_min, duty["min"])
                )
            if duty.get("max") is not None:
                duty_max = (
                    duty["max"] if duty_max is None
                    else max(duty_max, duty["max"])
                )
        elif duty:
            # A pre-failover peer without the "n" weight: its mean
            # cannot merge honestly — drop duty from the global row
            # rather than guess a weight.
            duty_missing = True
        hbm_used += float(get("hbm_used", 0.0))
        hbm_total += float(get("hbm_total", 0.0))
        ici = get("ici")
        if ici:
            ici_healthy += int(ici.get("healthy", 0))
            ici_links += int(ici.get("links", 0))
        if get("mfu") is not None:
            n = int(get("mfu_n", 0))
            if n:
                mfu_sum += float(bucket["mfu"]) * n
                mfu_n += n
            else:
                mfu_missing = True
        if get("step_rate") is not None:
            n = int(get("step_rate_n", 0))
            if n:
                step_rate_sum += float(bucket["step_rate"]) * n
                step_rate_n += n
        if get("energy_watts") is not None:
            energy_watts += float(bucket["energy_watts"])
            energy_n += int(get("energy_n", 1))
        if get("tokens_per_joule") is not None:
            n = int(get("tokens_per_joule_n", 0))
            if n:
                tpj_sum += float(bucket["tokens_per_joule"]) * n
                tpj_n += n
        if get("energy_source") == "modeled":
            energy_modeled = True
        lifecycle_transitions += int(get("lifecycle_transitions", 0))
        for cause, count in get("stragglers", {}).items():
            stragglers[cause] = stragglers.get(cause, 0) + int(count)
        skew = get("straggler_skew_max_pct")
        if skew is not None and (skew_max is None or skew > skew_max):
            skew_max = skew
        step_skew = get("straggler_step_skew_max_ratio")
        if step_skew is not None and (
            step_skew_max is None or step_skew > step_skew_max
        ):
            step_skew_max = step_skew
    out.hosts[UP] = hosts_up
    out.hosts[STALE] = hosts_stale
    out.hosts[DARK] = hosts_dark
    out.chips = chips
    out.degraded_hosts = degraded_hosts
    out.duty_sum = duty_sum
    out.duty_n = duty_n
    out.duty_min = duty_min
    out.duty_max = duty_max
    out.hbm_used = hbm_used
    out.hbm_total = hbm_total
    out.ici_healthy = ici_healthy
    out.ici_links = ici_links
    out.mfu_sum = mfu_sum
    out.mfu_n = mfu_n
    out.step_rate_sum = step_rate_sum
    out.step_rate_n = step_rate_n
    out.energy_watts = energy_watts
    out.energy_n = energy_n
    out.energy_modeled = energy_modeled
    out.tpj_sum = tpj_sum
    out.tpj_n = tpj_n
    out.lifecycle_transitions = lifecycle_transitions
    out.straggler_skew_max = skew_max
    out.straggler_step_skew_max = step_skew_max
    doc = out.to_dict()
    doc["stale"] = doc["stale"] or any(
        b.get("stale") for b in buckets if b
    )
    if duty_missing:
        doc.pop("duty", None)
    if mfu_missing:
        doc.pop("mfu", None)
        doc.pop("mfu_n", None)
    return doc


class IncrementalRollup:
    """Churn-proportional rollup: recompute only the buckets a change
    touches (ROADMAP item 3 — at 10k feeds × 1 Hz, re-rolling the world
    each cycle IS the fan-in wall once the wire is deltas).

    Structure: a node belongs to exactly one (pool, slice) bucket.
    Slice buckets re-aggregate from their member nodes only when a
    member's content/state/membership changed; pool docs merge their
    slices' docs (``merge_buckets`` — the exact math the cross-shard
    global row already uses, so mergeability is a proven property, not
    a new one); the fleet doc merges the pool docs. A cycle with zero
    dirty nodes reuses every cached doc wholesale.

    Dirtiness comes from the per-feed ``content_seq`` (bumped only when
    rollup-relevant content changed — an idle node's heartbeat never
    dirties) plus the age-derived ingest state, which CAN change with no
    delta arriving (fresh→stale→dark), so the per-cycle cost floor is
    one integer/str compare per feed — not one re-aggregation.

    Single-threaded by contract (the collect loop); the docs it returns
    are shared read-only with serving threads and are REPLACED on
    recompute, never mutated in place.
    """

    def __init__(self) -> None:
        #: target -> (content_seq, state) — the change fingerprint.
        self._node_key: dict[str, tuple[int, str]] = {}
        #: target -> (pool, slice) bucket membership.
        self._node_bucket: dict[str, tuple[str, str]] = {}
        #: bucket -> {target: (snap, state)} current members.
        self._members: dict[tuple[str, str], dict[str, tuple]] = {}
        #: bucket -> cached _Agg.to_dict() doc.
        self._slice_docs: dict[tuple[str, str], dict] = {}
        #: pool -> cached merged doc.
        self._pool_docs: dict[str, dict] = {}
        self._fleet_doc: dict = _Agg().to_dict()
        self._fleet_doc["slices"] = 0
        self._fleet_doc["pools"] = 0
        #: Last update's churn accounting (telemetry).
        self.last_dirty_nodes = 0
        self.last_dirty_buckets = 0

    def update(self, entries: list[tuple[str, dict | None, str, int]]) -> dict:
        """One cycle: ``entries`` is ``[(target, snap|None, state,
        content_seq), ...]`` for every feed this shard currently owns.
        Returns the same doc shape as :func:`rollup`."""
        dirty: set[tuple[str, str]] = set()
        dirty_nodes = 0
        # Local bindings: this loop runs once per feed per cycle — at
        # 10k feeds the attribute lookups alone were a measurable share
        # of the idle-path floor.
        node_key = self._node_key
        node_bucket = self._node_bucket
        members_map = self._members
        dirty_add = dirty.add
        seen = {entry[0] for entry in entries}
        for target, snap, state, content_seq in entries:
            key = (content_seq, state)
            if node_key.get(target) == key:
                continue
            dirty_nodes += 1
            node_key[target] = key
            snap = snap or {}
            ident = snap.get("identity") or {}
            bucket = (
                ident.get("accelerator") or UNKNOWN_POOL,
                ident.get("slice") or UNKNOWN_SLICE,
            )
            prev_bucket = node_bucket.get(target)
            if prev_bucket is not None and prev_bucket != bucket:
                members = members_map.get(prev_bucket)
                if members is not None:
                    members.pop(target, None)
                dirty_add(prev_bucket)
            node_bucket[target] = bucket
            members = members_map.get(bucket)
            if members is None:
                members = members_map[bucket] = {}
            members[target] = (snap, state)
            dirty_add(bucket)
        # Feeds that left this shard (membership change / takeover
        # hand-back) leave their buckets too — adopted-elsewhere nodes
        # must never stay counted here, or a takeover double-counts.
        # The main loop only ever ADDS to node_key, so after it
        # node_key ⊇ seen: a length mismatch is exactly "departures
        # exist", and steady-state cycles skip the O(fleet) scan.
        if len(node_key) > len(seen):
            for target in [t for t in node_key if t not in seen]:
                dirty_nodes += 1
                del node_key[target]
                bucket = node_bucket.pop(target, None)
                if bucket is not None:
                    members = members_map.get(bucket)
                    if members is not None:
                        members.pop(target, None)
                    dirty_add(bucket)
        dirty_pools: set[str] = set()
        for bucket in dirty:
            members = self._members.get(bucket)
            if not members:
                self._members.pop(bucket, None)
                self._slice_docs.pop(bucket, None)
            else:
                # The bucket fold is the rollup's hot loop — native
                # kernel when available, pinned Python loop otherwise.
                # Members fold in SORTED target order: float sums are
                # order-sensitive, and canonical order makes the doc a
                # pure function of the member set — byte-identical
                # across arrival histories, restarts, and shards
                # (tests/test_fleet_stripes.py pins it under a
                # concurrent-writer hammer).
                self._slice_docs[bucket] = members_doc(
                    [members[t] for t in sorted(members)]
                )
            dirty_pools.add(bucket[0])
        if dirty:
            for pool in dirty_pools:
                # Sorted slice order for the same canonical-order
                # reason as the member fold above.
                docs = [
                    doc for (p, _s), doc in sorted(self._slice_docs.items())
                    if p == pool
                ]
                if docs:
                    self._pool_docs[pool] = merge_buckets(docs)
                else:
                    self._pool_docs.pop(pool, None)
            fleet = merge_buckets(
                [self._pool_docs[p] for p in sorted(self._pool_docs)]
            )
            fleet["slices"] = len(self._slice_docs)
            fleet["pools"] = len(self._pool_docs)
            self._fleet_doc = fleet
        self.last_dirty_nodes = dirty_nodes
        self.last_dirty_buckets = len(dirty)
        # Fresh top-level dict per cycle (callers attach "global" etc.);
        # the bucket docs inside are shared, read-only.
        return {
            "slices": dict(self._slice_docs),
            "pools": dict(self._pool_docs),
            "fleet": self._fleet_doc,
        }


#: (family, help, extra labels beyond scope/pool/slice) — the builder
#: below and the FLEET_FAMILIES registry (tpumon/families.py) must agree;
#: the family-drift rule and tests/test_fleet.py hold them together.
_SCOPED = ("scope", "pool", "slice")


def _rows(doc: dict):
    """Every (labels, bucket) pair: slice rows, pool rows, the fleet
    row, and — when cross-shard peer data was merged in — the global
    row."""
    for (pool, slc), bucket in sorted(doc["slices"].items()):
        yield ("slice", pool, slc), bucket
    for pool, bucket in sorted(doc["pools"].items()):
        yield ("pool", pool, ""), bucket
    yield ("fleet", "", ""), doc["fleet"]
    if "global" in doc:
        yield ("global", "", ""), doc["global"]


def fleet_families(doc: dict) -> list:
    """The pre-aggregated exposition: one GaugeMetricFamily per signal,
    scope-labeled rows for every slice, pool, and the fleet."""
    hosts = GaugeMetricFamily(
        "tpu_fleet_hosts",
        "Exporter hosts known to this aggregator shard by ingest state "
        "(up = fresh, stale = serving last-good flagged data, dark = "
        "evicted from rollups).",
        labels=_SCOPED + ("state",),
    )
    chips = GaugeMetricFamily(
        "tpu_fleet_chips",
        "Accelerator chips contributing to this rollup (dark hosts "
        "excluded).",
        labels=_SCOPED,
    )
    duty = GaugeMetricFamily(
        "tpu_fleet_duty_cycle_percent",
        "Chip duty-cycle rollup across the scope (stat ∈ mean/min/max "
        "over contributing chips).",
        labels=_SCOPED + ("stat",),
    )
    hbm_used = GaugeMetricFamily(
        "tpu_fleet_hbm_used_bytes",
        "Summed HBM bytes in use across the scope.",
        labels=_SCOPED,
    )
    hbm_total = GaugeMetricFamily(
        "tpu_fleet_hbm_total_bytes",
        "Summed HBM capacity bytes across the scope.",
        labels=_SCOPED,
    )
    headroom = GaugeMetricFamily(
        "tpu_fleet_hbm_headroom_ratio",
        "Free fraction of the scope's HBM (1 - used/total).",
        labels=_SCOPED,
    )
    ici_links = GaugeMetricFamily(
        "tpu_fleet_ici_links",
        "ICI interconnect links across the scope by health "
        "(state ∈ healthy/degraded).",
        labels=_SCOPED + ("state",),
    )
    ici_score = GaugeMetricFamily(
        "tpu_fleet_ici_health_score",
        "ICI health scored per scope: healthy-link fraction, 1.0 = "
        "every link clean (absent when the scope reports no links).",
        labels=_SCOPED,
    )
    mfu = GaugeMetricFamily(
        "tpu_fleet_mfu_ratio",
        "Mean model-FLOPs utilization over hosts reporting it (absent "
        "when none do).",
        labels=_SCOPED,
    )
    step_rate = GaugeMetricFamily(
        "tpu_fleet_step_rate",
        "Mean workload optimizer steps/s over the scope's hosts "
        "reporting tpu_lifecycle_step_rate (absent when none do) — "
        "the per-slice training-progress rollup.",
        labels=_SCOPED,
    )
    energy_watts = GaugeMetricFamily(
        "tpu_fleet_energy_watts",
        "Summed node power across the scope (tpu_energy_power_watts "
        "rollup); source=measured only when every contributing host's "
        "power was device-reported.",
        labels=_SCOPED + ("source",),
    )
    tokens_per_joule = GaugeMetricFamily(
        "tpu_fleet_tokens_per_joule",
        "Mean tokens/joule over the scope's hosts reporting "
        "tpu_step_tokens_per_joule (absent when none do); one modeled "
        "host makes the scope read source=modeled.",
        labels=_SCOPED + ("source",),
    )
    lifecycle = GaugeMetricFamily(
        "tpu_fleet_lifecycle_transitions",
        "Hosts in the scope currently inside a workload-lifecycle "
        "transition window (tpu_lifecycle_state == 1: preemption / "
        "resize / restore in progress).",
        labels=_SCOPED,
    )
    degraded = GaugeMetricFamily(
        "tpu_fleet_degraded_hosts",
        "Hosts in the scope whose exporter reports degraded serving "
        "(tpumon_degraded — stale-but-served families or open breakers).",
        labels=_SCOPED,
    )
    stragglers = GaugeMetricFamily(
        "tpu_fleet_stragglers",
        "Hosts in the scope with an active straggler verdict "
        "(tpu_straggler_verdict, tpumon/hostcorr), by attributed cause.",
        labels=_SCOPED + ("cause",),
    )
    straggler_skew = GaugeMetricFamily(
        "tpu_fleet_straggler_skew_pct",
        "Worst straggler skew across the scope's hosts (max per-host "
        "worst-chip vs median duty skew; absent when none report it).",
        labels=_SCOPED,
    )
    straggler_step_skew = GaugeMetricFamily(
        "tpu_fleet_straggler_step_skew_ratio",
        "Worst step-skew ratio across the scope's hosts "
        "(tpu_straggler_step_skew_ratio max: the lagging-HOST "
        "magnitude duty skew cannot see; absent when none report it).",
        labels=_SCOPED,
    )
    stale_flag = GaugeMetricFamily(
        "tpu_fleet_stale_rollup",
        "1 when this scope's rollup includes stale (last-good) node "
        "data — stale-flagged beats silently absent.",
        labels=_SCOPED,
    )
    visibility = GaugeMetricFamily(
        "tpu_fleet_visibility_ratio",
        "Fraction of the scope's known hosts contributing FRESH data "
        "to this rollup — below 1.0 the rollup is PARTIAL (stale "
        "last-good inclusions, a partition, dead feeds, or a takeover "
        "in progress), never silently renormalized.",
        labels=_SCOPED,
    )

    # Mutation canary (docs/INVARIANTS.md, CI chaos-search job): with
    # TPUMON_CHAOS_MUTATE=missing_host_unflagged set, the render lies —
    # stale flag forced 0, visibility forced 1.0 — deliberately
    # re-introducing the missing-host-unflagged bug the invariant
    # checker exists to catch. CI fails unless the chaos search catches
    # and minimizes it; the flag is never set in production manifests.
    mutate_unflagged = "missing_host_unflagged" in os.environ.get(
        "TPUMON_CHAOS_MUTATE", ""
    )

    for labels, bucket in _rows(doc):
        for state, n in sorted(bucket["hosts"].items()):
            hosts.add_metric(labels + (state,), float(n))
        chips.add_metric(labels, float(bucket["chips"]))
        if "duty" in bucket:
            for stat in ("mean", "min", "max"):
                duty.add_metric(labels + (stat,), float(bucket["duty"][stat]))
        if "hbm_total" in bucket:
            hbm_used.add_metric(labels, bucket["hbm_used"])
            hbm_total.add_metric(labels, bucket["hbm_total"])
            headroom.add_metric(labels, bucket["hbm_headroom_ratio"])
        if "ici" in bucket:
            ici = bucket["ici"]
            ici_links.add_metric(labels + ("healthy",), float(ici["healthy"]))
            ici_links.add_metric(
                labels + ("degraded",), float(ici["links"] - ici["healthy"])
            )
            ici_score.add_metric(labels, ici["score"])
        if "mfu" in bucket:
            mfu.add_metric(labels, bucket["mfu"])
        if "step_rate" in bucket:
            step_rate.add_metric(labels, bucket["step_rate"])
        if "energy_watts" in bucket:
            energy_watts.add_metric(
                labels + (bucket.get("energy_source", "modeled"),),
                bucket["energy_watts"],
            )
        if "tokens_per_joule" in bucket:
            tokens_per_joule.add_metric(
                labels + (bucket.get("energy_source", "modeled"),),
                bucket["tokens_per_joule"],
            )
        if "lifecycle_transitions" in bucket:
            lifecycle.add_metric(
                labels, float(bucket["lifecycle_transitions"])
            )
        for cause, n in sorted(bucket.get("stragglers", {}).items()):
            stragglers.add_metric(labels + (cause,), float(n))
        if "straggler_skew_max_pct" in bucket:
            straggler_skew.add_metric(
                labels, bucket["straggler_skew_max_pct"]
            )
        if "straggler_step_skew_max_ratio" in bucket:
            straggler_step_skew.add_metric(
                labels, bucket["straggler_step_skew_max_ratio"]
            )
        degraded.add_metric(labels, float(bucket["degraded_hosts"]))
        stale_flag.add_metric(
            labels,
            0.0 if mutate_unflagged else (1.0 if bucket["stale"] else 0.0),
        )
        visibility.add_metric(
            labels,
            1.0 if mutate_unflagged else float(
                bucket.get("visibility", visibility_of(bucket["hosts"]))
            ),
        )

    return [
        hosts, chips, duty, hbm_used, hbm_total, headroom,
        ici_links, ici_score, mfu, step_rate,
        energy_watts, tokens_per_joule, lifecycle,
        stragglers, straggler_skew, straggler_step_skew,
        degraded, stale_flag, visibility,
    ]


def jsonable(doc: dict) -> dict:
    """The /fleet API form of a rollup doc (tuple keys → flat rows)."""
    out = {
        "slices": [
            {"pool": pool, "slice": slc, **bucket}
            for (pool, slc), bucket in sorted(doc["slices"].items())
        ],
        "pools": [
            {"pool": pool, **bucket}
            for pool, bucket in sorted(doc["pools"].items())
        ],
        "fleet": doc["fleet"],
    }
    if "global" in doc:
        out["global"] = doc["global"]
    return out


__all__ = [
    "DARK",
    "IncrementalRollup",
    "STALE",
    "UP",
    "aggregate_members",
    "classify",
    "fleet_families",
    "jsonable",
    "members_doc",
    "merge_buckets",
    "merge_buckets_py",
    "native_kernel",
    "rollup",
    "visibility_of",
]
