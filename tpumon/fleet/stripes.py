"""Striped ingest: per-slice accumulator shards for the fan-in hot path.

Before this module, every collect cycle walked every :class:`NodeFeed`
and took its lock to read the current snapshot — an O(fleet) lock
acquisition per second on the collect thread, interleaved against the
same locks the Watch threads and the poll executor were taking to
store data. At 10k feeds that serialization IS the ingest ceiling: the
cost grows with fleet size even when nothing changed.

Here the flow is inverted. Fan-in writers PUSH each stored snapshot
into one of N accumulator shards ("stripes"), each with its own lock,
chosen by **rendezvous hash of the slice identity** — so concurrent
apply-delta calls for different slices touch disjoint shards (a slice's
writers share one, which is also where its rollup locality lives), and
the collect cycle's publish step drains per-stripe state under N brief
lock holds instead of one per feed. The per-cycle floor is one age
classification per feed (states age without any write arriving —
fresh→stale→dark must be observed); everything heavier stays
churn-proportional one layer down (rollup.IncrementalRollup).

Membership discipline mirrors the rollup's no-double-count contract:
``register`` is the only admission (a never-reported feed is counted
DARK, not invisible), ``remove`` evicts, and a late in-flight ``put``
for a target this shard no longer owns is dropped — a handed-back feed
can never linger in a stripe and double-count across shards. A write
racing a same-target slice move can briefly leave a stale copy in the
old stripe; the publish scan resolves every target against the route
table and lazily evicts copies whose route moved on, so duplicates are
never emitted. Moves themselves (an identity change, or the first
identity-bearing store after admission) serialize against publish
scans on the route lock: a mid-move target must never be absent from
EVERY stripe while a scan runs, or the cycle would publish it as
departed and the goodput ledger would drop its accounting window
outside even the ``unaccounted`` bucket. The common write path — same
stripe as last time — takes only its stripe's lock.

Byte-identity contract: ``entries()`` feeds the same
:class:`~tpumon.fleet.rollup.IncrementalRollup` the single-lock path
used, so the published rollup is byte-identical to the reference
``rollup()`` over the same entries (tests/test_fleet_stripes.py
hammers exactly this with concurrent writers).
"""

from __future__ import annotations

import threading

from tpumon.fleet.rollup import classify
from tpumon.fleet.shard import shard_of


def stripe_of(key: str, stripe_count: int) -> int:
    """Rendezvous winner among ``stripe_count`` stripes for ``key`` —
    the shard-assignment hash (tpumon/fleet/shard.py) one level down,
    delegated so there is exactly ONE rendezvous contract: stable
    across processes, and growing the stripe set moves only the keys
    the new stripe wins."""
    return shard_of(key, stripe_count)


class _Stripe:
    """One accumulator shard: its lock, the per-target ingest state it
    holds, a write counter (the contention-spread telemetry), and the
    dirty-set publish cache (ISSUE 16 satellite of the ISSUE 15 path).

    ``changes`` advances under the stripe lock on EVERY membership or
    content mutation (store, placeholder insert, pop — including the
    move path's pop from the old stripe), so the publish scan can prove
    a stripe clean by comparing one integer. The cache fields hold the
    last built output rows plus everything that could invalidate them
    without a mutation: the thresholds they were classified against and
    the earliest future instant any row's age class transitions
    (fresh→stale→dark happen with no write arriving)."""

    __slots__ = (
        "lock", "entries", "writes", "changes",
        "cached_rows", "cached_changes", "cached_params",
        "cached_next_transition", "cached_built_at",
    )

    def __init__(self) -> None:
        self.lock = threading.Lock()
        #: target -> (snap|None, data_ts, content_seq); all three come
        #: from the writer's own feed state, captured atomically there.
        self.entries: dict[str, tuple] = {}
        self.writes = 0
        #: Mutations since construction (stripe lock). The publish
        #: cache is valid only while this matches cached_changes.
        self.changes = 0
        self.cached_rows: list[tuple] | None = None
        self.cached_changes = -1
        self.cached_params: tuple = ()
        self.cached_next_transition = 0.0
        self.cached_built_at = 0.0


class StripedIngest:
    """The stripe set plus the target→stripe route table."""

    def __init__(self, stripes: int = 16) -> None:
        self.stripe_count = max(1, int(stripes))
        self._stripes = [_Stripe() for _ in range(self.stripe_count)]
        #: slice-identity -> stripe index. Cache of a pure function;
        #: racy writes recompute the same deterministic answer, so no
        #: lock (GIL-atomic dict ops).
        self._slice_stripe: dict[str, int] = {}
        self._route_lock = threading.Lock()
        #: target -> stripe index it currently lives in. Registration
        #: is admission: a put for an unrouted target is a late
        #: in-flight store for a feed this shard handed away — dropped.
        self._route: dict[str, int] = {}  # guarded-by: self._route_lock
        #: Stripes actually drained (cache miss) by the last publish —
        #: the tpu_fleet_rollup_dirty_stripes gauge (collect thread).
        self.last_dirty_stripes = 0

    # -- routing ------------------------------------------------------------

    def _stripe_for_slice(self, slice_key: str) -> int:
        idx = self._slice_stripe.get(slice_key)
        if idx is None:
            idx = stripe_of(slice_key, self.stripe_count)
            self._slice_stripe[slice_key] = idx
        return idx

    @staticmethod
    def _slice_key(snap: dict | None) -> str | None:
        ident = (snap or {}).get("identity") or {}
        pool = ident.get("accelerator")
        slc = ident.get("slice")
        if not pool and not slc:
            return None
        return f"{pool or ''}|{slc or ''}"

    # -- membership (aggregator's membership thread) ------------------------

    def register(self, target: str) -> None:
        """Admit a target: a placeholder entry exists from this moment,
        so a feed that never delivers is counted DARK — absence stays
        observable, exactly like the pre-stripe path."""
        with self._route_lock:
            if target in self._route:
                return
            # No identity yet: route by target so placeholders spread.
            # Placeholder lands under the route lock (route→stripe
            # order) so a scan can never see the route without an
            # entry backing it.
            idx = stripe_of(target, self.stripe_count)
            self._route[target] = idx
            stripe = self._stripes[idx]
            with stripe.lock:
                if target not in stripe.entries:
                    stripe.entries[target] = (None, 0.0, 0)
                    stripe.changes += 1

    def remove(self, target: str) -> None:
        """Evict a handed-back/departed target. Stale copies a racing
        writer may have left elsewhere die lazily on the next publish
        scan (their route entry is gone)."""
        with self._route_lock:
            idx = self._route.pop(target, None)
            if idx is None:
                return
            stripe = self._stripes[idx]
            with stripe.lock:
                stripe.entries.pop(target, None)
                # Unconditional bump: a racing writer's ghost may land
                # right after this pop, and the conservative dirty mark
                # guarantees the next publish rescans (and evicts it).
                stripe.changes += 1

    # -- writers (Watch threads / poll executor) ----------------------------

    def put(
        self, target: str, snap: dict | None, data_ts: float,
        content_seq: int,
    ) -> None:
        """Land one stored snapshot in its slice's stripe.

        Common path (stripe unchanged): one GIL-atomic route read + the
        one stripe lock — writers for different slices never contend,
        and the route lock is untouched. An identity MOVE takes the
        route lock for the whole relocation (pop + insert under it, in
        route→stripe lock order), which serializes moves against
        publish scans: mid-move, the target is always present in at
        least one stripe a scan can still reach, so a live feed can
        never be published as departed for a cycle (which would make
        the goodput ledger silently drop its window)."""
        # Lock-free point read: a racing remove() leaves at worst a
        # ghost entry that the publish scan lazily evicts unemitted.
        cur = self._route.get(target)  # tpumon-invariants: disable=lock-discipline (GIL-atomic point read; the move path re-checks under the lock)
        if cur is None:
            return  # not (or no longer) owned: late in-flight store
        slice_key = self._slice_key(snap)
        dest = (
            self._stripe_for_slice(slice_key)
            if slice_key is not None else cur
        )
        if dest == cur:
            stripe = self._stripes[cur]
            with stripe.lock:
                stripe.entries[target] = (snap, data_ts, content_seq)
                stripe.writes += 1
                stripe.changes += 1
            return
        with self._route_lock:
            cur = self._route.get(target)
            if cur is None:
                return  # removed while we raced: drop, never resurrect
            if dest != cur:
                self._route[target] = dest
                old = self._stripes[cur]
                with old.lock:
                    old.entries.pop(target, None)
                    # The departure dirties the OLD stripe too — its
                    # cached rows still carry this target.
                    old.changes += 1
            stripe = self._stripes[dest]
            with stripe.lock:
                stripe.entries[target] = (snap, data_ts, content_seq)
                stripe.writes += 1
                stripe.changes += 1

    # -- publish (collect thread) -------------------------------------------

    def entries(
        self, now: float, stale_s: float, evict_s: float
    ) -> list[tuple]:
        """One cycle's ``(target, snap, state, content_seq)`` rows —
        the :class:`IncrementalRollup` / goodput-ledger input shape.
        At most N brief stripe-lock holds; zero feed locks. Targets
        whose route moved on (slice move, hand-back) are lazily evicted
        here rather than emitted twice. The route lock is held across
        the scan so a concurrent identity MOVE cannot leave a target
        absent from every stripe mid-scan (common-path writes never
        take it — only movers and membership wait, both rare).

        Dirty-set publish: a stripe whose change counter, thresholds,
        and age classes are all provably unchanged since its last drain
        replays its cached rows verbatim — zero per-row work — so an
        idle fleet's publish cost is proportional to the DIRTY stripe
        count, not the stripe count. The cache is invalidated by any
        mutation (the counter), a threshold change, the earliest
        fresh→stale→dark boundary any cached row crosses with no write
        arriving, or a clock that ran backwards (ages are monotone in
        ``now`` only forwards). Replayed rows are the exact list the
        rebuild would produce — same objects, same order — preserving
        the byte-identity contract."""
        out: list[tuple] = []
        params = (stale_s, evict_s)
        dirty = 0
        with self._route_lock:
            route_get = self._route.get
            for idx, stripe in enumerate(self._stripes):
                with stripe.lock:
                    if (
                        stripe.cached_rows is not None
                        and stripe.cached_changes == stripe.changes
                        and stripe.cached_params == params
                        and stripe.cached_built_at <= now
                        and now < stripe.cached_next_transition
                    ):
                        out.extend(stripe.cached_rows)
                        continue
                    dirty += 1
                    rows: list[tuple] = []
                    next_transition = float("inf")
                    evict: list[str] = []
                    for target, (snap, ts, seq) in stripe.entries.items():
                        if route_get(target) != idx:
                            evict.append(target)
                            continue
                        if ts == 0.0:
                            age = float("inf")
                        else:
                            age = max(0.0, now - ts)
                            # The instants this row's class next flips
                            # with no write arriving.
                            for bound in (ts + stale_s, ts + evict_s):
                                if now < bound < next_transition:
                                    next_transition = bound
                        rows.append(
                            (target, snap,
                             classify(age, stale_s, evict_s), seq)
                        )
                    for target in evict:
                        del stripe.entries[target]
                    stripe.cached_rows = rows
                    stripe.cached_changes = stripe.changes
                    stripe.cached_params = params
                    stripe.cached_next_transition = next_transition
                    stripe.cached_built_at = now
                    out.extend(rows)
        self.last_dirty_stripes = dirty
        return out

    def stats(self) -> list[dict]:
        """Per-stripe occupancy + cumulative writes (the
        ``tpu_fleet_rollup_shard_*`` telemetry)."""
        out = []
        for stripe in self._stripes:
            with stripe.lock:
                out.append(
                    {"entries": len(stripe.entries),
                     "writes": stripe.writes}
                )
        return out


__all__ = ["StripedIngest", "stripe_of"]
