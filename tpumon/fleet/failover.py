"""Shard failover: peer liveness, dead-shard takeover, and the
membership plane that owns "which targets is this shard watching".

PR 6's sharding was static: rendezvous over ``shard_count`` indices,
forever. A dead shard's slice of the fleet simply went invisible until
a human or a controller acted. This module closes that hole with the
same no-coordinator stance the sharding itself has:

- :class:`PeerWatcher` probes every peer shard's ``/fleet/summary``
  (cheap: a few hundred bytes of JSON, unguarded like a health probe).
  A peer unreachable for ``takeover_s`` is DEAD; one good probe brings
  it back. The summaries double as the cross-shard rollup feed — one
  probe buys liveness AND the ``scope="global"`` totals.
- :class:`MembershipPlane` runs the loop: resolve the target universe
  (tpumon/fleet/discovery), debounce churn, fold in peer liveness, and
  recompute ownership with :func:`~tpumon.fleet.shard.owned_targets_among`
  — rendezvous over the SURVIVING shards, so a takeover adopts exactly
  the dead peer's targets and nothing else moves (minimal movement, the
  property tests/test_fleet_chaos.py pins).

Every shard runs the same pure functions over the same inputs, so two
survivors never adopt the same orphan. The failure mode left open is
deliberate: a PARTITIONED (not dead) peer and its prober disagree about
liveness, and a target is briefly watched twice — duplicate fan-in is
the safe side. In the asymmetric case the unreachable peer's summary is
excluded from the global merge (we think it's dead), so its totals are
not double-counted; in the brief hand-back window where an alive peer
and we both still claim a target (at most ~one probe round), the global
row reports MORE hosts than the universe and the server flags it
(``contested`` + stale) instead of renormalizing — flagged-overlapping,
never silently wrong.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor, wait

from tpumon.fleet.discovery import Debouncer, TargetResolver
from tpumon.fleet.shard import owned_targets_among

log = logging.getLogger(__name__)

#: Everything a peer probe can throw (same curated set as ingest).
PROBE_ERRORS: tuple[type[BaseException], ...] = (
    urllib.error.URLError,
    OSError,
    ValueError,
)


def parse_peers(raw: str, shard_count: int) -> list[str]:
    """``TPUMON_FLEET_PEERS`` CSV -> index-ordered base URLs (position
    i = shard i). Empty entries are kept as ``""`` PLACEHOLDERS — an
    operator blanking their own slot must not shift every later peer's
    index — and placeholder/tail shards are simply unprobed (assumed
    alive, never declared dead). Extras beyond ``shard_count`` are
    ignored with a warning."""
    if not raw.strip():
        return []
    peers = [p.strip().rstrip("/") for p in raw.split(",")]
    for i, peer in enumerate(peers):
        if peer and not peer.startswith(("http://", "https://")):
            peers[i] = "http://" + peer
    if len(peers) > shard_count:
        log.warning(
            "TPUMON_FLEET_PEERS lists %d peers for %d shards; ignoring "
            "the extras", len(peers), shard_count,
        )
        peers = peers[:shard_count]
    return peers


class PeerWatcher:
    """Liveness + last summary for every peer shard.

    Probes run on the membership thread; ``alive()``/``summaries()``
    are read from the collect loop — one lock guards the maps.
    """

    def __init__(
        self,
        peers: list[str],
        shard_index: int,
        *,
        takeover_s: float,
        shard_count: int | None = None,
        timeout: float = 2.0,
        clock=time.time,
        fetch=None,
    ) -> None:
        self.shard_index = shard_index
        self.shard_count = (
            shard_count if shard_count is not None else len(peers)
        )
        self.takeover_s = takeover_s
        self.timeout = timeout
        self._clock = clock
        self._fetch = fetch if fetch is not None else self._http_fetch
        #: Probed peers only: an index with no URL (short list, ""
        #: placeholder) is NEVER probed and therefore never declared
        #: dead — a shard may only take over from peers it can actually
        #: observe failing.
        self.peers = {
            i: url for i, url in enumerate(peers) if i != shard_index and url
        }
        self._executor: ThreadPoolExecutor | None = None
        self._lock = threading.Lock()
        now = clock()
        #: Startup grace: every peer starts "alive" with a full takeover
        #: window to answer, so a cold sharded rollout doesn't have
        #: shard 0 claiming the whole fleet while shard 1 pulls images.
        self._last_ok = {i: now for i in self.peers}  # guarded-by: self._lock
        self._summaries: dict[int, dict] = {}  # guarded-by: self._lock
        self._errors: dict[int, str] = {}  # guarded-by: self._lock

    def _http_fetch(self, url: str) -> dict:
        with urllib.request.urlopen(
            url + "/fleet/summary", timeout=self.timeout
        ) as resp:
            return json.loads(resp.read().decode())

    def probe_once(self) -> None:
        """One probe round over every peer, CONCURRENTLY: sequential
        probes would make the round last up to len(peers)×timeout, and
        a round longer than takeover_s ages healthy peers' last-ok past
        the deadline — a partition hanging half the peers must never
        make the OTHER half read dead. The round blocks at most one
        probe timeout (+slack); a straggler probe finishes on its
        worker and still updates last-ok late."""
        if not self.peers:
            return
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=min(8, len(self.peers)),
                thread_name_prefix="tpumon-fleet-peer-probe",
            )
        futures = {
            self._executor.submit(self._probe_one, index, url)  # thread: fleet-peer-probe
            for index, url in self.peers.items()
        }
        wait(futures, timeout=self.timeout + 0.5)

    def _probe_one(self, index: int, url: str) -> None:
        try:
            summary = self._fetch(url)
        except PROBE_ERRORS as exc:
            with self._lock:
                self._errors[index] = str(exc)[:200]
            log.debug("peer %d (%s) probe failed: %s", index, url, exc)
            return
        if not isinstance(summary, dict):
            with self._lock:
                self._errors[index] = "non-object summary"
            return
        with self._lock:
            self._last_ok[index] = self._clock()
            self._summaries[index] = summary
            self._errors.pop(index, None)

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False)

    def alive(self) -> set[int]:
        """Shard indices currently considered alive: self, every
        UNPROBED index (no URL configured — assumed alive, we have no
        evidence either way), and every probed peer inside its takeover
        window."""
        now = self._clock()
        with self._lock:
            dead = {
                i for i, ts in self._last_ok.items()
                if now - ts > self.takeover_s
            }
        return set(range(self.shard_count)) - dead

    def summaries(self) -> dict[int, dict]:
        """index -> last /fleet/summary doc, ALIVE peers only (a dead
        peer's totals are its takeover's to re-earn, not ours to
        re-serve)."""
        live = self.alive()
        with self._lock:
            return {
                i: doc for i, doc in self._summaries.items() if i in live
            }

    def states(self) -> dict[int, dict]:
        """Per-peer debug/telemetry view (peer_up gauge, /debug/vars)."""
        now = self._clock()
        alive = self.alive()
        with self._lock:
            return {
                i: {
                    "url": url,
                    "alive": i in alive,
                    "last_ok_age_s": round(
                        max(0.0, now - self._last_ok[i]), 3
                    ),
                    "error": self._errors.get(i),
                }
                for i, url in self.peers.items()
            }


class MembershipPlane:
    """The coherent loop: discovery → debounce → liveness → ownership.

    ``on_membership(owned, info)`` fires (from the plane thread) every
    time this shard's owned target set changes; ``observe_event(kind,
    count)`` counts universe adds/removes and takeover adoptions into
    the server's ``tpu_fleet_membership_*`` / takeover counters.
    """

    def __init__(
        self,
        cfg,
        *,
        on_membership,
        observe_event=None,
        initial_universe: list[str] | None = None,
        initial_epochs: tuple[int, dict] | None = None,
        clock=time.time,
        fetch=None,
    ) -> None:
        self.cfg = cfg
        self._clock = clock
        self._on_membership = on_membership
        self._observe_event = observe_event
        self.resolver = TargetResolver(cfg)
        self.debouncer = Debouncer(cfg.discovery_debounce_s)
        self.watcher: PeerWatcher | None = None
        peers = parse_peers(cfg.peers, cfg.shard_count)
        if any(peers) and cfg.shard_count > 1:
            self.watcher = PeerWatcher(
                peers, cfg.shard_index,
                takeover_s=cfg.takeover_s,
                shard_count=cfg.shard_count,
                timeout=min(cfg.timeout, max(0.5, cfg.probe_interval)),
                clock=clock,
                fetch=fetch,
            )
        self._lock = threading.Lock()
        #: Last (universe, alive) rendezvous inputs — membership-thread
        #: only (plus the synchronous constructor seed), so unlocked.
        self._last_inputs: tuple | None = None
        self._universe: list[str] = []  # guarded-by: self._lock
        self._owned: list[str] | None = None  # guarded-by: self._lock
        self._alive: set[int] = set(range(cfg.shard_count))  # guarded-by: self._lock
        self.takeovers_total = 0  # guarded-by: self._lock
        #: Split-brain ownership epochs (ISSUE 18): a Lamport-style
        #: monotonic mint counter plus the epoch each owned target was
        #: adopted under. Minting folds in the highest epoch observed in
        #: any peer /fleet/summary, so a shard re-claiming targets after
        #: a restart always stamps them NEWER than the takeover that
        #: adopted them — the adapter resolves a double-answer window
        #: newest-epoch-wins instead of flapping the HPA.
        self._epoch_seq = 0  # guarded-by: self._lock
        self._target_epochs: dict[str, int] = {}  # guarded-by: self._lock
        if initial_epochs is not None:
            seq, targets = initial_epochs
            try:
                self._epoch_seq = max(0, int(seq))
                if self._epoch_seq:
                    # Warm-restart skip-ahead: a peer that adopted our
                    # targets while we were down folded the LAST seq we
                    # advertised and minted exactly one above it.
                    # Re-claiming from the same journaled seq would TIE
                    # that adoption epoch (no winner); one extra step
                    # makes restart re-claims strictly newer.
                    self._epoch_seq += 1
                self._target_epochs = {
                    t: int(e)
                    for t, e in dict(targets).items()
                    if isinstance(t, str)
                }
            except (TypeError, ValueError):
                # A corrupt spool section costs epoch warmth, never
                # startup — fresh epochs mint strictly above peers'.
                self._epoch_seq = 0
                self._target_epochs = {}
        self._discover_due = 0.0
        self._probe_due = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tpumon-fleet-membership", daemon=True
        )
        # Seed synchronously so the aggregator's first collect cycle has
        # feeds: a warm restart's spooled universe backs a failed first
        # k8s resolution, and static mode is complete before start().
        if initial_universe:
            self.debouncer.applied = list(initial_universe)
        resolved = self.resolver.resolve()
        if resolved is not None:
            self.debouncer.offer(resolved, self._clock())
        self._recompute(first=True)

    # -- loop --------------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if self.watcher is not None:
            self.watcher.close()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                # The membership loop must never die: a resolution bug
                # leaves the CURRENT feeds serving, which is the safe
                # degradation.
                log.exception("membership tick failed")
            step = min(
                max(0.5, self.cfg.probe_interval)
                if self.watcher is not None
                else self.cfg.discovery_interval,
                self.cfg.discovery_interval,
            )
            if self._stop.wait(max(0.25, step)):
                return

    def tick(self) -> None:
        """One membership round (tests drive this directly)."""
        now = self._clock()
        if now >= self._discover_due:
            self._discover_due = now + self.cfg.discovery_interval
            resolved = self.resolver.resolve()
            if resolved is not None:
                self.debouncer.offer(resolved, now)
        if self.watcher is not None and now >= self._probe_due:
            self._probe_due = now + max(0.5, self.cfg.probe_interval)
            self.watcher.probe_once()
        self._recompute()

    # -- ownership ---------------------------------------------------------

    def _recompute(self, first: bool = False) -> None:
        cfg = self.cfg
        universe = list(self.debouncer.applied or [])
        alive = (
            self.watcher.alive()
            if self.watcher is not None
            else set(range(cfg.shard_count))
        )
        # Steady-state fast path: same universe, same alive set ⇒ same
        # rendezvous outcome — skip re-hashing the whole universe every
        # tick (10k targets × N shards of md5 per probe round adds up).
        inputs = (tuple(universe), frozenset(alive))
        if not first and inputs == self._last_inputs:
            return
        self._last_inputs = inputs
        owned = owned_targets_among(
            universe, cfg.shard_index, alive, cfg.shard_count
        )
        with self._lock:
            old_universe = self._universe
            old_owned = self._owned
            old_alive = self._alive
            self._universe = universe
            self._owned = owned
            self._alive = alive
        universe_set, old_set = set(universe), set(old_universe)
        self._count(
            "add", len(universe_set) if first else len(universe_set - old_set)
        )
        self._count("remove", len(old_set - universe_set))
        if owned == old_owned and not first:
            return
        # Set-based diffs: list membership here would be O(n·m) string
        # compares — at fleet scale that stalls THIS thread (which also
        # runs the peer probes) long enough to age every peer past the
        # takeover deadline and mass-adopt the fleet spuriously.
        old_owned_set = set(old_owned or [])
        owned_set = set(owned)
        added = [t for t in owned if t not in old_owned_set]
        removed = [t for t in (old_owned or []) if t not in owned_set]
        self._mint_epochs(added, removed)
        #: Adoption caused by shards dying (not by universe growth):
        #: newly-owned targets that were already in the universe while a
        #: previously-alive shard dropped out.
        died = old_alive - alive
        if died and added:
            takeover = len([t for t in added if t in old_set])
            if takeover:
                with self._lock:
                    self.takeovers_total += takeover
                self._count("takeover", takeover)
                log.warning(
                    "shard %d adopting %d orphaned target(s) from dead "
                    "shard(s) %s", cfg.shard_index, takeover, sorted(died),
                )
        if added or removed or first:
            try:
                self._on_membership(
                    owned,
                    {
                        "universe": universe,
                        "alive": sorted(alive),
                        "added": added,
                        "removed": removed,
                        "first": first,
                    },
                )
            except Exception:
                log.exception("membership apply failed")

    def _mint_epochs(self, added: list[str], removed: list[str]) -> None:
        """Stamp adopted targets with a fresh ownership epoch minted
        STRICTLY ABOVE every epoch this shard has seen — its own mint
        counter and the highest ``epoch_seq`` any alive peer's summary
        advertises (the Lamport receive rule). A shard re-claiming
        targets after a restart or partition therefore always claims
        them newer than the takeover that adopted them, so a brief
        double-answer window resolves newest-epoch-wins at the
        actuation read model instead of flapping between two truths.
        Handed-back targets drop their epoch — the new owner's claim is
        the only live one."""
        if not added and not removed:
            return
        peer_seq = 0
        if self.watcher is not None:
            for summary in self.watcher.summaries().values():
                seq = summary.get("epoch_seq")
                if isinstance(seq, (int, float)):
                    peer_seq = max(peer_seq, int(seq))
        with self._lock:
            for target in removed:
                self._target_epochs.pop(target, None)
            if added:
                self._epoch_seq = max(self._epoch_seq, peer_seq) + 1
                for target in added:
                    self._target_epochs[target] = self._epoch_seq

    def _count(self, kind: str, n: int) -> None:
        if n and self._observe_event is not None:
            try:
                self._observe_event(kind, n)
            except Exception:
                # Metrics hooks must never break membership.
                log.debug("membership observer failed", exc_info=True)

    # -- views -------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            universe = list(self._universe)
            owned = list(self._owned or [])
            alive = sorted(self._alive)
            takeovers = self.takeovers_total
            epoch_seq = self._epoch_seq
        doc: dict = {
            "source": self.resolver.mode,
            "universe": len(universe),
            "owned": len(owned),
            "alive_shards": alive,
            "takeovers_total": takeovers,
            "epoch_seq": epoch_seq,
        }
        if self.watcher is not None:
            doc["peers"] = self.watcher.states()
        return doc

    def universe(self) -> list[str]:
        with self._lock:
            return list(self._universe)

    def alive_shards(self) -> set[int]:
        with self._lock:
            return set(self._alive)

    def peer_summaries(self) -> dict[int, dict]:
        if self.watcher is None:
            return {}
        return self.watcher.summaries()

    def epochs(self) -> dict[str, int]:
        """target -> ownership epoch for this shard's owned targets."""
        with self._lock:
            return dict(self._target_epochs)

    def epoch_seq(self) -> int:
        """The highest ownership epoch this shard has minted."""
        with self._lock:
            return self._epoch_seq


__all__ = ["MembershipPlane", "PeerWatcher", "PROBE_ERRORS", "parse_peers"]
