"""1 Hz sample-history flight recorder (DCGM field-cache analogue).

The exporter polls the device backend at 1 Hz, but Prometheus typically
scrapes every 15-60 s — transients like duty-cycle spikes, throttle events,
and ICI link flaps alias away between scrapes (SURVEY.md §2.1 "DCGM
engine" row: dcgm field watches keep exactly this kind of bounded
per-field sample cache). :class:`History` records every poll cycle's
points into a bounded per-series ring and serves windowed summaries
(min/max/avg/last/rate) and raw points back out via the exporter's
``/history`` endpoint and the ``tpumon smi`` CLI.

The engine is native C++ (``tpumon/_native/_history.cc``), compiled
on demand like the exposition renderer; a pure-Python implementation with
identical semantics (:class:`PyEngine`) backs no-compiler environments.
"""

from __future__ import annotations

import logging
import threading
from collections import deque

log = logging.getLogger(__name__)

def _load_native():
    """Build-on-demand via the shared tpumon._native pipeline (which owns
    memoization and the TPUMON_NO_NATIVE kill-switch); any failure means
    "use the fallback"."""
    from tpumon._native import load_extension

    return getattr(load_extension("_history"), "Engine", None)


def _summary(samples, lo: float):
    vals = [(ts, v) for ts, v in samples if ts >= lo]
    if not vals:
        return None
    values = [v for _, v in vals]
    first_ts, first = vals[0]
    last_ts, last = vals[-1]
    dt = last_ts - first_ts
    return {
        "count": len(vals),
        "min": min(values),
        "max": max(values),
        "avg": sum(values) / len(values),
        "first": first,
        "last": last,
        "first_ts": first_ts,
        "last_ts": last_ts,
        "rate": (last - first) / dt if dt > 0 else 0.0,
    }


class PyEngine:
    """Pure-Python engine, semantics identical to the C++ one (tested
    against it sample-for-sample in tests/test_history.py)."""

    def __init__(self, max_age: float = 600.0, max_samples: int = 4096) -> None:
        if max_age <= 0 or max_samples <= 0:
            raise ValueError("max_age and max_samples must be > 0")
        self._max_age = max_age
        self._max_samples = max_samples
        self._series: dict[str, deque] = {}  # guarded-by: self._lock
        self._record_calls = 0  # guarded-by: self._lock
        self._lock = threading.Lock()

    def record_batch(self, ts: float, items) -> None:
        with self._lock:
            for key, value in items:
                s = self._series.setdefault(key, deque())
                s.append((ts, float(value)))
                horizon = ts - self._max_age
                while s and (s[0][0] < horizon or len(s) > self._max_samples):
                    s.popleft()
            self._record_calls += 1
            if self._record_calls % 256 == 0:
                horizon = ts - self._max_age
                dead = [
                    k
                    for k, s in self._series.items()
                    if not s or s[-1][0] < horizon
                ]
                for k in dead:
                    del self._series[k]

    def query(self, key: str, since: float = 0.0):
        with self._lock:
            s = self._series.get(key, ())
            return [(ts, v) for ts, v in s if ts >= since]

    def summarize(self, key: str, window: float, now: float):
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return None
            samples = list(s)
        return _summary(samples, now - window)

    def summarize_all(self, window: float, now: float):
        with self._lock:
            copy = {k: list(s) for k, s in self._series.items()}
        out = {}
        for k, samples in copy.items():
            summ = _summary(samples, now - window)
            if summ is not None:
                out[k] = summ
        return out

    def keys(self):
        with self._lock:
            return sorted(self._series)

    def stats(self):
        with self._lock:
            return (
                len(self._series),
                sum(len(s) for s in self._series.values()),
            )


def make_engine(max_age: float = 600.0, max_samples: int = 4096, native=None):
    """Engine factory: native C++ when buildable, PyEngine otherwise.

    ``native=True`` forces the C++ engine (raises when unavailable),
    ``native=False`` forces the fallback; ``None`` picks automatically.
    """
    if native is False:
        return PyEngine(max_age, max_samples)
    cls = _load_native()
    if cls is None:
        if native is True:
            raise RuntimeError("native history engine unavailable")
        return PyEngine(max_age, max_samples)
    return cls(max_age, max_samples)


def native_available() -> bool:
    return _load_native() is not None


def series_key(family: str, labels: dict[str, str]) -> str:
    """Stable series identity: ``family{k="v",...}`` with sorted keys —
    matches the Prometheus sample identity minus the node-constant base
    labels, so /history keys read like the /metrics page."""
    if not labels:
        return family
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{family}{{{inner}}}"


#: Families whose samples are identity/enum rows (value is always 1 or a
#: label carries the signal) — no point recording them as time series.
SKIP_FAMILIES = frozenset(
    {"accelerator_info", "accelerator_core_state", "accelerator_pod_info"}
)


class History:
    """The recorder wired into the poll loop.

    ``record_families`` extracts (key, value) points from the poll cycle's
    metric families, dropping node-constant base labels from the key and
    skipping identity families.

    With ``native=None`` (the default, used by the exporter) construction
    is instant: recording starts on the pure-Python engine and a daemon
    thread builds/loads the C++ engine — a compile that can take tens of
    seconds must never sit inside ``Exporter.__init__``, where it would
    hold off the first poll and the readiness probe. When the native
    engine arrives, the samples accumulated meanwhile are replayed into
    it and the engines are swapped under a lock, so no poll cycle is
    lost across the upgrade. ``native=True``/``False`` stay synchronous
    (tests and benchmarks pin the engine deliberately).
    """

    def __init__(
        self,
        max_age: float = 600.0,
        max_samples: int = 4096,
        native=None,
    ) -> None:
        self.max_age = max_age
        #: Current per-series cap; tracked so a concurrent native
        #: upgrade honors a resize() that landed while it compiled.
        self.max_samples = max_samples
        self._swap_lock = threading.Lock()
        if native is None:
            self.engine = PyEngine(max_age, max_samples)
            threading.Thread(
                target=self._upgrade_to_native,
                args=(max_age,),
                name="tpumon-history-build",
                daemon=True,
            ).start()
        else:
            self.engine = make_engine(max_age, max_samples, native)

    def _upgrade_to_native(self, max_age: float) -> None:
        try:
            cls = _load_native()  # may compile; runs off the poll path
        except Exception as exc:  # pragma: no cover - load_extension guards
            log.info("native history engine unavailable: %s", exc)
            return
        if cls is None:
            return
        with self._swap_lock:
            fresh = cls(max_age, self.max_samples)
            old = self.engine
            # Replay everything recorded during the build. Per-series
            # timestamps are in order, which is all the engines' pruning
            # assumes; the lock keeps record_families from writing to the
            # old engine mid-replay.
            for key in old.keys():
                for ts, value in old.query(key):
                    fresh.record_batch(ts, ((key, value),))
            self.engine = fresh
        log.info("history engine upgraded to native (replayed %d series)",
                 len(old.keys()))

    def resize(self, max_samples: int) -> None:
        """Re-cap every series ring — the memory-watermark response
        (tpumon/guard/memwatch): swaps in a fresh engine at the new cap
        and replays the newest retained samples. Engine-agnostic: the
        replay uses only the public record/query API, so it works on the
        C++ engine and the Python fallback alike. Reversible (resizing
        back up keeps whatever survived the shrink)."""
        max_samples = max(1, int(max_samples))
        with self._swap_lock:
            if max_samples == self.max_samples:
                return
            self.max_samples = max_samples
            old = self.engine
            fresh = type(old)(self.max_age, max_samples)
            # Batch by timestamp (poll cycles share one ts across
            # series) so the replay is one record_batch per cycle, not
            # one per sample.
            batches: dict[float, list] = {}
            for key in old.keys():
                for ts, value in old.query(key)[-max_samples:]:
                    batches.setdefault(ts, []).append((key, value))
            for ts in sorted(batches):
                fresh.record_batch(ts, batches[ts])
            self.engine = fresh

    @property
    def is_native(self) -> bool:
        return not isinstance(self.engine, PyEngine)

    def record_families(self, ts: float, families, base_keys=()) -> None:
        base = set(base_keys)
        items = []
        for fam in families:
            if fam.name in SKIP_FAMILIES:
                continue
            for s in fam.samples:
                labels = {k: v for k, v in s.labels.items() if k not in base}
                items.append((series_key(s.name, labels), float(s.value)))
        if items:
            with self._swap_lock:
                self.engine.record_batch(ts, items)

    def query(self, key: str, since: float = 0.0):
        return self.engine.query(key, since)

    def summarize_all(self, window: float, now: float):
        return self.engine.summarize_all(window, now)

    def summarize(self, key: str, window: float, now: float):
        return self.engine.summarize(key, window, now)

    def keys(self):
        return self.engine.keys()

    def stats(self):
        return self.engine.stats()
