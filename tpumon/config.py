"""Env-first configuration with argparse overrides.

K8s-native precedence (SURVEY.md §5.6): every knob is an ``TPUMON_*``
environment variable (the natural way to configure a DaemonSet pod via
``env:`` / ConfigMap), and every knob has a CLI flag that wins over the
environment. Defaults are the 1 Hz / :9400 targets from BASELINE.md.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from dataclasses import dataclass


ENV_PREFIX = "TPUMON_"

#: Backends selectable via --backend / TPUMON_BACKEND.
#: "auto" picks libtpu when importable and devices are present, else stub.
BACKEND_CHOICES = ("auto", "libtpu", "grpc", "fake", "stub", "nvml")


def _env(name: str, default: str | None = None) -> str | None:
    return os.environ.get(ENV_PREFIX + name, default)


def _env_int(name: str, default: int) -> int:
    raw = _env(name)
    if raw is None or not raw.strip():
        return default
    try:
        return int(raw)
    except ValueError:
        # Malformed env must never CrashLoopBackOff the DaemonSet.
        return default


def _env_float(name: str, default: float) -> float:
    raw = _env(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_bool(name: str, default: bool) -> bool:
    raw = _env(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


def _split_csv(raw: str | None) -> tuple[str, ...]:
    if not raw:
        return ()
    return tuple(p.strip() for p in raw.split(",") if p.strip())


@dataclass(frozen=True)
class Config:
    """Immutable run configuration for the exporter and sidecar."""

    #: TCP port for the Prometheus /metrics endpoint.
    port: int = 9400
    #: Bind address for the HTTP server.
    addr: str = "0.0.0.0"
    #: Poll interval in seconds (1.0 == the 1 Hz BASELINE target).
    interval: float = 1.0
    #: Which device backend to use (see BACKEND_CHOICES).
    backend: str = "auto"
    #: Allow-list of libtpu metric names; empty = all supported.
    metric_allow: tuple[str, ...] = ()
    #: Deny-list of libtpu metric names; applied after the allow-list.
    metric_deny: tuple[str, ...] = ()
    #: Optional JSON file overriding discovered topology (tests, air-gapped).
    topology_file: str | None = None
    #: Fake backend topology preset (see tpumon.backends.fake.TOPOLOGIES).
    fake_topology: str = "v5e-16"
    #: gRPC monitoring service address (libtpu runtime default port).
    grpc_addr: str = "localhost:8431"
    #: gRPC request timeout in seconds.
    grpc_timeout: float = 2.0
    #: Full name of the runtime monitoring gRPC service to resolve via
    #: server reflection (the dynamic-stub metric transport).
    grpc_service: str = "tpu.monitoring.runtime.RuntimeMetricService"
    #: Serve the exporter's own gRPC metrics service (Get/Watch +
    #: reflection) on this port; -1 disables, 0 binds an ephemeral port.
    grpc_serve_port: int = -1
    #: Subscribe to the runtime service's server-streaming watch method
    #: when it has one (push-fed samples, unary fallback). Disable if a
    #: runtime's stream implementation misbehaves — polling always works.
    grpc_watch: bool = True
    #: Emit per-link ICI gauges (can be high-cardinality on big slices).
    ici_per_link: bool = True
    #: Emit host context gauges (CPU/mem/load/net via psutil) next to the
    #: device families for accelerator-symptom diagnosis.
    host_metrics: bool = True
    #: Emit cumulative duty-cycle / core-utilization histograms fed by the
    #: 1 Hz poll loop (BASELINE config 3), recovering the between-scrape
    #: distribution inside the scrape itself.
    histograms: bool = True
    #: Chip→pod attribution via the kubelet pod-resources API; degrades
    #: silently to absent off-cluster.
    pod_attribution: bool = True
    #: kubelet pod-resources gRPC socket.
    kubelet_socket: str = "unix:///var/lib/kubelet/pod-resources/kubelet.sock"
    #: Sample-history window in seconds (the 1 Hz flight recorder backing
    #: /history and `tpumon smi`); 0 disables recording.
    history_window: float = 600.0
    #: Per-series sample cap for the history engine.
    history_max_samples: int = 4096
    #: Streaming anomaly detection over the 1 Hz poll stream
    #: (tpumon.anomaly): tpu_anomaly_* families + /anomalies endpoint.
    #: Detector thresholds are separate TPUMON_ANOMALY_<FIELD> env vars
    #: (tpumon/anomaly/detectors.py).
    anomaly: bool = True
    #: Per-device retained-event cap for the anomaly engine's rings.
    anomaly_events_max: int = 256
    #: Fault-tolerance plane (tpumon/resilience): per-query circuit
    #: breakers + stale-but-served degradation in the poll loop. Off
    #: restores the pre-resilience behavior (failures drop families).
    resilience: bool = True
    #: Stale-but-served window seconds: on query failure / open breaker,
    #: the last good family keeps being served (flagged via
    #: tpumon_degraded / tpumon_family_staleness_seconds) up to this age.
    #: 0 disables last-good serving while keeping the breakers.
    stale_serve_s: float = 300.0
    #: Device-call retry attempts (1 = no retry) and the bounded
    #: exponential-backoff envelope between attempts.
    retry_attempts: int = 2
    retry_base_s: float = 0.05
    retry_max_s: float = 0.5
    #: Circuit breaker: consecutive failures that open it, seconds the
    #: open state refuses calls before a half-open probe, and probe
    #: successes required to close again.
    breaker_failures: int = 5
    breaker_open_s: float = 15.0
    breaker_probes: int = 2
    #: Poll-cycle hang budget in seconds before the watchdog recovers
    #: the backend (interrupt + channel teardown/re-init); 0 disables.
    watchdog_hang_s: float = 10.0
    #: Fault-injection spec (TPUMON_FAULTS, tpumon/resilience/faults.py)
    #: wrapping the selected backend — chaos testing only; empty = off.
    faults: str = ""
    #: Host-correlation plane (tpumon/hostcorr): 1 Hz procfs/cgroupfs
    #: host-signal sampling (cgroup PSI, per-pod sched delay, net/io
    #: rates, page-cache pressure) time-aligned with the poll stream,
    #: cross-signal straggler attribution (tpu_straggler_*), /hostcorr.
    hostcorr: bool = True
    #: Root prepended to every procfs/cgroupfs path the hostcorr sampler
    #: reads; empty = the real / (tests point it at a fixture tree).
    hostcorr_proc_root: str = ""
    #: Correlation-ring capacity (one joined host+device record per poll
    #: cycle, served by /hostcorr).
    hostcorr_ring: int = 600
    #: Energy & cost plane (tpumon/energy): per-chip power (measured
    #: where the backend exposes it, duty×TDP modeled where not — every
    #: family source-labeled), monotonic joules counters, pod-attributed
    #: energy, and tokens-per-joule / dollars-per-step joins against the
    #: lifecycle plane's step telemetry. Tuning (incl. the
    #: TPUMON_ENERGY_DOLLARS_PER_KWH price knob and the
    #: TPUMON_ENERGY_TDP_W override) rides separate TPUMON_ENERGY_<FIELD>
    #: env vars (tpumon/energy/model.py).
    energy: bool = True
    #: Workload-lifecycle robustness plane (tpumon/lifecycle): probe the
    #: workload harness's metrics port (tpu_step_* families), classify
    #: preemption/resize/restore transitions, suppress false verdicts
    #: during clean transitions, and arm the step-regression /
    #: ICI-contention detectors. Classifier thresholds are separate
    #: TPUMON_LIFECYCLE_<FIELD> env vars (tpumon/lifecycle/detectors.py).
    lifecycle: bool = True
    #: Workload step-feed URLs the lifecycle plane probes once per poll
    #: cycle (CSV; typically the harness --metrics-port on localhost).
    #: Empty = no feeds — the plane still tracks device-side lifecycle
    #: signatures (resize via topology re-enumeration).
    lifecycle_step_urls: str = ""
    #: Lifecycle-ring capacity (one joined step+device record per poll
    #: cycle, served by /lifecycle).
    lifecycle_ring: int = 600
    #: Self-protection plane (tpumon/guard): scrape admission control,
    #: request deadlines, cardinality governor, and memory watermarks.
    #: Off restores the unguarded serving paths (replay-response bounds
    #: stay — they are API semantics, not load policy).
    guard: bool = True
    #: Concurrent in-flight cap for /metrics requests (0 = uncapped).
    guard_metrics_inflight: int = 16
    #: Concurrent in-flight cap shared by the debug-class endpoints
    #: (/debug/*, /history, /anomalies, /health/devices).
    guard_debug_inflight: int = 4
    #: Token-bucket rate limits, requests/s with 2x burst (0 = unlimited).
    #: /metrics is uncapped by default — the scrape path serves cached
    #: bytes and must absorb Prometheus HA fan-in; the JSON endpoints
    #: allocate per request and get a real budget.
    guard_metrics_rps: float = 0.0
    guard_debug_rps: float = 20.0
    #: Header-read deadline seconds: once a request's first byte arrives,
    #: the full request line + headers must complete within this budget
    #: (the slowloris kill). 0 disables.
    guard_header_timeout_s: float = 5.0
    #: Idle keep-alive eviction seconds: a persistent connection with no
    #: next request within this window is closed. 0 disables.
    guard_idle_timeout_s: float = 65.0
    #: Response write deadline seconds (half-dead peers can't park a
    #: serving thread forever). 0 disables.
    guard_write_timeout_s: float = 10.0
    #: Replay-response bounds for /debug/traces and /anomalies ?since=
    #: reads: max items and max payload bytes per response; past either,
    #: the response is truncated with a continuation token.
    guard_replay_max_items: int = 256
    guard_replay_max_bytes: int = 1048576
    #: Per-family series budget (tpumon/guard/cardinality.py): overflow
    #: series collapse into a sentinel `other` label value. 0 disables.
    #: 10k (lifted from 1000 with the native-backed family index) so a
    #: full-size slice's per-link/per-pod families fit ungoverned while
    #: runaway label explosions still collapse.
    guard_max_series_per_family: int = 10000
    #: RSS watermarks in MB (tpumon/guard/memwatch.py): soft shrinks the
    #: trace/history/anomaly rings and disables slow-cycle capture; hard
    #: drops to metrics-only serving. 0 = auto (75% / 90% of the cgroup
    #: container memory limit; disarmed when the process has none — test
    #: runners and embedders); >0 absolute MB; <0 disables that stage.
    guard_soft_rss_mb: float = 0.0
    guard_hard_rss_mb: float = 0.0
    #: Concurrent gRPC Watch streams admitted per client address.
    guard_watch_per_client: int = 4
    #: Incremental (delta) page render: per-family cached byte segments
    #: with change fingerprints — only families whose samples changed
    #: re-render each poll cycle, the page assembles by concatenation.
    #: Off restores the full per-cycle render (a diagnostic escape
    #: hatch; output bytes are identical either way).
    render_delta: bool = True
    #: Exposition formats /metrics (and gRPC Get/Watch) will negotiate,
    #: CSV of: text (Prometheus 0.0.4, always kept — the compatibility
    #: floor), openmetrics (OpenMetrics 1.0 via Accept), snapshot (the
    #: compact length-prefixed binary snapshot the fleet tier's fan-in
    #: requests first), delta (sequence-numbered changed-segment frames
    #: against that snapshot — fan-in bytes proportional to change rate).
    exposition_formats: tuple[str, ...] = (
        "text", "openmetrics", "snapshot", "delta",
    )
    #: Watch streams serving the delta format push a full-snapshot
    #: resync frame after this many consecutive delta frames, bounding
    #: worst-case consumer divergence to one resync window.
    delta_resync_frames: int = 300
    #: Internal trace plane (tpumon/trace): per-stage spans around every
    #: poll-pipeline stage, served at /debug/traces (+/slow) and as the
    #: tpumon_trace_stage_duration_seconds self-metric.
    trace: bool = True
    #: Poll cycles slower than this many milliseconds are promoted to the
    #: slow-cycle flight recorder (/debug/traces/slow) with their full
    #: span tree and PollStats.
    trace_slow_cycle_ms: float = 250.0
    #: Completed-cycle trace ring capacity (/debug/traces).
    trace_ring: int = 128
    #: Slow-cycle ring capacity (/debug/traces/slow).
    trace_slow_ring: int = 32
    #: Log level name.
    log_level: str = "INFO"
    #: Log output format: "text" (human) or "json" (one structured object
    #: per line, trace-id correlated — tpumon/trace/logfmt.py).
    log_format: str = "text"
    #: Path where the discovery sidecar writes topology JSON.
    topology_out: str = "/var/run/tpumon/topology.json"

    @classmethod
    def from_env(cls) -> "Config":
        base = cls()
        return cls(
            port=_env_int("PORT", base.port),
            addr=_env("ADDR", base.addr) or base.addr,
            interval=_env_float("INTERVAL", base.interval),
            backend=_env("BACKEND", base.backend) or base.backend,
            metric_allow=_split_csv(_env("METRIC_ALLOW")),
            metric_deny=_split_csv(_env("METRIC_DENY")),
            topology_file=_env("TOPOLOGY_FILE"),
            fake_topology=_env("FAKE_TOPOLOGY", base.fake_topology)
            or base.fake_topology,
            grpc_addr=_env("GRPC_ADDR", base.grpc_addr) or base.grpc_addr,
            grpc_timeout=_env_float("GRPC_TIMEOUT", base.grpc_timeout),
            grpc_service=_env("GRPC_SERVICE", base.grpc_service)
            or base.grpc_service,
            grpc_serve_port=_env_int("GRPC_SERVE_PORT", base.grpc_serve_port),
            grpc_watch=_env_bool("GRPC_WATCH", base.grpc_watch),
            ici_per_link=_env_bool("ICI_PER_LINK", base.ici_per_link),
            host_metrics=_env_bool("HOST_METRICS", base.host_metrics),
            histograms=_env_bool("HISTOGRAMS", base.histograms),
            pod_attribution=_env_bool("POD_ATTRIBUTION", base.pod_attribution),
            history_window=_env_float("HISTORY_WINDOW", base.history_window),
            history_max_samples=_env_int(
                "HISTORY_MAX_SAMPLES", base.history_max_samples
            ),
            anomaly=_env_bool("ANOMALY", base.anomaly),
            anomaly_events_max=_env_int(
                "ANOMALY_EVENTS_MAX", base.anomaly_events_max
            ),
            resilience=_env_bool("RESILIENCE", base.resilience),
            stale_serve_s=_env_float("STALE_SERVE_S", base.stale_serve_s),
            retry_attempts=_env_int("RETRY_ATTEMPTS", base.retry_attempts),
            retry_base_s=_env_float("RETRY_BASE_S", base.retry_base_s),
            retry_max_s=_env_float("RETRY_MAX_S", base.retry_max_s),
            breaker_failures=_env_int(
                "BREAKER_FAILURES", base.breaker_failures
            ),
            breaker_open_s=_env_float("BREAKER_OPEN_S", base.breaker_open_s),
            breaker_probes=_env_int("BREAKER_PROBES", base.breaker_probes),
            watchdog_hang_s=_env_float(
                "WATCHDOG_HANG_S", base.watchdog_hang_s
            ),
            faults=_env("FAULTS", base.faults) or base.faults,
            hostcorr=_env_bool("HOSTCORR", base.hostcorr),
            hostcorr_proc_root=_env(
                "HOSTCORR_PROC_ROOT", base.hostcorr_proc_root
            )
            or base.hostcorr_proc_root,
            hostcorr_ring=_env_int("HOSTCORR_RING", base.hostcorr_ring),
            energy=_env_bool("ENERGY", base.energy),
            lifecycle=_env_bool("LIFECYCLE", base.lifecycle),
            lifecycle_step_urls=_env(
                "LIFECYCLE_STEP_URLS", base.lifecycle_step_urls
            )
            or base.lifecycle_step_urls,
            lifecycle_ring=_env_int("LIFECYCLE_RING", base.lifecycle_ring),
            guard=_env_bool("GUARD", base.guard),
            guard_metrics_inflight=_env_int(
                "GUARD_METRICS_INFLIGHT", base.guard_metrics_inflight
            ),
            guard_debug_inflight=_env_int(
                "GUARD_DEBUG_INFLIGHT", base.guard_debug_inflight
            ),
            guard_metrics_rps=_env_float(
                "GUARD_METRICS_RPS", base.guard_metrics_rps
            ),
            guard_debug_rps=_env_float(
                "GUARD_DEBUG_RPS", base.guard_debug_rps
            ),
            guard_header_timeout_s=_env_float(
                "GUARD_HEADER_TIMEOUT_S", base.guard_header_timeout_s
            ),
            guard_idle_timeout_s=_env_float(
                "GUARD_IDLE_TIMEOUT_S", base.guard_idle_timeout_s
            ),
            guard_write_timeout_s=_env_float(
                "GUARD_WRITE_TIMEOUT_S", base.guard_write_timeout_s
            ),
            guard_replay_max_items=_env_int(
                "GUARD_REPLAY_MAX_ITEMS", base.guard_replay_max_items
            ),
            guard_replay_max_bytes=_env_int(
                "GUARD_REPLAY_MAX_BYTES", base.guard_replay_max_bytes
            ),
            guard_max_series_per_family=_env_int(
                "GUARD_MAX_SERIES_PER_FAMILY",
                base.guard_max_series_per_family,
            ),
            guard_soft_rss_mb=_env_float(
                "GUARD_SOFT_RSS_MB", base.guard_soft_rss_mb
            ),
            guard_hard_rss_mb=_env_float(
                "GUARD_HARD_RSS_MB", base.guard_hard_rss_mb
            ),
            guard_watch_per_client=_env_int(
                "GUARD_WATCH_PER_CLIENT", base.guard_watch_per_client
            ),
            render_delta=_env_bool("RENDER_DELTA", base.render_delta),
            exposition_formats=_split_csv(_env("EXPOSITION_FORMATS"))
            or base.exposition_formats,
            delta_resync_frames=_env_int(
                "DELTA_RESYNC_FRAMES", base.delta_resync_frames
            ),
            trace=_env_bool("TRACE", base.trace),
            trace_slow_cycle_ms=_env_float(
                "TRACE_SLOW_CYCLE_MS", base.trace_slow_cycle_ms
            ),
            trace_ring=_env_int("TRACE_RING", base.trace_ring),
            trace_slow_ring=_env_int("TRACE_SLOW_RING", base.trace_slow_ring),
            log_format=_env("LOG_FORMAT", base.log_format) or base.log_format,
            kubelet_socket=_env("KUBELET_SOCKET", base.kubelet_socket)
            or base.kubelet_socket,
            log_level=_env("LOG_LEVEL", base.log_level) or base.log_level,
            topology_out=_env("TOPOLOGY_OUT", base.topology_out)
            or base.topology_out,
        )

    @classmethod
    def add_args(cls, parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("tpumon")
        g.add_argument("--port", type=int, help="HTTP port for /metrics")
        g.add_argument("--addr", help="bind address")
        g.add_argument("--interval", type=float, help="poll interval seconds")
        g.add_argument("--backend", choices=BACKEND_CHOICES, help="device backend")
        g.add_argument("--metric-allow", help="CSV allow-list of metric names")
        g.add_argument("--metric-deny", help="CSV deny-list of metric names")
        g.add_argument("--topology-file", help="JSON topology override")
        g.add_argument("--fake-topology", help="fake backend topology preset")
        g.add_argument("--grpc-addr", help="monitoring gRPC address")
        g.add_argument("--grpc-timeout", type=float, help="gRPC timeout seconds")
        g.add_argument(
            "--grpc-service",
            help="monitoring gRPC service full name (resolved via reflection)",
        )
        g.add_argument(
            "--grpc-serve-port",
            type=int,
            help="serve the gRPC metrics service (Get/Watch) on this port "
            "(-1 disables, 0 ephemeral)",
        )
        g.add_argument(
            "--history-window",
            type=float,
            help="sample-history window seconds (0 disables /history)",
        )
        g.add_argument(
            "--history-max-samples",
            type=int,
            help="per-series sample cap for the history engine",
        )
        g.add_argument(
            "--anomaly-events-max",
            type=int,
            help="per-device retained-event cap for the anomaly engine",
        )
        g.add_argument(
            "--stale-serve-s",
            type=float,
            help="serve last-good families up to this many seconds old "
            "when queries fail or a breaker is open (0 disables)",
        )
        g.add_argument(
            "--watchdog-hang-s",
            type=float,
            help="poll-cycle hang budget before the watchdog recovers "
            "the backend (0 disables)",
        )
        g.add_argument(
            "--breaker-open-s",
            type=float,
            help="seconds an open circuit breaker refuses device calls "
            "before a half-open probe",
        )
        g.add_argument(
            "--faults",
            help="fault-injection spec (chaos testing), e.g. "
            "error_rate=0.3,hang_every=20,hang_s=10",
        )
        g.add_argument(
            "--hostcorr-proc-root",
            help="root prepended to the procfs/cgroupfs paths the "
            "host-correlation sampler reads (fixture trees, tests)",
        )
        g.add_argument(
            "--hostcorr-ring",
            type=int,
            help="correlation-ring capacity for /hostcorr (one joined "
            "host+device record per poll cycle)",
        )
        g.add_argument(
            "--lifecycle-step-urls",
            help="workload step-feed URLs the lifecycle plane probes "
            "(CSV; the harness --metrics-port), e.g. "
            "http://127.0.0.1:9401",
        )
        g.add_argument(
            "--lifecycle-ring",
            type=int,
            help="lifecycle-ring capacity for /lifecycle (one joined "
            "step+device record per poll cycle)",
        )
        g.add_argument(
            "--guard-soft-rss-mb",
            type=float,
            help="soft memory watermark MB: shrink trace/history/anomaly "
            "rings and stop slow-cycle capture (0 disables)",
        )
        g.add_argument(
            "--guard-hard-rss-mb",
            type=float,
            help="hard memory watermark MB: drop to metrics-only serving "
            "(0 disables)",
        )
        g.add_argument(
            "--guard-debug-rps",
            type=float,
            help="token-bucket rate limit for the debug-class endpoints "
            "(/debug/*, /history, /anomalies), requests/s (0 = unlimited)",
        )
        g.add_argument(
            "--guard-header-timeout-s",
            type=float,
            help="request header-read deadline seconds (slowloris kill; "
            "0 disables)",
        )
        g.add_argument(
            "--trace-slow-cycle-ms",
            type=float,
            help="promote poll cycles slower than this to the slow-cycle "
            "trace ring (/debug/traces/slow)",
        )
        g.add_argument("--log-level", help="log level")
        g.add_argument(
            "--log-format",
            choices=("text", "json"),
            help="log output format (json = structured, trace-id "
            "correlated)",
        )
        g.add_argument("--kubelet-socket", help="pod-resources gRPC socket")
        g.add_argument("--topology-out", help="sidecar topology JSON path")

    def with_args(self, args: argparse.Namespace) -> "Config":
        updates: dict = {}
        for f in dataclasses.fields(self):
            cli_name = f.name.replace("-", "_")
            val = getattr(args, cli_name, None)
            if val is None:
                continue
            if f.name in ("metric_allow", "metric_deny") and isinstance(val, str):
                val = _split_csv(val)
            updates[f.name] = val
        return dataclasses.replace(self, **updates)

    @classmethod
    def load(cls, argv: list[str] | None = None) -> "Config":
        """Environment first, CLI flags override (SURVEY.md §5.6)."""
        parser = argparse.ArgumentParser(prog="tpumon")
        cls.add_args(parser)
        args = parser.parse_args(argv)
        return cls.from_env().with_args(args)

    def metric_enabled(self, name: str) -> bool:
        if self.metric_allow and name not in self.metric_allow:
            return False
        return name not in self.metric_deny
