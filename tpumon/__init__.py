"""tpumon — TPU-native Kubernetes accelerator-telemetry framework.

A ground-up TPU-first re-design of the capabilities of the
``ma2331550908/k8s-gpu-monitor`` GPU exporter stack (see SURVEY.md — the
reference mount was empty, so the blueprint is SURVEY.md's reconstruction
from driver metadata plus live libtpu probes):

- **Device backend** (L1): ``libtpu.sdk.tpumonitoring`` / ``slice`` / ``tpuz``
  adapters replace NVML/DCGM; a gRPC monitoring client covers the
  DCGM-hostengine-analogue path.
- **Discovery** (L2): TPU slice topology (host/chip/core + coords) replaces
  PCIe-BDF identity.
- **Exporter core** (L3): poll loop + sample cache + ``/metrics`` with a
  unified ``accelerator_*`` schema shared across TPU and GPU.
- **Scrape plane / deployment / dashboards** (L4-L6): Prometheus exposition,
  K8s DaemonSet manifests, Grafana dashboards incl. ICI fabric heatmaps.

Layer map and component inventory: SURVEY.md §1-§2.
"""

__version__ = "0.1.0"

from tpumon.config import Config
from tpumon.backends import create_backend
from tpumon.backends.base import Backend, RawMetric
from tpumon.discovery.topology import Topology, discover

__all__ = [
    "Config",
    "create_backend",
    "Backend",
    "RawMetric",
    "Topology",
    "discover",
    "__version__",
]
