"""Request-level serving telemetry for the harness /metrics port.

The training side already closes the monitor↔trainer loop through
``tpu_step_*`` (tpumon/workload/stats.py); this module is the serving
counterpart for the inference-shaped preset (ISSUE 16): completed
requests, windowed requests/s, live queue depth, effective batch size,
a time-to-first-token proxy, and goodput under SLO — the ``tpu_serve_*``
families the node exporter's lifecycle plane lifts into
``tpu_lifecycle_serve_*`` and the fleet actuation tier
(tpumon/actuate) turns into External Metrics an HPA can scale on.

The TTFT proxy is queue wait plus one decode-step latency for requests
admitted in the window — the harness has no real token stream, but the
proxy moves with exactly the things that move real TTFT (queueing and
step time), which is what the scale signal needs. SLO attainment is the
fraction of the window's requests whose proxy met the configured
threshold; both follow the absent-not-zero rule until the serving loop
records its first window.
"""

from __future__ import annotations

import threading


class ServeStats:
    """Thread-safe serving telemetry shared between the request loop
    (writer) and a Prometheus collector on the metrics port (reader)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._requests_total = 0  # guarded-by: self._lock
        self._window_rate: float | None = None  # guarded-by: self._lock
        self._queue_depth = 0  # guarded-by: self._lock
        self._batch_mean: float | None = None  # guarded-by: self._lock
        self._ttft_s: float | None = None  # guarded-by: self._lock
        self._slo_ratio: float | None = None  # guarded-by: self._lock
        self._slo_threshold_s: float | None = None  # guarded-by: self._lock

    def configure(self, *, slo_threshold_s: float | None) -> None:
        """Static run fact: the TTFT SLO the attainment ratio is
        measured against (None = no SLO configured; the ratio family is
        then absent rather than measured against a made-up bound)."""
        with self._lock:
            self._slo_threshold_s = (
                float(slo_threshold_s) if slo_threshold_s else None
            )

    def set_queue_depth(self, depth: int) -> None:
        """Instantaneous admitted-but-incomplete request count (the
        serving loop updates it on admit and on completion)."""
        with self._lock:
            self._queue_depth = max(0, int(depth))

    def record_window(
        self,
        *,
        requests: int,
        seconds: float,
        batch_mean: float | None,
        ttft_worst_s: float | None,
        slo_met: int | None = None,
    ) -> None:
        """One serving window: ``requests`` completed in ``seconds``
        wall, with the window's mean effective batch, worst TTFT proxy,
        and how many of the completed requests met the SLO."""
        with self._lock:
            self._requests_total += int(requests)
            if requests > 0 and seconds > 0:
                self._window_rate = requests / seconds
            if batch_mean is not None:
                self._batch_mean = float(batch_mean)
            if ttft_worst_s is not None:
                self._ttft_s = float(ttft_worst_s)
            if (
                slo_met is not None
                and requests > 0
                and self._slo_threshold_s is not None
            ):
                self._slo_ratio = min(1.0, max(0.0, slo_met / requests))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_total": self._requests_total,
                "requests_per_second": self._window_rate,
                "queue_depth": self._queue_depth,
                "batch_size": self._batch_mean,
                "ttft_seconds": self._ttft_s,
                "slo_attainment_ratio": self._slo_ratio,
                "slo_threshold_seconds": self._slo_threshold_s,
            }


def serve_families(stats: ServeStats):
    """Prometheus families for the harness /metrics endpoint. One
    snapshot serves the whole scrape (coherent rate/queue/ttft/slo)."""
    from prometheus_client.core import (
        CounterMetricFamily,
        GaugeMetricFamily,
    )

    snap = stats.snapshot()

    total = CounterMetricFamily(
        "tpu_serve_requests_total",
        "Inference requests completed by the serving loop since start.",
    )
    total.add_metric((), snap["requests_total"])
    yield total

    depth = GaugeMetricFamily(
        "tpu_serve_queue_depth",
        "Requests admitted but not yet completed (instantaneous) — the "
        "scale-out pressure signal the actuation tier exports to HPAs.",
    )
    depth.add_metric((), snap["queue_depth"])
    yield depth

    if snap["requests_per_second"] is not None:
        rate = GaugeMetricFamily(
            "tpu_serve_requests_per_second",
            "Completed requests per second over the most recent stats "
            "window.",
        )
        rate.add_metric((), snap["requests_per_second"])
        yield rate

    if snap["batch_size"] is not None:
        batch = GaugeMetricFamily(
            "tpu_serve_batch_size",
            "Mean effective batch size over the most recent window.",
        )
        batch.add_metric((), snap["batch_size"])
        yield batch

    if snap["ttft_seconds"] is not None:
        ttft = GaugeMetricFamily(
            "tpu_serve_ttft_seconds",
            "Time-to-first-token proxy over the most recent window: "
            "queue wait plus one decode-step latency for newly "
            "admitted requests.",
        )
        ttft.add_metric((), snap["ttft_seconds"])
        yield ttft

    if snap["slo_attainment_ratio"] is not None:
        slo = GaugeMetricFamily(
            "tpu_serve_slo_attainment_ratio",
            "Fraction of requests whose TTFT proxy met the configured "
            "SLO over the most recent window — goodput under SLO.",
        )
        slo.add_metric((), snap["slo_attainment_ratio"])
        yield slo

    if snap["slo_threshold_seconds"] is not None:
        thr = GaugeMetricFamily(
            "tpu_serve_slo_threshold_seconds",
            "The configured TTFT SLO threshold the attainment ratio is "
            "measured against (constant per run).",
        )
        thr.add_metric((), snap["slo_threshold_seconds"])
        yield thr


class ServeCollector:
    """Registry adapter: ``registry.register(ServeCollector(stats))``."""

    def __init__(self, stats: ServeStats) -> None:
        self._stats = stats

    def collect(self):
        return serve_families(self._stats)
