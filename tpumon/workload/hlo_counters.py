"""In-process XLA collective-op counters via the libtpu HLO logger
(SURVEY.md §3.5; BASELINE config 4 'XLA collective-op counters').

``libtpu.sdk.logger.register_hlo_logger(cb)`` (signature probed live on
libtpu 0.0.34) delivers HLO log events to the *workload* process — these
counters therefore live workload-side; the node exporter observes the
fabric from outside via ``collective_e2e_latency``/``ici_link_health``.
The harness can expose them on its own /metrics port so Prometheus sees
both views of the same traffic.

The event payload format is undocumented, so extraction is defensive:
stringify everything, regex for collective-op names, never raise from the
callback (it runs inside the runtime).
"""

from __future__ import annotations

import logging
import re
import threading
from collections import Counter

log = logging.getLogger(__name__)

#: XLA collective HLO op names worth counting (ICI traffic generators).
COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute"
    r"|collective-broadcast|send|recv)\b"
)


class HloOpCounters:
    """Counts collective-op mentions in HLO logger events. Thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Counter[str] = Counter()
        self._events = 0
        self._ids = None

    # -- registration ------------------------------------------------------

    def start(self) -> bool:
        """Register with the libtpu HLO logger; False if unavailable."""
        try:
            from libtpu.sdk import logger as tpu_logger

            self._ids = tpu_logger.register_hlo_logger(self._callback)
            return True
        except Exception as exc:
            log.debug("HLO logger unavailable: %s", exc)
            return False

    def stop(self) -> None:
        if self._ids is None:
            return
        try:
            from libtpu.sdk import logger as tpu_logger

            tpu_logger.unregister_hlo_logger(self._ids)
        except Exception as exc:
            log.debug("HLO logger unregister failed: %s", exc)
        self._ids = None

    def __enter__(self) -> "HloOpCounters":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- event path --------------------------------------------------------

    def _callback(self, *args, **kwargs) -> None:
        # Runs inside the TPU runtime: must never raise.
        try:
            text = " ".join(str(a) for a in args)
            if kwargs:
                text += " " + " ".join(f"{k}={v}" for k, v in kwargs.items())
            self.observe(text)
        except Exception:
            pass

    def observe(self, text: str) -> None:
        """Count collective mentions in one event (public for tests)."""
        ops = COLLECTIVE_RE.findall(text.lower())
        with self._lock:
            self._events += 1
            for op in ops:
                self._counts[op] += 1

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> tuple[dict[str, int], int]:
        with self._lock:
            return dict(self._counts), self._events


def counters_families(counters: HloOpCounters):
    """Prometheus families for a workload-side /metrics endpoint."""
    from prometheus_client.core import CounterMetricFamily

    counts, events = counters.snapshot()
    fam = CounterMetricFamily(
        "workload_collective_ops_total",
        "XLA collective HLO ops observed by the in-process libtpu HLO "
        "logger, by op.",
        labels=("op",),
    )
    for op, n in sorted(counts.items()):
        fam.add_metric((op,), n)
    yield fam

    ev = CounterMetricFamily(
        "workload_hlo_log_events_total",
        "Total HLO logger events received in-process.",
    )
    ev.add_metric((), events)
    yield ev


class CountersCollector:
    """Registry adapter: ``registry.register(CountersCollector(c))``."""

    def __init__(self, counters: HloOpCounters) -> None:
        self._counters = counters

    def collect(self):
        return counters_families(self._counters)
