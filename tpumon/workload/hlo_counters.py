"""In-process XLA collective-op counters via the libtpu HLO logger
(SURVEY.md §3.5; BASELINE config 4 'XLA collective-op counters').

``libtpu.sdk.logger.register_hlo_logger(cb)`` (signature probed live on
libtpu 0.0.34) delivers HLO log events to the *workload* process — these
counters therefore live workload-side; the node exporter observes the
fabric from outside via ``collective_e2e_latency``/``ici_link_health``.
The harness can expose them on its own /metrics port so Prometheus sees
both views of the same traffic.

The event payload format is undocumented, so extraction is defensive:
stringify everything, regex for collective-op names plus per-op
latency/bytes figures when the payload carries them (duration_us=…,
took 3 ms, bytes_accessed=…, 2KiB, …), never raise from the callback
(it runs inside the runtime). Timing/size extraction makes the
workload-side view quantitatively correlatable with the exporter's
``accelerator_collective_latency_microseconds`` (BASELINE config 4
pairs link bandwidth with these counters): both describe the same
fabric traffic, one from inside the process, one from the node.

Live capture attempt (2026-07-31, ``harness --hlo-raw-dump`` training
on this host's real TPU v5 lite): registration succeeds but **zero
events are delivered** — on a dev host whose chip is reached through
the axon dispatch tunnel, the runtime (and its logger) lives off-host,
exactly like ``tpumonitoring.get_metric(...).data() == []`` on the same
host (BASELINE.md config 4 note). The regex fixtures in
``tests/test_hlo_counters.py`` therefore remain the spec for the
payload shapes until a run on a GKE TPU VM (runtime on-host) can dump
real payloads via ``--hlo-raw-dump``.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from collections import Counter

log = logging.getLogger(__name__)

#: XLA collective HLO op names worth counting (ICI traffic generators).
COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute"
    r"|collective-broadcast|send|recv)\b"
)

#: Duration figures in event text, any of the spellings observed across
#: XLA/runtime log genres. Two shapes — unit after the value
#: (``took 3 ms``, ``latency: 250ns``) and unit embedded in the key
#: (``duration_us=12.5``, ``time_ns: 40``). Unit is required either way —
#: a bare number after "time" is as likely a timestamp as a duration.
_DURATION_VALUNIT_RE = re.compile(
    r"\b(?:duration|latency|elapsed|took|time)[_\s:=]*?[\s:=]"
    r"(\d+(?:\.\d+)?)\s*(ns|us|µs|usec|microseconds?|ms|msec|"
    r"milliseconds?|s|sec|seconds?)\b",
    re.IGNORECASE,
)

_DURATION_KEYUNIT_RE = re.compile(
    r"\b(?:duration|latency|elapsed|time)_(ns|us|usec|ms|msec|s|sec)"
    r"\s*[:=]\s*(\d+(?:\.\d+)?)",
    re.IGNORECASE,
)

_DURATION_US = {
    "ns": 1e-3,
    "us": 1.0, "µs": 1.0, "usec": 1.0, "microsecond": 1.0,
    "microseconds": 1.0,
    "ms": 1e3, "msec": 1e3, "millisecond": 1e3, "milliseconds": 1e3,
    "s": 1e6, "sec": 1e6, "second": 1e6, "seconds": 1e6,
}

#: Byte figures: ``bytes_accessed=4096``, ``size: 2KiB``, ``payload=1MB``.
#: The unit suffix is optional (default: bytes).
_BYTES_RE = re.compile(
    r"(?:bytes(?:_accessed|_transferred|_sent|_received)?|"
    r"size(?:_bytes|_in_bytes)?|payload)[_\s:=]*"
    r"(\d+(?:\.\d+)?)\s*(kib|kb|mib|mb|gib|gb|b)?\b",
    re.IGNORECASE,
)

_BYTES_MULT = {
    None: 1.0, "": 1.0, "b": 1.0,
    "kb": 1e3, "kib": 1024.0,
    "mb": 1e6, "mib": 1024.0**2,
    "gb": 1e9, "gib": 1024.0**3,
}


class HloOpCounters:
    """Counts collective-op mentions in HLO logger events. Thread-safe.

    ``raw_path`` dumps each event's stringified text (exactly what
    :meth:`observe` parses) as one JSON string per line, capped at
    ``raw_limit`` events — the capture mode that turns a real runtime's
    undocumented payloads into a pinned test fixture
    (tests/fixtures/hlo_logger_*.jsonl).
    """

    def __init__(self, raw_path: str | None = None, raw_limit: int = 4096) -> None:
        self._lock = threading.Lock()
        self._counts: Counter[str] = Counter()
        # Per-op extracted figures (absent until an event carries one):
        # summed latency (µs) + how many events contributed (the honest
        # denominator for averages — most events carry no timing), and
        # summed bytes likewise.
        self._latency_us: Counter[str] = Counter()
        self._latency_samples: Counter[str] = Counter()
        self._bytes: Counter[str] = Counter()
        self._bytes_samples: Counter[str] = Counter()
        self._events = 0
        self._ids = None
        self._raw_path = raw_path
        self._raw_limit = raw_limit
        self._raw_file = None
        self._raw_count = 0

    # -- registration ------------------------------------------------------

    def start(self) -> bool:
        """Register with the libtpu HLO logger; False if unavailable."""
        try:
            from libtpu.sdk import logger as tpu_logger

            self._ids = tpu_logger.register_hlo_logger(self._callback)
            return True
        except Exception as exc:
            log.debug("HLO logger unavailable: %s", exc)
            return False

    def stop(self) -> None:
        # Disable capture BEFORE closing: a late in-flight callback must
        # not reopen the file through _dump_raw after we close it.
        with self._lock:
            self._raw_path = None
            if self._raw_file is not None:
                try:
                    self._raw_file.close()
                except OSError:
                    pass
                self._raw_file = None
        if self._ids is None:
            return
        try:
            from libtpu.sdk import logger as tpu_logger

            tpu_logger.unregister_hlo_logger(self._ids)
        except Exception as exc:
            log.debug("HLO logger unregister failed: %s", exc)
        self._ids = None

    def __enter__(self) -> "HloOpCounters":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- event path --------------------------------------------------------

    def _callback(self, *args, **kwargs) -> None:
        # Runs inside the TPU runtime: must never raise.
        try:
            text = " ".join(str(a) for a in args)
            if kwargs:
                text += " " + " ".join(f"{k}={v}" for k, v in kwargs.items())
            if self._raw_path is not None:
                # Guarded separately: a broken capture path (unwritable
                # file) must not silently disable the counting below.
                try:
                    self._dump_raw(text)
                except OSError as exc:
                    log.warning("HLO raw capture disabled: %s", exc)
                    self._raw_path = None
            self.observe(text)
        except Exception:
            pass

    def _dump_raw(self, text: str) -> None:
        """Write one JSON-encoded event line to the capture file
        (truncated on this instance's first write — a fixture must not
        mix events from different runs)."""
        with self._lock:
            # Recheck under the lock: stop() may have disabled capture
            # between the callback's unlocked check and here.
            if self._raw_path is None or self._raw_count >= self._raw_limit:
                return
            if self._raw_file is None:
                self._raw_file = open(self._raw_path, "w")
            self._raw_file.write(json.dumps(text) + "\n")
            self._raw_file.flush()
            self._raw_count += 1

    def observe(self, text: str) -> None:
        """Count collective mentions in one event (public for tests);
        extract per-op latency/bytes when the payload carries them.

        A single event's figures are attributed to its FIRST collective
        mention: an event naming several ops (a fusion log line) has no
        per-op breakdown to honor, and attributing one duration to every
        mentioned op would multiply the measured time.
        """
        lower = text.lower()
        ops = COLLECTIVE_RE.findall(lower)
        dur_us = 0.0
        n_dur = 0
        nbytes = 0.0
        n_bytes = 0
        if ops:
            for value, unit in _DURATION_VALUNIT_RE.findall(lower):
                dur_us += float(value) * _DURATION_US[unit]
                n_dur += 1
            for unit, value in _DURATION_KEYUNIT_RE.findall(lower):
                dur_us += float(value) * _DURATION_US[unit]
                n_dur += 1
            for value, unit in _BYTES_RE.findall(lower):
                nbytes += float(value) * _BYTES_MULT[unit or None]
                n_bytes += 1
        with self._lock:
            self._events += 1
            for op in ops:
                self._counts[op] += 1
            if ops and n_dur:
                self._latency_us[ops[0]] += dur_us
                self._latency_samples[ops[0]] += 1
            if ops and n_bytes:
                self._bytes[ops[0]] += nbytes
                self._bytes_samples[ops[0]] += 1

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> tuple[dict[str, int], int]:
        with self._lock:
            return dict(self._counts), self._events

    def detailed_snapshot(self) -> dict:
        """Counts plus the extracted per-op latency/bytes aggregates."""
        with self._lock:
            return {
                "counts": dict(self._counts),
                "events": self._events,
                "latency_us": dict(self._latency_us),
                "latency_samples": dict(self._latency_samples),
                "bytes": dict(self._bytes),
                "bytes_samples": dict(self._bytes_samples),
            }


def counters_families(counters: HloOpCounters):
    """Prometheus families for a workload-side /metrics endpoint.

    One snapshot serves the whole scrape: counts and latency figures
    taken under separate lock acquisitions could disagree (a scrape
    showing more latency samples than op counts breaks avg queries).
    """
    from prometheus_client.core import CounterMetricFamily

    detail = counters.detailed_snapshot()
    counts, events = detail["counts"], detail["events"]
    fam = CounterMetricFamily(
        "workload_collective_ops_total",
        "XLA collective HLO ops observed by the in-process libtpu HLO "
        "logger, by op.",
        labels=("op",),
    )
    for op, n in sorted(counts.items()):
        fam.add_metric((op,), n)
    yield fam

    ev = CounterMetricFamily(
        "workload_hlo_log_events_total",
        "Total HLO logger events received in-process.",
    )
    ev.add_metric((), events)
    yield ev

    if detail["latency_us"]:
        lat = CounterMetricFamily(
            "workload_collective_op_latency_microseconds_total",
            "Summed per-op latency extracted from HLO logger events "
            "(absent until an event carries a duration figure; correlate "
            "with accelerator_collective_latency_microseconds).",
            labels=("op",),
        )
        samples = CounterMetricFamily(
            "workload_collective_op_latency_samples_total",
            "Events that carried a duration figure, by op — the honest "
            "denominator for average-latency queries.",
            labels=("op",),
        )
        for op, us in sorted(detail["latency_us"].items()):
            lat.add_metric((op,), us)
            samples.add_metric((op,), detail["latency_samples"][op])
        yield lat
        yield samples
    if detail["bytes"]:
        by = CounterMetricFamily(
            "workload_collective_op_bytes_total",
            "Summed per-op payload bytes extracted from HLO logger "
            "events (absent until an event carries a size figure).",
            labels=("op",),
        )
        for op, n in sorted(detail["bytes"].items()):
            by.add_metric((op,), n)
        yield by


class CountersCollector:
    """Registry adapter: ``registry.register(CountersCollector(c))``."""

    def __init__(self, counters: HloOpCounters) -> None:
        self._counters = counters

    def collect(self):
        return counters_families(self._counters)
