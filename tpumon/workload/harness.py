"""Training harness: the ICI-traffic generator (SURVEY.md §3.5).

One jitted SPMD train step (next-token cross-entropy + Adam) over a dp×tp
mesh. Run it while the exporter polls from another process and the
collective / duty-cycle / HBM families go non-empty — the process boundary
is the point: the monitor must see traffic it did not generate.

CLI:  python -m tpumon.workload.harness --steps 20 --dp 1 --tp 1
      (add --metrics-port to expose in-process collective-op counters)
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpumon.workload.models.llama import LlamaConfig, forward, init_params
from tpumon.workload.models.moe import MoeConfig
from tpumon.workload.models.moe import forward as moe_forward
from tpumon.workload.models.moe import init_params as moe_init_params
from tpumon.workload.parallel.mesh import (
    batch_spec,
    make_act_sharder,
    make_expert_sharder,
    make_mesh,
    moe_param_specs,
    param_specs,
    shard_tree,
)
from tpumon.workload.parallel.pipeline import (
    make_pipelined_forward,
    moe_pipeline_param_specs,
    pipeline_param_specs,
)
from tpumon.workload.parallel.ring import make_ring_attn

log = logging.getLogger(__name__)


AUX_LOSS_WEIGHT = 0.01  # GShard load-balancing loss weight (MoE only)


def _chunked_nll(x, unembed_w, targets, chunk, dtype):
    """Mean next-token NLL with the unembed fused into the loss, one
    sequence chunk at a time: x [B,S,D] (final-norm hidden), targets
    [B,S] → scalar f32.

    The full [B, S, vocab] float32 logits tensor — several GB for
    chip-sized presets at long seq — never materializes: each scan
    iteration projects one chunk, reduces it to its NLL sum, and
    ``jax.checkpoint`` makes the backward recompute the chunk's logits
    instead of stashing them, so loss-path memory is O(B·chunk·vocab).
    Mathematically identical to the unchunked loss (same log_softmax per
    token; only the summation order differs).
    """
    B, S, D = x.shape
    n = S // chunk
    xs = x.reshape(B, n, chunk, D).swapaxes(0, 1)  # [n, B, chunk, D]
    ts = targets.reshape(B, n, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def body(total, xt):
        xc, tc = xt
        logits = (xc @ unembed_w.astype(dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tc[..., None], axis=-1)
        return total + jnp.sum(nll), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (xs, ts))
    return total / (B * S)


def loss_fn(
    params,
    tokens,
    cfg,
    attn_impl=None,
    shard_acts=None,
    shard_experts=None,
    forward_fn=None,
    remat=False,
    loss_chunk=0,
):
    """Next-token cross-entropy; inputs [B, S], targets are the shift-by-1.

    Accepts LlamaConfig or MoeConfig; the MoE path adds the weighted
    load-balancing auxiliary loss. ``forward_fn`` overrides the model
    forward entirely (the pipelined-forward path, parallel.pipeline).
    ``remat`` recomputes layer activations in the backward (dense and
    unpipelined-MoE forwards; the pipelined forward takes it itself).
    ``loss_chunk`` (dense model only) fuses the unembed projection into
    the loss in sequence chunks of that many tokens (:func:`_chunked_nll`).
    """
    targets = tokens[:, 1:]
    if forward_fn is not None:
        out = forward_fn(params, tokens[:, :-1])
        # The pipelined MoE forward returns (logits, aux) like the
        # unpipelined MoE model; the dense pipeline returns logits only.
        logits, aux = out if isinstance(out, tuple) else (out, 0.0)
    elif isinstance(cfg, MoeConfig):
        logits, aux = moe_forward(
            params, tokens[:, :-1], cfg, attn_impl, shard_acts,
            shard_experts, remat,
        )
    else:
        if loss_chunk:
            x = forward(
                params, tokens[:, :-1], cfg, attn_impl, shard_acts, remat,
                unembed=False,
            )
            return _chunked_nll(
                x, params["unembed"], targets, loss_chunk, cfg.dtype
            )
        logits = forward(
            params, tokens[:, :-1], cfg, attn_impl, shard_acts, remat
        )
        aux = 0.0
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll) + AUX_LOSS_WEIGHT * aux


def make_train_step(
    cfg,
    optimizer,
    attn_impl=None,
    shard_acts=None,
    shard_experts=None,
    forward_fn=None,
    grad_accum: int = 1,
    remat: bool = False,
    with_grad_norm: bool = False,
    loss_chunk: int = 0,
):
    """One jitted optimizer step; ``grad_accum > 1`` splits the batch
    into that many chunks and accumulates gradients over a ``lax.scan``
    before the single optimizer update — the standard pretrain pattern
    for batch sizes beyond activation memory. Equal chunks mean the
    accumulated mean-of-chunk-gradients equals the full-batch gradient,
    so the math is unchanged; what changes is the *cadence* of the
    gradient collectives the monitor observes (one burst per chunk
    instead of one per step)."""

    def grad_of(params, tokens):
        return jax.value_and_grad(loss_fn)(
            params, tokens, cfg, attn_impl, shard_acts, shard_experts,
            forward_fn, remat, loss_chunk,
        )

    def train_step(params, opt_state, tokens):
        if grad_accum == 1:
            loss, grads = grad_of(params, tokens)
        else:
            B = tokens.shape[0]
            # Strided chunking: chunk a takes rows {a, a+A, a+2A, ...},
            # so every chunk stays balanced across the dp shards (tokens
            # are batch-sharded on axis 0). A contiguous reshape would
            # put chunk 0 entirely on the first shards and force GSPMD
            # to insert reshard traffic real per-shard microbatch
            # loaders never emit.
            chunks = tokens.reshape(
                B // grad_accum, grad_accum, -1
            ).swapaxes(0, 1)

            def acc(carry, chunk):
                loss, grads = grad_of(params, chunk)
                return (
                    jax.tree.map(jnp.add, carry[0], grads),
                    carry[1] + loss,
                ), None

            zero = jax.tree.map(jnp.zeros_like, params)
            (gsum, lsum), _ = jax.lax.scan(acc, (zero, 0.0), chunks)
            grads = jax.tree.map(lambda g: g / grad_accum, gsum)
            loss = lsum / grad_accum
        # Global gradient L2 norm, returned alongside the loss: a second,
        # independent parity signal for the multi-chip dryrun (a sharding
        # bug that barely moves the loss — e.g. one mis-scaled psum —
        # shows up at full strength in the gradients). Opt-in: the
        # whole-tree reduction would tax every benchmark step's HBM
        # bandwidth, so throughput runs return NaN instead.
        gnorm = (
            optax.global_norm(grads) if with_grad_norm
            else jnp.float32(float("nan"))
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, gnorm

    return train_step


@dataclasses.dataclass
class RunResult:
    losses: list[float]
    steps_per_sec: float
    dp: int
    tp: int
    sp: int = 1
    pp: int = 1
    ep: int = 1
    #: First global step this run executed (> 0 after a checkpoint resume).
    start_step: int = 0
    #: Model FLOPs per optimizer step (tpumon.workload.flops accounting).
    model_flops_per_step: float = 0.0
    #: Model FLOPs utilization vs the devices' peak bf16 (SURVEY §6);
    #: None when the device peak is unknown (CPU) or throughput absent.
    mfu: float | None = None
    #: Global gradient L2 norm at the final step (the dryrun's second
    #: dense-parity signal alongside the loss).
    grad_norm: float | None = None


def _make_phase_probe(cfg, optimizer, attn_impl, shard_acts, shard_experts,
                      forward_fn, remat, loss_chunk, grad_accum: int = 1):
    """One instrumented step split into timed fwd / fwd+bwd / optimizer
    phases (``--phase-stats``). Three separately-jitted functions with
    NO donation (the live params/opt state must survive), run at most
    once per stats window: bounded overhead, honest wall timings. bwd is
    the grad pass minus the forward pass — the standard decomposition
    when the train step itself is one fused jit.

    Under ``grad_accum > 1`` the probe times ONE microbatch chunk and
    scales fwd/bwd by the chunk count: the real step never executes a
    full-batch backward (accumulation exists precisely because it would
    not fit activation memory), so probing one would OOM exactly the
    configs that need accumulation — and describe a step shape the run
    never takes."""

    def loss_of(params, tokens):
        return loss_fn(
            params, tokens, cfg, attn_impl, shard_acts, shard_experts,
            forward_fn, remat, loss_chunk,
        )

    fwd_fn = jax.jit(loss_of)
    grad_fn = jax.jit(jax.value_and_grad(loss_of))

    def opt_of(params, opt_state, grads):
        updates, _ = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates)

    opt_fn = jax.jit(opt_of)
    chunks = max(1, int(grad_accum))

    def probe(params, opt_state, tokens) -> dict[str, float]:
        if chunks > 1:
            # Strided rows, mirroring make_train_step's chunking (every
            # chunk stays balanced across the dp shards).
            tokens = tokens[::chunks]
        t0 = time.perf_counter()
        jax.block_until_ready(fwd_fn(params, tokens))
        fwd_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        _, grads = grad_fn(params, tokens)
        jax.block_until_ready(grads)
        grad_s = time.perf_counter() - t0
        # Grads land untimed above; only the update+apply is clocked.
        t0 = time.perf_counter()
        jax.block_until_ready(opt_fn(params, opt_state, grads))
        opt_s = time.perf_counter() - t0
        return {
            # Per-step phase estimate: chunk count × per-chunk time for
            # the accumulated phases; the optimizer runs once per step.
            "fwd": fwd_s * chunks,
            "bwd": max(0.0, grad_s - fwd_s) * chunks,
            "optimizer": opt_s,
        }

    return probe


def _record_serve_window(serve, batch: int, n_steps: int, window_s: float) -> None:
    """One serving window from the traffic generator's shape: every
    sequence in the batch is one request per step, and the window's
    per-step wall time stands in for TTFT (queue wait + one decode
    step). SLO attainment is all-or-nothing per window — the steps in a
    window share one measured latency — which is exactly the granularity
    the scale signal consumes (windowed ratios, not per-request tails)."""
    step_s = window_s / max(n_steps, 1)
    requests = n_steps * batch
    thr = serve.snapshot()["slo_threshold_seconds"]
    serve.set_queue_depth(batch)
    serve.record_window(
        requests=requests,
        seconds=window_s,
        batch_mean=float(batch),
        ttft_worst_s=step_s,
        slo_met=(
            None if thr is None else (requests if step_s <= thr else 0)
        ),
    )


def run(
    cfg,
    *,
    steps: int = 10,
    batch: int = 8,
    seq: int | None = None,
    dp: int = 1,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    ep: int = 1,
    microbatches: int = 2,
    interleave: int = 1,
    sp_layout: str = "contiguous",
    grad_accum: int = 1,
    remat: bool = False,
    with_grad_norm: bool = False,
    loss_chunk: int = 0,
    zero1: bool = False,
    seed: int = 0,
    mesh=None,
    attn: str = "xla",
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    stats=None,
    stats_every: int = 20,
    phase_stats: bool = False,
    collective_us=None,
    serve=None,
) -> RunResult:
    """Build, shard, and run the train step; returns losses + throughput.

    ``cfg`` is a LlamaConfig (dense) or MoeConfig (mixture-of-experts).
    ``sp > 1`` turns on sequence/context parallelism: ring attention over
    the mesh's ``seq`` axis (parallel.ring) plus a persistent
    batch×seq-sharded residual stream. ``ep > 1`` shards MoE expert banks
    over the ``expert`` axis so dispatch/combine become all-to-alls.
    ``attn="flash"`` swaps the attention core for the pallas flash kernel
    (ops.flash_attention); it composes with every axis and layout:
    dp/tp/ep/pp, zigzag sp (the ring runs the kernel per stripe pair —
    parallel.ring.zigzag_ring_flash_local), and contiguous sp (each hop
    is one of three static mask cases — parallel.ring.ring_flash_local);
    inside pipeline stage bodies too. ``pp > 1`` composes with dp/tp/sp —
    under either sp layout: ``sp_layout="zigzag"`` runs the balanced
    zigzag ring inside the pipeline stage bodies too — and with MoE as
    dp×pp×ep×tp (expert banks sharded over expert and Megatron-split
    over model inside stage bodies; sp stays 1 on that path).
    ``interleave > 1`` selects the circular (interleaved) pipeline
    schedule — bubble ÷ interleave (parallel.pipeline). ``remat=True``
    recomputes layer activations in the backward (dense and pipelined
    paths) — O(1)-layers activation memory for ~⅓ extra forward FLOPs.

    ``checkpoint_dir`` turns on orbax checkpoint/resume (SURVEY.md §5.4 —
    the monitor itself is stateless; the *workload* checkpoints so long
    traffic-generation runs survive preemption): the latest step in the
    directory is restored on entry, params+opt state are saved every
    ``checkpoint_every`` steps (0 = only at the end), and a resumed run
    replays the exact losses of an uninterrupted one (same data keyed by
    seed, bitwise-restored state; asserted in tests/test_checkpoint.py).

    ``zero1=True`` shards the optimizer state (Adam moments — two full
    f32 copies of the model) over the ``data`` axis, ZeRO-1 style: each
    dp shard updates 1/dp of the moments and GSPMD all-gathers the
    applied updates (parallel.mesh.zero1_shard_opt_state). Composes
    with every other axis; requires dp > 1.

    ``stats`` (a workload.stats.WorkloadStats) turns on live telemetry
    for the /metrics port: every ``stats_every`` steps the loop blocks on
    the latest loss and records the window's exact steps/s (the dispatch
    pipeline stays full between windows — one sync per window, not per
    step, so the generated traffic keeps its shape).

    ``phase_stats=True`` additionally runs ONE instrumented step per
    stats window (three undonated jitted calls: fwd, fwd+bwd, optimizer)
    and records the per-phase wall times — the ``tpu_step_phase_seconds``
    families the lifecycle plane consumes. ``collective_us`` (a callable
    returning the HLO logger's cumulative collective-latency µs, or
    None) turns on the per-window collective-wait fraction.

    ``serve`` (a workload.serve.ServeStats) reinterprets the loop as an
    inference-shaped traffic generator: each sequence in the batch is
    one request per step, the window's per-step wall time is the TTFT
    proxy (queue wait + one decode step), and SLO attainment is whether
    the proxy met the configured threshold — the ``tpu_serve_*``
    families the actuation tier scales on.
    """
    is_moe = isinstance(cfg, MoeConfig)
    if ep > 1 and not is_moe:
        raise ValueError("ep > 1 requires a MoeConfig")
    if pp > 1 and is_moe and sp > 1:
        # pp×MoE runs dp×pp×ep×tp (expert banks sharded over expert AND
        # Megatron-split over model inside stage bodies); sp stays out —
        # routing's capacity cumsum needs the whole sequence.
        raise ValueError("pp with MoE composes with dp/ep/tp only (sp=1)")
    seq = seq or cfg.max_seq
    if seq > cfg.max_seq:
        # Long-context runs beyond the preset's nominal window: extend the
        # RoPE table to the requested length (positions are computed from
        # max_seq at trace time, so this is exact, not extrapolation).
        cfg = dataclasses.replace(cfg, max_seq=seq)
    key = jax.random.PRNGKey(seed)
    k_params, k_data = jax.random.split(key)

    params = (moe_init_params if is_moe else init_params)(cfg, k_params)
    optimizer = optax.adamw(1e-3)
    tokens = jax.random.randint(k_data, (batch, seq + 1), 0, cfg.vocab, jnp.int32)

    if mesh is None and dp * tp * sp * pp * ep > 1:
        mesh = make_mesh(dp, tp, sp, pp, ep)

    attn_impl = shard_acts = shard_experts = forward_fn = None
    if attn == "flash":
        if sp == 1 and pp == 1:
            # Under pp the pipelined forward builds its own kernel impl;
            # under sp the ring construction below owns it (flash=True).
            from tpumon.workload.ops.flash_attention import make_flash_attn

            attn_impl = make_flash_attn()
    elif attn != "xla":
        raise ValueError(f"unknown attn impl: {attn!r}")
    if sp > 1:
        if mesh is None:
            raise ValueError("sp > 1 requires a mesh")
        if seq % sp:
            raise ValueError(f"seq ({seq}) must divide by sp ({sp})")
        if sp_layout not in ("contiguous", "zigzag"):
            raise ValueError(f"unknown sp_layout: {sp_layout!r}")
        if sp_layout == "zigzag" and seq % (2 * sp):
            raise ValueError(
                f"zigzag needs an even local shard: seq ({seq}) must "
                f"divide by 2*sp ({2 * sp})"
            )
        if pp == 1:
            # Under pp the pipelined forward owns the attention impl AND
            # the activation layout (its shard_map specs), so both stay
            # unset on that path — attn/sp_layout are passed through to
            # make_pipelined_forward instead.
            attn_impl = make_ring_attn(
                mesh,
                head_axis="model" if tp > 1 else None,
                zigzag=sp_layout == "zigzag",
                flash=attn == "flash",
            )
            shard_acts = make_act_sharder(mesh, sp=True)
    if is_moe and mesh is not None and pp == 1:
        # Under pp the pipelined forward owns expert sharding (manual
        # collectives in the stage bodies); these GSPMD constraints are
        # for the unpipelined MoE path only.
        shard_experts = make_expert_sharder(mesh)
        if shard_acts is None:
            shard_acts = make_act_sharder(mesh)
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    if grad_accum > 1:
        if pp > 1:
            # The pipelined forward already microbatches inside its
            # schedule; stacking a second accumulation loop on top would
            # obscure which knob produced which traffic.
            raise ValueError("grad_accum composes with dp/tp/sp/ep, not pp")
        per_shard = batch // max(dp, 1)
        if per_shard % grad_accum:
            raise ValueError(
                f"per-data-shard batch ({per_shard}) must divide by "
                f"grad_accum ({grad_accum})"
            )
    if loss_chunk:
        if loss_chunk < 1:
            raise ValueError(f"loss_chunk must be >= 1, got {loss_chunk}")
        if is_moe or pp > 1 or sp > 1:
            raise ValueError(
                "loss_chunk fuses the dense model's unembed into the "
                "loss; it composes with dp/tp (not MoE, pp, or sp — the "
                "seq-chunk reshape would fight the seq sharding)"
            )
        if seq % loss_chunk:
            raise ValueError(
                f"seq ({seq}) must divide by loss_chunk ({loss_chunk})"
            )
    if pp > 1:
        forward_fn = make_pipelined_forward(
            mesh, cfg, microbatches=microbatches, interleave=interleave,
            sp_layout=sp_layout, remat=remat, attn=attn,
        )
    train_step = make_train_step(
        cfg, optimizer, attn_impl, shard_acts, shard_experts, forward_fn,
        grad_accum=grad_accum, remat=remat and pp == 1,
        with_grad_norm=with_grad_norm, loss_chunk=loss_chunk,
    )

    if mesh is not None:
        # Shard params FIRST; optimizer.init on sharded params then makes the
        # Adam moments inherit the same layout (no replicated moment memory).
        if pp > 1:
            specs = (
                moe_pipeline_param_specs() if is_moe
                else pipeline_param_specs()
            )
        elif is_moe:
            specs = moe_param_specs()
        else:
            specs = param_specs()
        params = shard_tree(params, specs, mesh)
        tokens = shard_tree(tokens, batch_spec(), mesh)
    opt_state = optimizer.init(params)
    out_shardings = None
    if zero1:
        if mesh is None or dp < 2:
            raise ValueError("zero1 shards optimizer state over dp; it "
                             "needs a mesh with dp > 1")
        from tpumon.workload.parallel.mesh import zero1_shard_opt_state

        opt_state, opt_shardings = zero1_shard_opt_state(opt_state, mesh)
        # Pin BOTH state outputs to their input layouts. The opt state
        # keeps the ZeRO layout across the donate round-trip; the params
        # must be pinned too because with dp-sharded updates GSPMD would
        # otherwise infer a data-sharded params output — a layout drift
        # that made a checkpoint-resumed step (params restored to the
        # replicated template layout) compile a different executable
        # than the live step and diverge from the exact-replay invariant
        # (observed: 1e-4 loss drift at dp=2×tp=2; exact after pinning).
        param_shardings = jax.tree.map(lambda x: x.sharding, params)
        out_shardings = (param_shardings, opt_shardings, None, None)
    step = jax.jit(
        train_step, donate_argnums=(0, 1), out_shardings=out_shardings
    )

    from tpumon.workload import flops as flops_mod

    run_devices = list(mesh.devices.flat) if mesh is not None else [
        jax.devices()[0]
    ]
    if stats is not None:
        peaks = [flops_mod.peak_flops_per_chip(d) for d in run_devices]
        stats.configure(
            flops_per_step=flops_mod.train_flops_per_step(cfg, batch, seq),
            tokens_per_step=batch * seq,
            peak_flops_total=(
                sum(peaks) if peaks and all(p is not None for p in peaks)
                else None
            ),
            axes={"dp": dp, "tp": tp, "sp": sp, "pp": pp, "ep": ep},
        )

    phase_probe = None
    if stats is not None and phase_stats:
        phase_probe = _make_phase_probe(
            cfg, optimizer, attn_impl, shard_acts, shard_experts,
            forward_fn, remat and pp == 1, loss_chunk,
            grad_accum=grad_accum,
        )

    def _record_window_extras(window_s: float, state: list) -> None:
        # Collective-wait fraction: HLO-logger latency accumulated this
        # window over the window's wall time across the run's devices.
        if collective_us is None:
            return
        try:
            cur = collective_us()
        except Exception:
            log.debug("collective_us probe failed", exc_info=True)
            return
        if cur is None:
            return
        if state and window_s > 0:
            delta = max(0.0, cur - state[0])
            stats.record_collective_wait(
                (delta / 1e6) / (window_s * max(1, len(run_devices)))
            )
        state[:] = [cur]  # window_s <= 0 seeds the µs baseline only

    if serve is not None and checkpoint_dir is not None:
        # The checkpointed loop records per step by design; the serving
        # window shape below assumes the windowed loop.
        raise ValueError("serve telemetry composes with the windowed "
                         "loop, not --checkpoint-dir")
    if checkpoint_dir is not None:
        return _run_checkpointed(
            step, params, opt_state, tokens, steps, checkpoint_dir,
            checkpoint_every, mesh, cfg=cfg, batch=batch, seq=seq,
            stats=stats, phase_probe=phase_probe,
            dp=dp, tp=tp, sp=sp, pp=pp, ep=ep,
        )

    # Warmup/compile outside the timed window.
    params, opt_state, loss, gnorm = step(params, opt_state, tokens)
    loss.block_until_ready()
    losses = [float(loss)]

    t0 = time.perf_counter()
    if stats is None:
        for _ in range(steps):
            params, opt_state, loss, gnorm = step(params, opt_state, tokens)
    else:
        window_t0, done = t0, 0
        wait_state: list[float] = []
        _record_window_extras(0.0, wait_state)  # seed the µs baseline
        for i in range(1, steps + 1):
            params, opt_state, loss, gnorm = step(params, opt_state, tokens)
            if i % max(stats_every, 1) == 0 or i == steps:
                lv = float(loss)  # one host-read sync per window
                now = time.perf_counter()
                stats.record(lv, i - done, now - window_t0)
                if serve is not None:
                    _record_serve_window(
                        serve, batch, i - done, now - window_t0
                    )
                _record_window_extras(now - window_t0, wait_state)
                if phase_probe is not None:
                    try:
                        stats.record_phases(
                            phase_probe(params, opt_state, tokens)
                        )
                    except Exception:
                        # Telemetry must never kill the traffic generator.
                        log.exception("phase probe failed")
                        phase_probe = None
                    # Re-seed the µs baseline AFTER the probe: its own
                    # collectives must not land in the next window's
                    # numerator while its wall time is excluded from
                    # the denominator (a systematic over-read).
                    _record_window_extras(0.0, wait_state)
                window_t0, done = time.perf_counter(), i
    # The barrier is a host read, not block_until_ready: on remote-
    # dispatch transports (axon tunnel) block_until_ready can resolve
    # ~5% before execution completes (measured); float() cannot.
    final_loss = float(loss)
    elapsed = time.perf_counter() - t0
    losses.append(final_loss)
    steps_per_sec = steps / elapsed if elapsed > 0 else float("inf")
    return RunResult(
        losses=losses,
        steps_per_sec=steps_per_sec,
        dp=dp,
        tp=tp,
        sp=sp,
        pp=pp,
        ep=ep,
        model_flops_per_step=flops_mod.train_flops_per_step(cfg, batch, seq),
        mfu=flops_mod.mfu(cfg, batch, seq, steps_per_sec, run_devices),
        # After the loss sync — no extra stall; NaN (norm not requested)
        # maps to None.
        grad_norm=(float(gnorm) if with_grad_norm else None),
    )


def _run_checkpointed(
    step, params, opt_state, tokens, steps, checkpoint_dir, checkpoint_every,
    mesh=None, cfg=None, batch=0, seq=0, stats=None, phase_probe=None,
    **axes,
) -> RunResult:
    """Checkpoint/resume driver around the jitted train step.

    Separate from the fast path on purpose: it records a loss per step
    (host sync each iteration) and touches disk, so the pure
    traffic-generator loop keeps its pipelined, sync-free timing.
    Restore uses the freshly initialized (and mesh-sharded) train state as
    the template, so restored arrays inherit the correct shardings on any
    dp/tp/sp/pp/ep mesh.
    """
    import os

    import orbax.checkpoint as ocp

    mngr = ocp.CheckpointManager(
        os.path.abspath(checkpoint_dir),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=2, enable_async_checkpointing=False
        ),
    )
    try:
        start_step = 0
        latest = mngr.latest_step()
        if latest is not None:
            restore_t0 = time.perf_counter()
            restored = mngr.restore(
                latest,
                args=ocp.args.StandardRestore(
                    {"params": params, "opt_state": opt_state}
                ),
            )
            if mesh is not None:
                # Orbax commits restored arrays to their template's devices.
                # Template scalars (Adam step count) were uncommitted
                # single-device arrays — promote them to mesh-replicated so
                # they are compatible with the mesh-sharded params in one
                # jitted computation.
                from jax.sharding import (
                    NamedSharding,
                    PartitionSpec,
                    SingleDeviceSharding,
                )

                replicated = NamedSharding(mesh, PartitionSpec())
                restored = jax.tree.map(
                    lambda x: jax.device_put(x, replicated)
                    if isinstance(x.sharding, SingleDeviceSharding)
                    else x,
                    restored,
                )
            params, opt_state = restored["params"], restored["opt_state"]
            start_step = latest
            if stats is not None:
                # The restore span + training-global step offset the
                # lifecycle plane reads (tpu_step_checkpoint_seconds
                # {op="restore"} is the restore-storm signature).
                stats.record_checkpoint(
                    "restore", time.perf_counter() - restore_t0
                )
                stats.set_start_step(start_step)
            log.info("resumed from %s at step %d", checkpoint_dir, latest)

        losses: list[float] = []
        timed = 0.0
        timed_steps = 0
        saved_at = start_step if latest is not None else -1
        gnorm = None
        for i in range(start_step, steps):
            t0 = time.perf_counter()
            params, opt_state, loss, gnorm = step(params, opt_state, tokens)
            losses.append(float(loss))  # blocks; keeps loss-per-step record
            dt = time.perf_counter() - t0
            if i > start_step:  # first iteration pays compile
                timed += dt
                timed_steps += 1
                if stats is not None:
                    # This path already syncs per step; record each as a
                    # window (compile-paying first iteration excluded, same
                    # as the `timed` accounting — a ~60s compile would
                    # otherwise publish a near-zero steps/s and MFU).
                    stats.record(losses[-1], 1, dt)
            elif stats is not None:
                # The compile-paying step still HAPPENED: it advances the
                # global step counter (seconds=0 → no rate sample), or
                # tpu_step_counter would sit one behind the checkpoint's
                # own step index after every resume.
                stats.record(losses[-1], 1, 0.0)
            done = i + 1
            if (checkpoint_every and done % checkpoint_every == 0) or done == steps:
                if done != saved_at:
                    save_t0 = time.perf_counter()
                    mngr.save(
                        done,
                        args=ocp.args.StandardSave(
                            {"params": params, "opt_state": opt_state}
                        ),
                    )
                    saved_at = done
                    if stats is not None:
                        stats.record_checkpoint(
                            "save", time.perf_counter() - save_t0
                        )
            if stats is not None and phase_probe is not None and done == steps:
                # One instrumented step at the end of the run (this path
                # already syncs per step, so once is the honest budget).
                try:
                    stats.record_phases(
                        phase_probe(params, opt_state, tokens)
                    )
                except Exception:
                    log.exception("phase probe failed")
                    phase_probe = None
        mngr.wait_until_finished()
        if not losses:
            log.info(
                "checkpoint at %s already covers %d steps; nothing to run",
                checkpoint_dir,
                steps,
            )
        from tpumon.workload import flops as flops_mod

        steps_per_sec = timed_steps / timed if timed > 0 else 0.0
        run_devices = list(mesh.devices.flat) if mesh is not None else [
            jax.devices()[0]
        ]
        return RunResult(
            losses=losses,
            # 0.0 (not inf) when no step ran outside the compile window —
            # consumers treat it as "no throughput measured".
            steps_per_sec=steps_per_sec,
            start_step=start_step,
            model_flops_per_step=(
                flops_mod.train_flops_per_step(cfg, batch, seq) if cfg else 0.0
            ),
            mfu=(
                flops_mod.mfu(cfg, batch, seq, steps_per_sec, run_devices)
                if cfg
                else None
            ),
            # NaN = norm not requested (make_train_step's opt-in).
            grad_norm=(
                float(gnorm)
                if gnorm is not None and float(gnorm) == float(gnorm)
                else None
            ),
            **axes,
        )
    finally:
        mngr.close()


def _install_sigterm_marker(stats, grace_s: float | None = None) -> None:
    """Flag a SIGTERM on the metrics page for the preemption grace
    window, then exit with the conventional 143.

    Kubernetes preemption is SIGTERM → grace period → SIGKILL; the
    lifecycle plane (tpumon/lifecycle) probes the workload page at poll
    cadence and needs to SEE ``tpu_step_terminating 1`` inside that
    window to classify the event as a clean preemption instead of an
    anonymous duty collapse. The handler marks the page immediately and
    defers the exit by TPUMON_STEP_TERM_GRACE_S (default 5 s, clamped
    ≥0) — well inside any real grace period, long enough for a 1 Hz
    prober to observe the flag. A second SIGTERM exits immediately.
    """
    import signal
    import threading

    if grace_s is None:
        raw = os.environ.get("TPUMON_STEP_TERM_GRACE_S", "5")
        try:
            grace_s = max(0.0, float(raw))
        except ValueError:
            grace_s = 5.0

    state = {"seen": False}

    def _on_term(signum, frame):
        if state["seen"]:
            os._exit(143)
        state["seen"] = True
        stats.mark_terminating()
        timer = threading.Timer(grace_s, lambda: os._exit(143))
        timer.daemon = True  # a finished run must not wait on the timer
        timer.start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        # Not the main thread (embedders driving main() from a worker):
        # the flag can still be set by the embedder; skip the handler.
        log.debug("SIGTERM marker not installed (not main thread)")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpumon-workload")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=None)
    parser.add_argument(
        "--preset",
        choices=("tiny", "small", "medium", "llama3-8b"),
        default="tiny",
        help="model size: tiny/small for dev hosts; medium (~0.67B) fills "
        "a single 16 GB chip at seq 4096 (pair with --attn flash and "
        "--grad-accum); llama3-8b is the BASELINE config-4 pretrain "
        "shape (needs a real pod + a mesh, e.g. --dp 4 --tp 8 --sp 2 "
        "on v5p-64)",
    )
    parser.add_argument(
        "--model",
        choices=("llama", "moe"),
        default="llama",
        help="dense Llama-style decoder or mixture-of-experts (EP-capable)",
    )
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument(
        "--sp",
        type=int,
        default=1,
        help="sequence/context parallelism: ring attention over this many "
        "devices on the mesh's seq axis",
    )
    parser.add_argument(
        "--sp-layout",
        choices=("contiguous", "zigzag"),
        default="contiguous",
        help="sequence-shard layout for ring attention: zigzag balances "
        "the causal workload and halves attention FLOPs "
        "(parallel.ring.zigzag_ring_attention_local)",
    )
    parser.add_argument(
        "--pp",
        type=int,
        default=1,
        help="pipeline parallelism: GPipe stages over the mesh's stage axis",
    )
    parser.add_argument(
        "--microbatches",
        type=int,
        default=2,
        help="microbatches per step on the pipeline-parallel path",
    )
    parser.add_argument(
        "--grad-accum",
        type=int,
        default=1,
        help="gradient-accumulation chunks per optimizer step (composes "
        "with dp/tp/sp/ep; pp has its own microbatching)",
    )
    parser.add_argument(
        "--remat",
        action="store_true",
        help="recompute layer activations in the backward pass "
        "(jax.checkpoint): activation memory O(1) layers for ~1/3 extra "
        "forward FLOPs — lets chip-sized presets train at long seq",
    )
    parser.add_argument(
        "--zero1",
        action="store_true",
        help="ZeRO-1: shard the optimizer state (Adam moments) over the "
        "dp axis — each data shard keeps and updates 1/dp of the "
        "moments, GSPMD all-gathers the applied updates. Cuts the "
        "8 bytes/param moment memory to 8/dp; requires --dp > 1",
    )
    parser.add_argument(
        "--loss-chunk",
        type=int,
        default=0,
        help="fuse the unembed projection into the loss in sequence "
        "chunks of this many tokens (0 = off): the [B,S,vocab] f32 "
        "logits never materialize — several GB back at chip-sized "
        "presets. Use >= 1024 on vocab-32k presets (measured 2x faster "
        "than 512 at medium@4096). Dense model, dp/tp only",
    )
    parser.add_argument(
        "--interleave",
        type=int,
        default=1,
        help="virtual pipeline stages per device (circular/interleaved "
        "schedule; 1 = GPipe). Requires n_layers %% (pp*interleave) == 0 "
        "and microbatches %% pp == 0",
    )
    parser.add_argument(
        "--ep",
        type=int,
        default=1,
        help="expert parallelism: shard MoE expert banks over this many "
        "devices (requires --model moe)",
    )
    parser.add_argument(
        "--capacity-factor",
        type=float,
        default=None,
        help="MoE expert-capacity factor (default: the preset's, 2.0): "
        "per-expert buffer = top_k*seq*factor/n_experts tokens. Lower "
        "shrinks the dispatch/combine tensors (the MoE model's largest "
        "activations and einsums) at the cost of dropping overflow "
        "tokens from unlucky routing",
    )
    parser.add_argument(
        "--attn",
        choices=("xla", "flash"),
        default="xla",
        help="attention core: XLA einsums or the pallas flash kernel "
        "(ops.flash_attention; interpreted off-TPU)",
    )
    parser.add_argument(
        "--checkpoint-dir",
        default=None,
        help="orbax checkpoint directory; resumes from the latest step "
        "found there (SURVEY §5.4 — workload-side checkpoint/resume)",
    )
    parser.add_argument(
        "--checkpoint-every",
        type=int,
        default=0,
        help="save every N steps (0 = only at the end of the run)",
    )
    parser.add_argument(
        "--hlo-raw-dump",
        default=None,
        help="capture raw HLO-logger event strings (one JSON line each) "
        "to this file — the fixture-harvest mode for pinning "
        "hlo_counters' regexes against real runtime payloads "
        "(env: TPUMON_HLO_RAW_DUMP)",
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="expose workload-side collective-op counters (0 = off)",
    )
    parser.add_argument(
        "--stats-every",
        type=int,
        default=20,
        help="steps per live-telemetry window (one host sync per window; "
        "only meaningful with --metrics-port, and ignored with "
        "--checkpoint-dir, whose loop records losses per step by design "
        "so stats windows are per-step there)",
    )
    parser.add_argument(
        "--phase-stats",
        action="store_true",
        help="run ONE instrumented step per stats window (fwd / fwd+bwd "
        "/ optimizer timed separately, no donation) and publish "
        "tpu_step_phase_seconds — the lifecycle plane's phase "
        "breakdown; needs --metrics-port",
    )
    parser.add_argument(
        "--serve",
        action="store_true",
        help="inference-shaped preset: publish request-level serving "
        "telemetry (tpu_serve_* — requests/s, queue depth, batch size, "
        "TTFT proxy, goodput under SLO) alongside the step families; "
        "each sequence in the batch counts as one request per step and "
        "per-step latency is the TTFT proxy; needs --metrics-port",
    )
    parser.add_argument(
        "--serve-slo-ms",
        type=float,
        default=500.0,
        help="TTFT SLO threshold for --serve goodput accounting, in "
        "milliseconds (0 disables the attainment ratio)",
    )
    parser.add_argument(
        "--platform",
        choices=("auto", "cpu"),
        default="auto",
        help="force the jax platform; 'cpu' gives a virtual device mesh "
        "sized dp*tp (the JAX_PLATFORMS env var is ignored when a TPU "
        "plugin is present, so this must be a flag)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        help="jax.distributed coordinator address (host:port) — enables "
        "the multi-host path (SURVEY §3.5); pair with --num-processes "
        "and --process-id",
    )
    parser.add_argument("--num-processes", type=int, default=1)
    parser.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="this process's index; defaults to $TPU_WORKER_ID or 0",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(message)s")

    num_processes = args.num_processes if args.coordinator else 1
    total = max(args.dp * args.tp * args.sp * args.pp * args.ep, 1)
    if total % max(num_processes, 1):
        parser.error(
            f"--dp*--tp*--sp*--pp*--ep ({total}) must be divisible by --num-processes "
            f"({num_processes})"
        )
    if args.num_processes > 1 and not args.coordinator:
        parser.error("--num-processes > 1 requires --coordinator")
    if args.serve and not args.metrics_port:
        parser.error("--serve publishes tpu_serve_* on the metrics "
                     "port; it needs --metrics-port")
    if args.serve and args.checkpoint_dir:
        parser.error("--serve composes with the windowed loop, not "
                     "--checkpoint-dir")

    if args.platform == "cpu":
        from tpumon.workload.platform import force_cpu_devices

        # Each process owns its share of the dp*tp global mesh.
        force_cpu_devices(total // max(num_processes, 1))

    if args.coordinator:
        process_id = args.process_id
        if process_id is None:
            process_id = int(os.environ.get("TPU_WORKER_ID", "0") or 0)
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=process_id,
        )
        log.info(
            "distributed: process %d/%d, %d local / %d global devices",
            process_id,
            args.num_processes,
            len(jax.local_devices()),
            len(jax.devices()),
        )

    if args.model == "moe":
        moe_presets = {"tiny": MoeConfig.tiny, "small": MoeConfig.small}
        if args.preset not in moe_presets:
            log.warning("--model moe has tiny/small presets; ignoring "
                        "--preset %s", args.preset)
        cfg = moe_presets.get(args.preset, MoeConfig.tiny)()
        if args.capacity_factor is not None:
            cfg = dataclasses.replace(
                cfg, capacity_factor=args.capacity_factor
            )
    else:
        if args.capacity_factor is not None:
            parser.error("--capacity-factor requires --model moe")
        cfg = {
            "tiny": LlamaConfig.tiny,
            "small": LlamaConfig.small,
            "medium": LlamaConfig.medium,
            "llama3-8b": LlamaConfig.llama3_8b,
        }[args.preset]()
    groups = args.pp * args.interleave
    if args.pp > 1 and cfg.n_layers % groups:
        # Pipeline stages need a whole number of layers per (virtual)
        # stage; round up so the CLI works as a traffic generator at any
        # --pp/--interleave.
        n = ((cfg.n_layers + groups - 1) // groups) * groups
        log.info(
            "rounding n_layers %d → %d for pp=%d interleave=%d",
            cfg.n_layers, n, args.pp, args.interleave,
        )
        cfg = dataclasses.replace(cfg, n_layers=n)

    from tpumon.workload.hlo_counters import CountersCollector, HloOpCounters

    raw_dump = args.hlo_raw_dump or os.environ.get("TPUMON_HLO_RAW_DUMP")
    counters = HloOpCounters(raw_path=raw_dump or None)
    hooked = counters.start()
    server = None
    stats = None
    serve_stats = None
    if args.metrics_port:
        from prometheus_client.registry import CollectorRegistry

        from tpumon.exporter.server import (
            ExporterServer,
            _make_app,
            registry_renderer,
        )
        from tpumon.exporter.telemetry import SelfTelemetry

        from tpumon.workload.stats import StatsCollector, WorkloadStats

        registry = CollectorRegistry()
        registry.register(CountersCollector(counters))
        stats = WorkloadStats()
        registry.register(StatsCollector(stats))
        if args.serve:
            from tpumon.workload.serve import ServeCollector, ServeStats

            serve_stats = ServeStats()
            serve_stats.configure(
                slo_threshold_s=(
                    args.serve_slo_ms / 1000.0
                    if args.serve_slo_ms > 0 else None
                )
            )
            registry.register(ServeCollector(serve_stats))
        telemetry = SelfTelemetry(registry)
        telemetry.last_poll.set(time.time())
        # No device poll loop here; the serving process is the liveness.
        # Without this the shared tpumon_up gauge reads 0 forever and
        # falsely trips the TPUMonPollLoopDown alert (same fix as the
        # discovery sidecar).
        telemetry.up.set(1)
        server = ExporterServer(
            _make_app(registry_renderer(registry), telemetry, lambda: (True, "ok\n")),
            "0.0.0.0",
            args.metrics_port,
        )
        server.start()
        log.info("workload counters at %s/metrics", server.url)
        _install_sigterm_marker(stats)

    collective_us = None
    if stats is not None and hooked:
        def collective_us() -> float:
            detail = counters.detailed_snapshot()
            return float(sum(detail["latency_us"].values()))

    try:
        result = run(
            cfg,
            steps=args.steps,
            batch=args.batch,
            seq=args.seq,
            dp=args.dp,
            tp=args.tp,
            sp=args.sp,
            pp=args.pp,
            ep=args.ep,
            microbatches=args.microbatches,
            interleave=args.interleave,
            sp_layout=args.sp_layout,
            grad_accum=args.grad_accum,
            remat=args.remat,
            zero1=args.zero1,
            loss_chunk=args.loss_chunk,
            attn=args.attn,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            stats=stats,
            stats_every=args.stats_every,
            phase_stats=args.phase_stats,
            collective_us=collective_us,
            serve=serve_stats,
        )
        log.info(
            "loss %.4f → %.4f | %.2f steps/s | %.1f GFLOP/step | MFU %s | "
            "mesh dp=%d tp=%d sp=%d pp=%d ep=%d | devices=%s",
            result.losses[0] if result.losses else float("nan"),
            result.losses[-1] if result.losses else float("nan"),
            result.steps_per_sec,
            result.model_flops_per_step / 1e9,
            f"{result.mfu:.2%}" if result.mfu is not None else "n/a (no peak)",
            result.dp,
            result.tp,
            result.sp,
            result.pp,
            result.ep,
            jax.devices()[0].platform,
        )
        if hooked:
            counts, events = counters.snapshot()
            log.info("hlo events=%d collectives=%s", events, counts or "{}")
    finally:
        counters.stop()
        if server is not None:
            server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
