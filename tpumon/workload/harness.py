"""Training harness: the ICI-traffic generator (SURVEY.md §3.5).

One jitted SPMD train step (next-token cross-entropy + Adam) over a dp×tp
mesh. Run it while the exporter polls from another process and the
collective / duty-cycle / HBM families go non-empty — the process boundary
is the point: the monitor must see traffic it did not generate.

CLI:  python -m tpumon.workload.harness --steps 20 --dp 1 --tp 1
      (add --metrics-port to expose in-process collective-op counters)
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
import sys
import time

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpumon.workload.models.llama import LlamaConfig, forward, init_params
from tpumon.workload.parallel.mesh import (
    batch_spec,
    make_mesh,
    param_specs,
    shard_tree,
)

log = logging.getLogger(__name__)


def loss_fn(params, tokens, cfg: LlamaConfig):
    """Next-token cross-entropy; inputs [B, S], targets are the shift-by-1."""
    logits = forward(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def make_train_step(cfg: LlamaConfig, optimizer):
    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


@dataclasses.dataclass
class RunResult:
    losses: list[float]
    steps_per_sec: float
    dp: int
    tp: int


def run(
    cfg: LlamaConfig,
    *,
    steps: int = 10,
    batch: int = 8,
    seq: int | None = None,
    dp: int = 1,
    tp: int = 1,
    seed: int = 0,
    mesh=None,
) -> RunResult:
    """Build, shard, and run the train step; returns losses + throughput."""
    seq = seq or cfg.max_seq
    key = jax.random.PRNGKey(seed)
    k_params, k_data = jax.random.split(key)

    params = init_params(cfg, k_params)
    optimizer = optax.adamw(1e-3)
    train_step = make_train_step(cfg, optimizer)
    tokens = jax.random.randint(k_data, (batch, seq + 1), 0, cfg.vocab, jnp.int32)

    if mesh is None and dp * tp > 1:
        mesh = make_mesh(dp, tp)

    if mesh is not None:
        # Shard params FIRST; optimizer.init on sharded params then makes the
        # Adam moments inherit the same layout (no replicated moment memory).
        params = shard_tree(params, param_specs(), mesh)
        tokens = shard_tree(tokens, batch_spec(), mesh)
    opt_state = optimizer.init(params)
    step = jax.jit(train_step, donate_argnums=(0, 1))

    # Warmup/compile outside the timed window.
    params, opt_state, loss = step(params, opt_state, tokens)
    loss.block_until_ready()
    losses = [float(loss)]

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    loss.block_until_ready()
    elapsed = time.perf_counter() - t0
    losses.append(float(loss))
    return RunResult(
        losses=losses,
        steps_per_sec=steps / elapsed if elapsed > 0 else float("inf"),
        dp=dp,
        tp=tp,
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpumon-workload")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=None)
    parser.add_argument("--preset", choices=("tiny", "small"), default="tiny")
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="expose workload-side collective-op counters (0 = off)",
    )
    parser.add_argument(
        "--platform",
        choices=("auto", "cpu"),
        default="auto",
        help="force the jax platform; 'cpu' gives a virtual device mesh "
        "sized dp*tp (the JAX_PLATFORMS env var is ignored when a TPU "
        "plugin is present, so this must be a flag)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        help="jax.distributed coordinator address (host:port) — enables "
        "the multi-host path (SURVEY §3.5); pair with --num-processes "
        "and --process-id",
    )
    parser.add_argument("--num-processes", type=int, default=1)
    parser.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="this process's index; defaults to $TPU_WORKER_ID or 0",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(message)s")

    num_processes = args.num_processes if args.coordinator else 1
    total = max(args.dp * args.tp, 1)
    if total % max(num_processes, 1):
        parser.error(
            f"--dp*--tp ({total}) must be divisible by --num-processes "
            f"({num_processes})"
        )
    if args.num_processes > 1 and not args.coordinator:
        parser.error("--num-processes > 1 requires --coordinator")

    if args.platform == "cpu":
        import os

        # Each process owns its share of the dp*tp global mesh.
        n = total // max(num_processes, 1)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={max(n, 1)}"
            ).strip()
        jax.config.update("jax_platforms", "cpu")

    if args.coordinator:
        import os

        process_id = args.process_id
        if process_id is None:
            process_id = int(os.environ.get("TPU_WORKER_ID", "0") or 0)
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=process_id,
        )
        log.info(
            "distributed: process %d/%d, %d local / %d global devices",
            process_id,
            args.num_processes,
            len(jax.local_devices()),
            len(jax.devices()),
        )

    cfg = LlamaConfig.tiny() if args.preset == "tiny" else LlamaConfig.small()

    from tpumon.workload.hlo_counters import CountersCollector, HloOpCounters

    counters = HloOpCounters()
    hooked = counters.start()
    server = None
    if args.metrics_port:
        from prometheus_client.registry import CollectorRegistry

        from tpumon.exporter.server import (
            ExporterServer,
            _make_app,
            registry_renderer,
        )
        from tpumon.exporter.telemetry import SelfTelemetry

        registry = CollectorRegistry()
        registry.register(CountersCollector(counters))
        telemetry = SelfTelemetry(registry)
        telemetry.last_poll.set(time.time())
        server = ExporterServer(
            _make_app(registry_renderer(registry), telemetry, lambda: (True, "ok\n")),
            "0.0.0.0",
            args.metrics_port,
        )
        server.start()
        log.info("workload counters at %s/metrics", server.url)

    try:
        result = run(
            cfg,
            steps=args.steps,
            batch=args.batch,
            seq=args.seq,
            dp=args.dp,
            tp=args.tp,
        )
        log.info(
            "loss %.4f → %.4f | %.2f steps/s | mesh dp=%d tp=%d | devices=%s",
            result.losses[0],
            result.losses[-1],
            result.steps_per_sec,
            result.dp,
            result.tp,
            jax.devices()[0].platform,
        )
        if hooked:
            counts, events = counters.snapshot()
            log.info("hlo events=%d collectives=%s", events, counts or "{}")
    finally:
        counters.stop()
        if server is not None:
            server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
