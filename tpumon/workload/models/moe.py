"""Mixture-of-Experts decoder with expert parallelism, TPU-first.

GShard/Switch-style MoE built the XLA way: routing, dispatch, and combine
are dense einsums over a STATIC expert-capacity axis — no gather/scatter,
no dynamic shapes — so the whole layer tiles onto the MXU and the
dispatch/combine contractions lower to all-to-alls when expert weights are
sharded over the mesh's ``expert`` axis (tpumon.workload.parallel.mesh).
Those all-to-alls are the EP traffic the monitor's collective counters and
``ici_link_health`` observe (SURVEY.md §2.4).

Routing is top-k with renormalized gates and per-(batch-row, expert)
capacity; overflow tokens are dropped (their combine weight is zero), the
standard static-shape trade. The GShard auxiliary load-balancing loss is
returned alongside the logits so the harness can keep experts from
collapsing.

Attention reuses the Llama block (models.llama) including its pluggable
``attn_impl``, so EP composes with ring-attention SP and tensor parallelism.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from tpumon.workload.models import llama as _llama
from tpumon.workload.ops.core import rms_norm, rope_freqs


@dataclass(frozen=True)
class MoeConfig:
    vocab: int = 512
    dim: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    ffn_dim: int = 256
    max_seq: int = 128
    n_experts: int = 4
    top_k: int = 2
    capacity_factor: float = 2.0
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def tiny(cls) -> "MoeConfig":
        return cls()

    @classmethod
    def small(cls) -> "MoeConfig":
        """Chip-scale MoE: the dense `LlamaConfig.small` trunk with an
        8-expert top-2 bank per layer (0.153 B params, ~0.05 B active
        per token) — sized so a single 16 GB chip trains it at seq 4096
        with the flash kernel, giving the monitor a hardware-realistic
        routed-FFN traffic source (and `--ep` something real to shard
        on a pod)."""
        return cls(
            vocab=8192, dim=512, n_layers=8, n_heads=8, n_kv_heads=4,
            ffn_dim=1408, max_seq=4096, n_experts=8, top_k=2,
        )

    def capacity(self, seq: int) -> int:
        """Static per-(batch-row, expert) token capacity."""
        return max(
            1, math.ceil(self.top_k * seq * self.capacity_factor / self.n_experts)
        )


def init_params(cfg: MoeConfig, key: jax.Array) -> dict:
    """Llama-shaped attention + per-layer expert banks on a leading E axis."""
    k_embed, k_attn, k_moe, k_out = jax.random.split(key, 4)
    init = jax.nn.initializers.normal(0.02)
    L, D, F, E = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.n_experts
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    ka = jax.random.split(k_attn, 4)
    km = jax.random.split(k_moe, 4)

    return {
        "embed": init(k_embed, (cfg.vocab, D), jnp.float32),
        "layers": {
            "attn_norm": jnp.ones((L, D), jnp.float32),
            "wq": init(ka[0], (L, D, H * HD), jnp.float32),
            "wk": init(ka[1], (L, D, KV * HD), jnp.float32),
            "wv": init(ka[2], (L, D, KV * HD), jnp.float32),
            "wo": init(ka[3], (L, H * HD, D), jnp.float32),
            "mlp_norm": jnp.ones((L, D), jnp.float32),
            "router": init(km[0], (L, D, E), jnp.float32),
            "w_gate": init(km[1], (L, E, D, F), jnp.float32),
            "w_up": init(km[2], (L, E, D, F), jnp.float32),
            "w_down": init(km[3], (L, E, F, D), jnp.float32),
        },
        "final_norm": jnp.ones((D,), jnp.float32),
        "unembed": init(k_out, (D, cfg.vocab), jnp.float32),
    }


def _route(probs: jnp.ndarray, top_k: int, capacity: int):
    """probs [B,S,E] → (dispatch [B,S,E,C] bool-ish, combine [B,S,E,C]).

    Slot-by-slot top-k (k is tiny and static, so the Python loop unrolls
    into k fused one-hot/cumsum passes), with a running per-expert fill
    count so slot j respects the tokens slot j-1 already placed.
    """
    B, S, E = probs.shape
    p = probs
    gates, onehots = [], []
    for _ in range(top_k):
        g = jnp.max(p, axis=-1)
        e = jnp.argmax(p, axis=-1)
        oh = jax.nn.one_hot(e, E, dtype=probs.dtype)  # [B,S,E]
        gates.append(g)
        onehots.append(oh)
        p = p * (1.0 - oh)  # mask the chosen expert for the next slot

    denom = sum(gates) + 1e-9  # renormalize gate mass over the k slots
    fill = jnp.zeros((B, 1, E), probs.dtype)
    dispatch = jnp.zeros((B, S, E, capacity), probs.dtype)
    combine = jnp.zeros((B, S, E, capacity), probs.dtype)
    for g, oh in zip(gates, onehots):
        # Position of each token in its chosen expert's buffer: exclusive
        # cumsum over the sequence plus what earlier slots already placed.
        pos_e = jnp.cumsum(oh, axis=1) - oh + fill  # [B,S,E]
        pos = jnp.sum(pos_e * oh, axis=-1).astype(jnp.int32)  # [B,S]
        keep = (pos < capacity) & (jnp.sum(oh, axis=-1) > 0)
        pos_oh = jax.nn.one_hot(
            jnp.minimum(pos, capacity - 1), capacity, dtype=probs.dtype
        )  # [B,S,C]
        d = oh[..., None] * pos_oh[:, :, None, :] * keep[..., None, None]
        dispatch = dispatch + d
        combine = combine + (g / denom)[..., None, None] * d
        fill = fill + jnp.sum(oh, axis=1, keepdims=True)
    return dispatch, combine


def route_tokens(x, layer, cfg: MoeConfig):
    """Router + top-k routing for one layer: x [B,S,D] →
    (dispatch [B,S,E,C], combine [B,S,E,C], probs [B,S,E] f32).

    Shared by the GSPMD MoE forward below and the pipelined stage body
    (parallel.pipeline._moe_mlp_local), so the routing math cannot drift
    between the two paths the dense-parity checks compare.
    """
    logits = jnp.einsum(
        "bsd,de->bse", x.astype(jnp.float32), layer["router"],
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine = _route(probs, cfg.top_k, cfg.capacity(x.shape[1]))
    return dispatch, combine, probs


def expert_ffn(x, dispatch, combine, layer, cfg: MoeConfig, shard_experts=None):
    """Dispatch → expert SwiGLU → combine, as dense einsums over the
    static capacity axis: x [B,S,D] with dispatch/combine [B,S,E',C] and
    expert banks [E',D,F] → out [B,S,D].

    E' may be the full expert count (GSPMD path: sharding the banks over
    the mesh's ``expert`` axis makes the dispatch contraction the
    all-to-all) or a local slice (pipelined path: the caller slices and
    psums). Shared between both so the expert math cannot drift.
    """
    xin = jnp.einsum(
        "bsec,bsd->ebcd", dispatch.astype(cfg.dtype), x,
        preferred_element_type=cfg.dtype,
    )
    if shard_experts is not None:
        xin = shard_experts(xin)
    gate = jnp.einsum("ebcd,edf->ebcf", xin, layer["w_gate"].astype(cfg.dtype))
    up = jnp.einsum("ebcd,edf->ebcf", xin, layer["w_up"].astype(cfg.dtype))
    y = jnp.einsum(
        "ebcf,efd->ebcd", jax.nn.silu(gate) * up,
        layer["w_down"].astype(cfg.dtype),
    )
    return jnp.einsum(
        "bsec,ebcd->bsd", combine.astype(cfg.dtype), y,
        preferred_element_type=cfg.dtype,
    )


def _moe_mlp(x, layer, cfg: MoeConfig, shard_experts=None):
    """x [B,S,D] → (out [B,S,D], aux load-balancing loss scalar)."""
    E = cfg.n_experts
    dispatch, combine, probs = route_tokens(x, layer, cfg)

    # GShard aux loss: E * Σ_e mean-fraction-routed(e) · mean-prob(e).
    frac = jnp.mean(jnp.sum(dispatch, axis=-1), axis=(0, 1))  # [E]
    aux = jnp.float32(E) * jnp.sum(frac / cfg.top_k * jnp.mean(probs, axis=(0, 1)))

    out = expert_ffn(x, dispatch, combine, layer, cfg, shard_experts)
    return out, aux


@partial(
    jax.jit,
    static_argnames=("cfg", "attn_impl", "shard_acts", "shard_experts",
                     "remat"),
)
def forward(
    params: dict,
    tokens: jnp.ndarray,
    cfg: MoeConfig,
    attn_impl=None,
    shard_acts=None,
    shard_experts=None,
    remat: bool = False,
):
    """tokens [B,S] → (logits [B,S,vocab] f32, aux loss scalar f32).

    ``remat=True`` wraps the layer body in ``jax.checkpoint`` exactly as
    the dense model does (models.llama.forward) — the MoE layer's
    dispatch/combine tensors ([B,S,E,C], the capacity-padded routing)
    are the largest activations in the model, so recomputing them in
    the backward is what lets chip-scale MoE presets train at seq 4096
    on one 16 GB chip (measured: 21.1 G without remat, fits with)."""
    B, S = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    if shard_acts is not None:
        x = shard_acts(x)
    freqs = rope_freqs(cfg.head_dim, cfg.max_seq)
    mask = jnp.triu(jnp.full((cfg.max_seq, cfg.max_seq), -1e9, jnp.float32), k=1)

    def block(carry, layer):
        h, aux = carry
        h = h + _llama._attention(
            rms_norm(h, layer["attn_norm"]), layer, cfg, freqs, mask, attn_impl
        )
        moe_out, layer_aux = _moe_mlp(
            rms_norm(h, layer["mlp_norm"]), layer, cfg, shard_experts
        )
        h = h + moe_out
        if shard_acts is not None:
            h = shard_acts(h)
        return (h, aux + layer_aux), None

    (x, aux), _ = jax.lax.scan(
        jax.checkpoint(block) if remat else block,
        (x, jnp.float32(0.0)),
        params["layers"],
    )
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, aux / cfg.n_layers
