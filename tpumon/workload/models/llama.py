"""Compact Llama-style decoder, TPU-first.

Pure-functional JAX (params as a pytree, no framework state) so the whole
train step jits into one XLA program:

- matmuls in **bfloat16** with float32 accumulation (MXU-native);
- static shapes everywhere; the layer stack is a ``lax.scan`` over stacked
  per-layer params, so XLA compiles ONE layer body regardless of depth;
- grouped-query attention + SwiGLU, mirroring the Llama-3 shape the
  BASELINE config 4 workload names ("JAX Llama-3-8B pretrain");
- tensor-parallel-friendly layout: head and FFN dims lead the sharded axes
  (see tpumon.workload.parallel.mesh for the PartitionSpecs).

Used by the ICI-traffic harness and as the graft-entry flagship model; the
'tiny' preset keeps single-chip compile fast while the sharding logic is
identical at any size.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from tpumon.workload.ops.core import apply_rope, rms_norm, rope_freqs


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 512
    dim: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    ffn_dim: int = 256
    max_seq: int = 128
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def small(cls) -> "LlamaConfig":
        return cls(
            vocab=8192, dim=512, n_layers=8, n_heads=8, n_kv_heads=4,
            ffn_dim=1408, max_seq=512,
        )

    @classmethod
    def medium(cls) -> "LlamaConfig":
        """Chip-sized preset: ~0.67 B params (embed 134 M + 12 layers ×
        45 M), sized so f32 params + Adam moments (~8 GB) plus bf16
        activations fill most of a single 16 GB-HBM chip at seq 4096 —
        the shape for a *sweet-spot* single-chip MFU measurement, where
        every layer matmul is MXU-sized (2048×2048 and larger), unlike
        ``small`` whose dim-512 matmuls underfill the systolic array.
        Pair with ``--attn flash`` (the XLA path's [B,H,S,S] scores add
        ~2 GB per batch row at seq 4096) and ``--grad-accum`` to fit
        batch sizes beyond activation memory."""
        return cls(
            vocab=32768, dim=2048, n_layers=12, n_heads=16,
            n_kv_heads=4, ffn_dim=5632, max_seq=4096,
        )

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        """The BASELINE config-4 workload shape ("JAX Llama-3-8B
        pretrain"): Llama-3-8B's published architecture — 32 layers,
        4096 dim, 32 query / 8 KV heads (GQA 4:1), 14336 SwiGLU hidden,
        128k vocab. Too large to *run* on this dev host; it exists so
        mesh planning, FLOPs/MFU accounting, and sharding specs are
        exercised at the real shape (tests/test_workload.py pins the
        FLOPs math against the 6·N/token rule at this size)."""
        return cls(
            vocab=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, ffn_dim=14336, max_seq=8192,
        )


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Per-layer weights stacked on a leading layer axis (for lax.scan)."""
    k_embed, k_layers, k_out = jax.random.split(key, 3)
    init = jax.nn.initializers.normal(0.02)
    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    ks = jax.random.split(k_layers, 7)

    def stacked(key, shape):
        return init(key, (L, *shape), jnp.float32)

    return {
        "embed": init(k_embed, (cfg.vocab, D), jnp.float32),
        "layers": {
            "attn_norm": jnp.ones((L, D), jnp.float32),
            "wq": stacked(ks[0], (D, H * HD)),
            "wk": stacked(ks[1], (D, KV * HD)),
            "wv": stacked(ks[2], (D, KV * HD)),
            "wo": stacked(ks[3], (H * HD, D)),
            "mlp_norm": jnp.ones((L, D), jnp.float32),
            "w_gate": stacked(ks[4], (D, F)),
            "w_up": stacked(ks[5], (D, F)),
            "w_down": stacked(ks[6], (F, D)),
        },
        "final_norm": jnp.ones((D,), jnp.float32),
        "unembed": init(k_out, (D, cfg.vocab), jnp.float32),
    }


def _attention(x, layer, cfg: LlamaConfig, freqs, mask, attn_impl=None):
    B, S, D = x.shape
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    q = (x @ layer["wq"].astype(cfg.dtype)).reshape(B, S, H, HD)
    k = (x @ layer["wk"].astype(cfg.dtype)).reshape(B, S, KV, HD)
    v = (x @ layer["wv"].astype(cfg.dtype)).reshape(B, S, KV, HD)

    q = apply_rope(q, freqs[:S])
    k = apply_rope(k, freqs[:S])

    if attn_impl is not None:
        # Pluggable causal attention q [B,S,H,D], k/v [B,S,KV,D] → [B,S,H,D].
        # K/V keep their grouped-query head count; each impl resolves the
        # sharing itself (pallas flash via index maps, ring attention by a
        # local repeat after the hop — fewer bytes on the ICI ring).
        out = attn_impl(q, k, v).reshape(B, S, H * HD)
    else:
        # Grouped-query: repeat KV heads up to H (cheap reshape-broadcast;
        # XLA folds it into the einsum rather than materializing).
        rep = H // KV
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        scores = scores / jnp.sqrt(jnp.float32(HD)) + mask[:S, :S]
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, H * HD)
    return out @ layer["wo"].astype(cfg.dtype)


def _mlp(x, layer, cfg: LlamaConfig):
    gate = x @ layer["w_gate"].astype(cfg.dtype)
    up = x @ layer["w_up"].astype(cfg.dtype)
    return (jax.nn.silu(gate) * up) @ layer["w_down"].astype(cfg.dtype)


@partial(
    jax.jit,
    static_argnames=("cfg", "attn_impl", "shard_acts", "remat", "unembed"),
)
def forward(
    params: dict,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    attn_impl=None,
    shard_acts=None,
    remat: bool = False,
    unembed: bool = True,
) -> jnp.ndarray:
    """tokens [B, S] int32 → logits [B, S, vocab] float32.

    ``unembed=False`` returns the final-norm hidden states [B, S, dim]
    (cfg.dtype) instead — the entry point for losses that fuse the
    unembed projection with the cross-entropy in chunks so the full
    [B, S, vocab] float32 logits tensor never materializes
    (harness.loss_fn's ``loss_chunk``).

    ``attn_impl`` swaps the attention core (ring attention for sequence
    parallelism, pallas flash attention); ``shard_acts`` is an optional
    x→x sharding constraint applied to the residual stream so sequence-
    parallel layouts persist between layers instead of round-tripping
    through a replicated view. ``remat=True`` wraps the layer body in
    ``jax.checkpoint`` so the backward pass recomputes each layer's
    activations instead of stashing them — activation memory drops from
    O(n_layers) to O(1) layers for ~⅓ extra forward FLOPs, the standard
    HBM-for-FLOPs trade that lets chip-sized models train at long
    sequence lengths on one chip.
    """
    B, S = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    if shard_acts is not None:
        x = shard_acts(x)
    freqs = rope_freqs(cfg.head_dim, cfg.max_seq)
    mask = jnp.triu(jnp.full((cfg.max_seq, cfg.max_seq), -1e9, jnp.float32), k=1)

    def block(carry, layer):
        h = carry
        h = h + _attention(
            rms_norm(h, layer["attn_norm"]), layer, cfg, freqs, mask, attn_impl
        )
        h = h + _mlp(rms_norm(h, layer["mlp_norm"]), layer, cfg)
        if shard_acts is not None:
            h = shard_acts(h)
        return h, None

    # One compiled layer body for any depth — lax.scan over stacked params.
    x, _ = jax.lax.scan(
        jax.checkpoint(block) if remat else block, x, params["layers"]
    )
    x = rms_norm(x, params["final_norm"])
    if not unembed:
        return x
    return (x @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)
