from tpumon.workload.models.llama import LlamaConfig, forward, init_params

__all__ = ["LlamaConfig", "forward", "init_params"]
