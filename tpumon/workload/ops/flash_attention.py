"""Pallas flash attention — the workload model's hot op, TPU-first.

The attention core is where the O(S²) FLOPs and HBM traffic live, so it is
the one op worth a hand kernel (everything else in the model fuses fine
under XLA). Design, per the TPU kernel playbook:

- **Online softmax, one pass:** the kernel never materializes the [S, S]
  score matrix. Each q-block keeps float32 running max ``m``, denominator
  ``l``, and a weighted-value accumulator in registers while it streams
  k-blocks from VMEM — O(S) memory instead of O(S²).
- **MXU-shaped matmuls:** both einsums are ``jax.lax.dot_general`` with
  ``preferred_element_type=float32``; probabilities are cast back to the
  value dtype (bfloat16 in the workload) so the second matmul rides the
  MXU at bf16 throughput with f32 accumulation.
- **Grouped-query without the repeat:** the grid is (batch, q_heads,
  q_blocks) and the K/V BlockSpec index-map sends q-head ``h`` to kv-head
  ``h * KV // H`` — GQA sharing happens in the index map, so the repeated
  K/V copies the XLA path materializes (models/llama.py `jnp.repeat`)
  never exist.
- **Causal skipping:** the k-block loop for q-block ``i`` runs only to the
  diagonal (`lax.fori_loop` with a traced bound), halving work; the
  diagonal block is masked with a 2D ``broadcasted_iota`` compare.

Gradients: ``flash_attention`` carries a ``jax.custom_vjp``. The forward
is this kernel; the backward recomputes attention blockwise from the saved
(q, k, v) — flash-style O(S) memory — via three more Pallas kernels in
this module (dq over q-blocks; dk/dv over k-blocks with the GQA group
reduced inside the kernel).

Runs compiled on TPU and in interpreter mode elsewhere (auto-detected), so
the same code path is exercised by the CPU test mesh and the real chip.
SURVEY.md §2.4: the workload exists to drive MXU/ICI traffic for the
monitor; this kernel is what makes the MXU side of that traffic realistic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Finite stand-in for -inf (same constant as parallel.ring): masked logits
# underflow to exp(x - m) == 0 without ever forming inf - inf.
_NEG_BIG = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# Mosaic tiling: the last block dim must be a multiple of 128 (or the full
# array dim) and the second-to-last a multiple of 8 (or full). Row-wise
# softmax state (lse, Δ) is therefore carried lane-broadcast at this width —
# the same convention as jax.experimental.pallas.ops.tpu.flash_attention.
_LANES = 128


def _pick_block(size: int, requested: int) -> int:
    """Largest divisor of ``size`` ≤ requested that keeps blocks tileable.

    Prefers multiples of 8 (the f32 sublane); a full-size block is always
    legal, so fall back to that when no aligned divisor exists.
    """
    for b in range(min(requested, size), 0, -1):
        if size % b == 0 and (b % 8 == 0 or b == size):
            return b
    return size


#: Tuned tile table from the measured TPU v5 lite sweep (BASELINE.md
#: "Flash kernel tiling sweep"): larger k-blocks dominate — fewer grid
#: iterations and larger MXU tiles per dot (256×512 ran the seq-4096
#: forward 2.5× faster than 128×128). Rows are (min seq_k, (block_q,
#: block_k)), first match wins; sizes the table doesn't cover keep the
#: conservative 128×128 (always VMEM-safe).
#: The full fwd+bwd sweep across seq 1k–8k (2026-07-31, TPU v5 lite)
#: measured 256×512 best or within noise of best at every length
#: ≥ 1024 — one row covers that whole resident-layout regime (seq 4096
#: fwd 5.53 ms vs 10.77 at 128×128; seq 8192 fwd+bwd 15.6 vs 51.0).
_TUNED_BLOCKS: tuple[tuple[int, tuple[int, int]], ...] = (
    (1024, (256, 512)),
)

#: In the streamed regime (K/V bands no longer VMEM-resident — see
#: _kv_fits_resident) much larger square tiles win: the full 5×5 sweep
#: at seq 16384 measured 1024×1024 fastest (45.7 ms fwd+bwd vs 71.4
#: for 256×512 and 49.2 for 512×2048), and it sustains 231 full-S²
#: TFLOP/s at seq 32768; 2048-wide q- or 4096-wide k-blocks OOM the
#: backward's scoped VMEM. These tiles were measured only with the
#: streamed layout, so the chooser keys on the *layout*, not on seq_k
#: alone (seq 16384 at head_dim 64 stays resident and keeps 256×512).
_STREAMED_BLOCKS: tuple[int, int] = (1024, 1024)


def default_blocks(
    seq_q: int, seq_k: int, head_dim: int = 128, itemsize: int = 2
) -> tuple[int, int]:
    """Tuned (block_q, block_k) for this problem size.

    Looked up by the key-side length (the k-block loop is where the
    sweep showed the win) within the kernel layout the shape selects —
    ``head_dim``/``itemsize`` determine whether the K/V bands stay
    VMEM-resident (defaults match the benchmarked GQA shapes). Callers
    passing explicit blocks bypass this entirely. ``_pick_block`` still
    clamps the choice to divisors of the actual lengths, so small or
    ragged shapes (ring stripes, rectangular composition) stay legal.
    """
    if not _kv_fits_resident(seq_k, head_dim, itemsize):
        return _STREAMED_BLOCKS
    for min_k, blocks in _TUNED_BLOCKS:
        if seq_k >= min_k:
            return blocks
    return (128, 128)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


# Two forward/dq kernel layouts, selected per problem size (measured on
# TPU v5 lite, BASELINE.md "resident vs streamed"):
#
# - **resident**: whole [Sk, D] K/V bands live in VMEM per (batch, head)
#   program; the k-block loop streams from VMEM. Fastest — K/V is
#   fetched from HBM exactly once per (b, h) — but the bands
#   (2 arrays × 2 DMA buffers × Sk × D × 2 B) outgrow the ~16 MB scoped
#   VMEM limit around Sk ≈ 10 k at head_dim 128.
# - **streamed**: k-blocks advance through the innermost grid dim with
#   the softmax state in persistent scratch; O(block) VMEM at any Sk,
#   but each q-block re-fetches its K/V stripe from HBM (measured 2.2×
#   slower at seq 8192, entirely accounted by the extra HBM traffic).
#
# The crossover is purely a VMEM-capacity cliff, so selection is by
# band size, not by timing.
_RESIDENT_KV_BYTES = 10 * 2 ** 20


def _kv_fits_resident(Sk: int, D: int, itemsize: int) -> bool:
    """Whether the resident layout's K/V bands (two arrays, double-
    buffered) fit the scoped-VMEM budget."""
    return 2 * 2 * Sk * D * itemsize <= _RESIDENT_KV_BYTES


def _causal_kj(block_q, block_k, causal):
    """Streamed-layout k-block index clamp.

    For causal problems, grid steps whose k-block lies fully above the
    diagonal re-reference the diagonal block (already resident — no
    DMA); the kernels skip the same steps' FLOPs with the matching
    ``pl.when((qi + 1) * block_q - 1 >= kj * block_k)`` guard. One
    helper serves the forward and dq call sites so the clamp and the
    skip cannot drift apart."""
    if not causal:
        return lambda i, j: j
    return lambda i, j: jnp.minimum(j, (i * block_q + block_q - 1) // block_k)


def _online_softmax_step(q, k, v, qi_row, kb_col, m, l, acc, *, block_q,
                         block_k, causal):
    """One k-block update of the online-softmax state — the single home
    of the numerically sensitive core (masking constant, exp rescaling,
    accumulation dtypes) shared by the resident and streamed forward
    kernels.

    ``q`` is pre-scaled f32 [block_q, D]; ``k``/``v`` raw blocks;
    ``qi_row``/``kb_col`` the block-origin row/col offsets (ignored when
    not causal); ``(m, l)`` f32 [block_q, 1]; ``acc`` f32 [block_q, D].
    """
    s = jax.lax.dot_general(
        q, k.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_q, block_k]
    if causal:
        row = qi_row + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        col = kb_col + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(row >= col, s, _NEG_BIG)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc * alpha + pv


def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                         block_q, block_k, n_kb, causal):
    """One (batch, head, q-block) program: k-blocks stream from the
    VMEM-resident K/V band, softmax state carried in registers."""
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, D]
    D = q.shape[-1]

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]  # [block_k, D]
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        return _online_softmax_step(
            q, k, v, qi * block_q, kb * block_k, m, l, acc,
            block_q=block_q, block_k=block_k, causal=causal,
        )

    if causal:
        # Last k-block that overlaps the causal triangle of this q-block.
        hi = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
        hi = jnp.minimum(hi, n_kb)
    else:
        hi = n_kb
    m0 = jnp.full((block_q, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    a0 = jnp.zeros((block_q, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, a0))

    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    # log-sum-exp per row (the flash backward's softmax residual),
    # lane-broadcast so the block stays tileable.
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (block_q, _LANES))


def _fwd_kernel_streamed(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                         acc_ref, *, scale, block_q, block_k, n_kb, causal):
    """One (batch, head, q-block, k-block) program: online softmax with the
    k-block stream in the *grid* and the running (m, l, acc) state in VMEM
    scratch, which persists across grid steps on TPU.

    Streaming k-blocks through the grid instead of holding the whole
    [Sk, D] K/V in VMEM caps this kernel's footprint at O(block) for any
    sequence length — the resident layout's bands outgrow the 16 M
    scoped-vmem limit at seq 16384 (2×8 MB of K/V double-buffered).
    """
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _NEG_BIG, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, D]
        m = m_ref[...][:, :1]  # lane-broadcast scratch → [block_q, 1]
        l = l_ref[...][:, :1]
        m_new, l_new, acc_new = _online_softmax_step(
            q, k_ref[0, 0], v_ref[0, 0], qi * block_q, kj * block_k,
            m, l, acc_ref[...],
            block_q=block_q, block_k=block_k, causal=causal,
        )
        acc_ref[...] = acc_new
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # k-blocks fully above the diagonal contribute nothing: skip
        # their FLOPs here; their DMA is skipped by the index-map clamp
        # in _flash_fwd (they re-reference the diagonal block).
        pl.when(qi * block_q + block_q - 1 >= kj * block_k)(compute)
    else:
        compute()

    @pl.when(kj == n_kb - 1)
    def _done():
        l = l_ref[...][:, :1]
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # log-sum-exp per row (the flash backward's softmax residual),
        # lane-broadcast so the block stays tileable.
        lse_ref[0, 0] = m_ref[...] + jnp.log(l_ref[...])


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, resident=None):
    """q [B,H,S,D], k/v [B,KV,Sk,D] → (out [B,H,S,D], lse [B,H,S,LANES] f32).

    Sk may differ from S only when ``causal=False`` (rectangular
    attention — the blockwise/ring composition attends one q stripe to a
    different-length key stripe); causal masking is only meaningful when
    query and key positions share an origin, i.e. Sk == S.
    ``resident=None`` auto-selects the kernel layout by K/V band size.
    """
    B, H, S, D = q.shape
    KV, Sk = k.shape[1], k.shape[2]
    if causal and Sk != S:
        raise ValueError(
            f"causal flash attention needs matching seq lengths (q {S}, "
            f"k {Sk}); rectangular attention must be causal=False"
        )
    block_q = _pick_block(S, block_q)
    block_k = _pick_block(Sk, block_k)
    n_kb = Sk // block_k
    scale = 1.0 / (D ** 0.5)
    if resident is None:
        resident = _kv_fits_resident(Sk, D, k.dtype.itemsize)

    q_spec3 = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0))
    out_shape = [
        jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        jax.ShapeDtypeStruct((B, H, S, _LANES), jnp.float32),
    ]
    if resident:
        kv_band = pl.BlockSpec(
            (1, 1, Sk, D), lambda b, h, i: (b, (h * KV) // H, 0, 0)
        )
        return pl.pallas_call(
            functools.partial(
                _fwd_kernel_resident, scale=scale, block_q=block_q,
                block_k=block_k, n_kb=n_kb, causal=causal,
            ),
            grid=(B, H, S // block_q),
            in_specs=[q_spec3, kv_band, kv_band],
            out_specs=[
                q_spec3,
                pl.BlockSpec(
                    (1, 1, block_q, _LANES), lambda b, h, i: (b, h, i, 0)
                ),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(q, k, v)

    # Streamed: k-blocks in the innermost grid dim, softmax state in
    # persistent scratch (causal DMA clamp: _causal_kj).
    _kj = _causal_kj(block_q, block_k, causal)
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D),
        lambda b, h, i, j: (b, (h * KV) // H, _kj(i, j), 0),
    )
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel_streamed, scale=scale, block_q=block_q,
            block_k=block_k, n_kb=n_kb, causal=causal,
        ),
        grid=(B, H, S // block_q, n_kb),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[
            q_spec,
            pl.BlockSpec(
                (1, 1, block_q, _LANES), lambda b, h, i, j: (b, h, i, 0)
            ),
        ],
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # denominator l
            pltpu.VMEM((block_q, D), jnp.float32),       # weighted acc
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------
#
# Standard flash decomposition. With P = softmax(QKᵀ·scale) (row lse saved),
# dP = dO Vᵀ, Δ_i = Σ_j dO_ij O_ij (per row), dS = P ∘ (dP − Δ):
#   dQ = scale · dS K          (kernel over q-blocks, streams k-blocks)
#   dK = scale · dSᵀ Q,  dV = Pᵀ dO   (kernel over k-blocks, streams q-blocks,
#                                      summing the GQA head group in-kernel)


def _recompute_p(q, k, lse_blk, scale, row, col, causal):
    """P block [block_q, block_k] in f32 from saved lse [block_q, 1]."""
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        s = jnp.where(row >= col, s, _NEG_BIG)
    return jnp.exp(s - lse_blk)


def _recompute_ds(q, k, v, do, lse, delta, qi_row, kb_col, *, block_q,
                  block_k, scale, causal):
    """(P, scale·dS) for one (q-block, k-block) pair — the shared core
    of all three backward kernels: P recomputed from the saved lse,
    dP = dO·Vᵀ, dS = P∘(dP−Δ), with ``scale`` folded in so no kernel
    needs an epilogue pass. All operands f32 blocks; ``lse``/``delta``
    are [block_q, 1] columns."""
    row = qi_row + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    col = kb_col + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    p = _recompute_p(q, k, lse, scale, row, col, causal)
    dp = jax.lax.dot_general(
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return p, p * (dp - delta) * scale


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, *, scale, block_q, block_k, n_kb, causal):
    """One (batch, head, q-block) program; k-blocks stream from the
    VMEM-resident K/V band (fast path, Sk-bounded — see the layout note
    above _kv_fits_resident)."""
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, :1]    # lane-broadcast → [block_q, 1]
    delta = delta_ref[0, 0][:, :1]
    D = q.shape[-1]

    def body(kb, dq):
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        _, ds = _recompute_ds(
            q, k, v, do, lse, delta, qi * block_q, kb * block_k,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
        )
        return dq + jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        hi = jnp.minimum(
            jax.lax.div((qi + 1) * block_q + block_k - 1, block_k), n_kb
        )
    else:
        hi = n_kb
    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dq_kernel_streamed(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, *, scale, block_q, block_k, causal):
    """One (batch, head, q-block, k-block) program; dq accumulates in the
    revisited f32 output block (its index map ignores the k dim), so
    VMEM holds O(block) for any Sk — same restructure as _dkv_kernel."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        dq_ref[...] = jnp.zeros(dq_ref.shape, dq_ref.dtype)

    def compute():
        k = k_ref[0, 0].astype(jnp.float32)
        _, ds = _recompute_ds(
            q_ref[0, 0].astype(jnp.float32), k,
            v_ref[0, 0].astype(jnp.float32),
            do_ref[0, 0].astype(jnp.float32),
            lse_ref[0, 0][:, :1], delta_ref[0, 0][:, :1],
            qi * block_q, kj * block_k,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
        )
        dq_ref[0, 0] += jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Masked k-blocks skip FLOPs here and DMA via the index-map
        # clamp in _flash_bwd.
        pl.when(qi * block_q + block_q - 1 >= kj * block_k)(compute)
    else:
        compute()


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, block_q, block_k, causal):
    """One (batch, kv-head, k-block, q-head-in-group, q-block) program.

    The GQA group sum and the q-block stream live in the *grid*, not in
    in-kernel loops over VMEM-resident whole-sequence bands: dk/dv output
    blocks are revisited across the two inner grid dims (their index map
    ignores g and qb), so Mosaic keeps the f32 accumulator resident in
    VMEM and this kernel only ever holds O(block_q·D + block_k·D) —
    the whole-band layout needed group·S·(2D+2·_LANES·2) bytes and
    vmem-OOM'd at medium-preset shapes (48.5M vs the 16M scoped limit,
    observed live on TPU v5 lite at group=4, S=4096).
    """
    ki = pl.program_id(2)
    g = pl.program_id(3)
    qb = pl.program_id(4)

    @pl.when(jnp.logical_and(g == 0, qb == 0))
    def _init():
        dk_ref[...] = jnp.zeros(dk_ref.shape, dk_ref.dtype)
        dv_ref[...] = jnp.zeros(dv_ref.shape, dv_ref.dtype)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
        do = do_ref[0, 0].astype(jnp.float32)
        p, ds = _recompute_ds(
            q, k_ref[0, 0].astype(jnp.float32),
            v_ref[0, 0].astype(jnp.float32), do,
            lse_ref[0, 0][:, :1], delta_ref[0, 0][:, :1],
            qb * block_q, ki * block_k,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
        )
        dv_ref[0, 0] += jax.lax.dot_general(
            p, do, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_ref[0, 0] += jax.lax.dot_general(
            ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Skip q-blocks strictly above this k-block's causal triangle
        # (max row of qb < min col of ki ⇒ fully masked). Their grid
        # steps still fetch blocks, but pay no FLOPs.
        pl.when((qb + 1) * block_q - 1 >= ki * block_k)(compute)
    else:
        compute()


def _flash_bwd(q, k, v, out, lse, do, causal, block_q, block_k, interpret,
               g_lse=None, resident=None):
    B, H, S, D = q.shape
    KV, Sk = k.shape[1], k.shape[2]
    group = H // KV
    block_q = _pick_block(S, block_q)
    block_k = _pick_block(Sk, block_k)
    scale = 1.0 / (D ** 0.5)

    # Δ_i = Σ_d dO·O per row — tiny elementwise reduce; XLA fuses it.
    # Lane-broadcast to _LANES like lse so its blocks stay tileable.
    # When the caller also differentiates the lse output (blockwise/ring
    # composition), its cotangent folds in right here: dS = P∘(dP − Δ) +
    # g_lse·P = P∘(dP − (Δ − g_lse)), so the kernels never change.
    delta_rows = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1, keepdims=True,
    )
    if g_lse is not None:
        delta_rows = delta_rows - g_lse.astype(jnp.float32)[..., None]
    delta = jnp.broadcast_to(delta_rows, (B, H, S, _LANES))

    # dQ: resident fast path when the K/V bands fit VMEM, else k-blocks
    # stream through the innermost grid dim (same clamp trick as the
    # forward: causally masked steps re-reference the diagonal k-block,
    # paying neither FLOPs nor DMA).
    if resident is None:
        resident = _kv_fits_resident(Sk, D, k.dtype.itemsize)
    if resident:
        kv_band = pl.BlockSpec(
            (1, 1, Sk, D), lambda b, h, i: (b, (h * KV) // H, 0, 0)
        )
        q_blk3 = pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)
        )
        row_blk3 = pl.BlockSpec(
            (1, 1, block_q, _LANES), lambda b, h, i: (b, h, i, 0)
        )
        dq = pl.pallas_call(
            functools.partial(
                _dq_kernel_resident, scale=scale, block_q=block_q,
                block_k=block_k, n_kb=Sk // block_k, causal=causal,
            ),
            grid=(B, H, S // block_q),
            in_specs=[q_blk3, kv_band, kv_band, q_blk3, row_blk3, row_blk3],
            out_specs=q_blk3,
            out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            interpret=interpret,
        )(q, k, v, do, lse, delta)
    else:
        _kj = _causal_kj(block_q, block_k, causal)
        kv_spec = pl.BlockSpec(
            (1, 1, block_k, D),
            lambda b, h, i, j: (b, (h * KV) // H, _kj(i, j), 0),
        )
        q_blk = pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)
        )
        row_blk = pl.BlockSpec(
            (1, 1, block_q, _LANES), lambda b, h, i, j: (b, h, i, 0)
        )
        dq = pl.pallas_call(
            functools.partial(
                _dq_kernel_streamed, scale=scale, block_q=block_q,
                block_k=block_k, causal=causal,
            ),
            grid=(B, H, S // block_q, Sk // block_k),
            in_specs=[q_blk, kv_spec, kv_spec, q_blk, row_blk, row_blk],
            out_specs=q_blk,
            out_shape=jax.ShapeDtypeStruct((B, H, S, D), jnp.float32),
            interpret=interpret,
        )(q, k, v, do, lse, delta)
        dq = dq.astype(q.dtype)

    # dK/dV: grid (batch, kv-head, k-block, q-head-in-group, q-block).
    # The dk/dv index maps ignore the two inner dims, so the f32
    # accumulator block stays VMEM-resident across the GQA group and the
    # q-block stream — O(block) VMEM (the whole-band layout OOM'd the
    # 16M scoped limit at medium shapes; see _dkv_kernel docstring).
    if causal:
        # Clamp masked q-block steps (qb strictly above the k-block's
        # causal triangle) onto the first active block: their index map
        # then re-references the already-resident block, so the skipped
        # steps pay no DMA either (the kernel already skips their FLOPs).
        def _qj(i, j):
            return jnp.maximum(j, (i * block_k) // block_q)
    else:
        def _qj(i, j):
            return j

    q_by_g = pl.BlockSpec(
        (1, 1, block_q, D),
        lambda b, h, i, g, j: (b, h * group + g, _qj(i, j), 0),
    )
    row_by_g = pl.BlockSpec(
        (1, 1, block_q, _LANES),
        lambda b, h, i, g, j: (b, h * group + g, _qj(i, j), 0),
    )
    kv_blk = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, i, g, j: (b, h, i, 0)
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
            causal=causal,
        ),
        grid=(B, KV, Sk // block_k, group, S // block_q),
        in_specs=[q_by_g, kv_blk, kv_blk, q_by_g, row_by_g, row_by_g],
        out_specs=[kv_blk, kv_blk],
        out_shape=[
            jax.ShapeDtypeStruct((B, KV, Sk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, KV, Sk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, causal, block_q, block_k, interpret, resident):
    out, lse = _flash_fwd(
        q, k, v, causal, block_q, block_k, interpret, resident
    )
    return out, lse[..., 0]


def _flash_lse_vjp_fwd(q, k, v, causal, block_q, block_k, interpret,
                       resident):
    out, lse = _flash_fwd(
        q, k, v, causal, block_q, block_k, interpret, resident
    )
    return (out, lse[..., 0]), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(causal, block_q, block_k, interpret, resident, res,
                       g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    return _flash_bwd(
        q, k, v, out, lse, g_out, causal, block_q, block_k, interpret,
        g_lse=g_lse, resident=resident,
    )


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    resident: bool | None = None,
) -> jnp.ndarray:
    """Flash attention over [B, S, H, D] tensors (model layout).

    K/V may carry fewer heads than Q (grouped-query); sharing is resolved
    in the kernel's index maps, never materialized. Differentiable (custom
    VJP, flash-style recompute backward). ``interpret=None`` auto-selects
    interpreter mode off-TPU so the CPU test mesh runs the same code.
    ``block_q``/``block_k`` default to the measured tuned tiles for the
    problem size (:func:`default_blocks`); pass explicit values to
    override (tiling experiments, VMEM-constrained compositions).
    ``resident=None`` auto-selects the forward/dq kernel layout —
    VMEM-resident K/V bands (fast) when they fit, grid-streamed
    k-blocks (any length) beyond — by band size; pass a bool to force
    one (tests, experiments).
    """
    # One custom-vjp path serves both public entry points: with lse
    # unused its cotangent is zero and the backward's Δ fold is a no-op.
    out, _ = flash_attention_with_lse(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, resident=resident,
    )
    return out


def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    resident: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Flash attention returning ``(out [B,S,H,D], lse [B,H,S] f32)``.

    The per-row log-sum-exp is what makes flash partials *composable*:
    two results over the same queries but different keys merge exactly as

        lse = logaddexp(lse_a, lse_b)
        out = out_a·e^{lse_a−lse} + out_b·e^{lse_b−lse}

    which is how parallel.ring's zigzag ring runs this kernel per K/V
    block and still matches dense attention bit-for-tolerance. Both
    outputs are differentiable: the lse cotangent folds into the
    backward's Δ term (see _flash_bwd), so the gradient kernels are the
    same three used by :func:`flash_attention`.
    """
    if interpret is None:
        interpret = _interpret_default()
    B, S, H, D = q.shape
    KV = k.shape[2]
    if H % KV:
        raise ValueError(f"q heads ({H}) must be a multiple of kv heads ({KV})")
    if block_q is None or block_k is None:
        tuned_q, tuned_k = default_blocks(
            S, k.shape[1], head_dim=D, itemsize=k.dtype.itemsize
        )
        block_q = tuned_q if block_q is None else block_q
        block_k = tuned_k if block_k is None else block_k
    out, lse = _flash_lse(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal, block_q, block_k, interpret, resident,
    )
    return out.transpose(0, 2, 1, 3), lse


def make_flash_attn(*, causal: bool = True, block_q: int | None = None,
                    block_k: int | None = None, interpret: bool | None = None):
    """``attn_impl`` factory for models.llama.forward / models.moe.forward.

    Blocks default to the measured tuned tiles (:func:`default_blocks`)."""

    def attn(q, k, v):
        return flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )

    return attn
