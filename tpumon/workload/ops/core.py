"""Functional core ops for the workload model.

Written XLA-first: pure functions over static shapes, fusable elementwise
chains, no data-dependent Python control flow — everything here traces once
under ``jit`` and fuses into the surrounding matmuls (HBM-bandwidth rule:
elementwise work rides the MXU ops' memory traffic).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm in float32 accumulation, cast back to the input dtype."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale * weight).astype(dtype)


def rope_freqs(head_dim: int, max_seq: int, theta: float = 10000.0) -> jnp.ndarray:
    """Precompute rotary-embedding angles [max_seq, head_dim // 2]."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    return jnp.outer(t, inv)


def apply_rope(x: jnp.ndarray, freqs: jnp.ndarray) -> jnp.ndarray:
    """Rotate pairs of channels; x is [B, S, H, D], freqs [S, D//2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = jnp.cos(freqs)[None, :, None, :]
    sin = jnp.sin(freqs)[None, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)
