from tpumon.workload.ops.core import apply_rope, rms_norm, rope_freqs

__all__ = ["apply_rope", "rms_norm", "rope_freqs"]
