"""Live workload throughput/MFU telemetry for the harness /metrics port.

The harness already exposes collective-op counters (hlo_counters); this
module adds the *throughput* side: steps, loss, windowed steps/s, and
live MFU — so one Grafana view can correlate the workload's own model
FLOPs utilization with the chip-side ``accelerator_duty_cycle_percent``
the node exporter scrapes (SURVEY.md §3.5: the monitor observes traffic
it did not generate; the workload publishes what it *meant* to drive).

Sampling discipline: the harness's fast loop is pipelined — it enqueues
steps without host syncs, which is what makes its traffic realistic. So
stats are recorded on a *window* boundary (every ``stats_every`` steps
the loop blocks on the latest loss and records the window), not per
step: one sync per window keeps the dispatch pipeline full between
samples and makes the windowed steps/s exact rather than estimated from
dispatch cadence.
"""

from __future__ import annotations

import threading


class WorkloadStats:
    """Thread-safe run telemetry shared between the train loop (writer)
    and a Prometheus collector on the metrics port (reader)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._steps_total = 0
        self._last_loss: float | None = None
        self._window_rate: float | None = None
        self._flops_per_step = 0.0
        self._tokens_per_step = 0
        self._peak_flops_total: float | None = None
        self._axes: dict[str, int] = {}

    def configure(
        self,
        *,
        flops_per_step: float,
        tokens_per_step: int,
        peak_flops_total: float | None,
        axes: dict[str, int],
    ) -> None:
        """Static run facts, set once the model/mesh are known.

        ``peak_flops_total`` is the summed published bf16 peak of the run's
        devices, or None when unknown (CPU dryruns) — MFU is then absent
        from the exposition rather than computed against a made-up peak
        (same rule as workload.flops.mfu).
        """
        with self._lock:
            self._flops_per_step = float(flops_per_step)
            self._tokens_per_step = int(tokens_per_step)
            self._peak_flops_total = peak_flops_total
            self._axes = dict(axes)

    def record(self, loss: float, steps: int, seconds: float) -> None:
        """One window: ``steps`` optimizer steps took ``seconds`` wall."""
        with self._lock:
            self._steps_total += int(steps)
            self._last_loss = float(loss)
            if steps > 0 and seconds > 0:
                self._window_rate = steps / seconds

    def snapshot(self) -> dict:
        with self._lock:
            rate = self._window_rate
            mfu = None
            if (
                rate is not None
                and self._peak_flops_total
                and self._flops_per_step
            ):
                mfu = self._flops_per_step * rate / self._peak_flops_total
            return {
                "steps_total": self._steps_total,
                "last_loss": self._last_loss,
                "steps_per_second": rate,
                "tokens_per_second": (
                    rate * self._tokens_per_step if rate is not None else None
                ),
                "model_flops_per_step": self._flops_per_step,
                "mfu": mfu,
                "axes": dict(self._axes),
            }


def stats_families(stats: WorkloadStats):
    """Prometheus families for the harness /metrics endpoint. One
    snapshot serves the whole scrape (coherent steps/rate/mfu)."""
    from prometheus_client.core import (
        CounterMetricFamily,
        GaugeMetricFamily,
    )

    snap = stats.snapshot()

    steps = CounterMetricFamily(
        "workload_steps_total",
        "Optimizer steps completed by the harness train loop.",
    )
    steps.add_metric((), snap["steps_total"])
    yield steps

    if snap["axes"]:
        mesh = GaugeMetricFamily(
            "workload_mesh_info",
            "Parallelism degrees of the running workload's mesh.",
            labels=("dp", "tp", "sp", "pp", "ep"),
        )
        mesh.add_metric(
            tuple(str(snap["axes"].get(a, 1)) for a in ("dp", "tp", "sp", "pp", "ep")),
            1,
        )
        yield mesh

    if snap["last_loss"] is not None:
        loss = GaugeMetricFamily(
            "workload_loss",
            "Training loss at the most recent recorded window boundary.",
        )
        loss.add_metric((), snap["last_loss"])
        yield loss

    if snap["steps_per_second"] is not None:
        rate = GaugeMetricFamily(
            "workload_steps_per_second",
            "Optimizer steps per second over the most recent window "
            "(windowed host sync; the loop stays pipelined between windows).",
        )
        rate.add_metric((), snap["steps_per_second"])
        yield rate

    if snap["tokens_per_second"] is not None:
        toks = GaugeMetricFamily(
            "workload_tokens_per_second",
            "Training tokens per second over the most recent window.",
        )
        toks.add_metric((), snap["tokens_per_second"])
        yield toks

    if snap["model_flops_per_step"]:
        fl = GaugeMetricFamily(
            "workload_model_flops_per_step",
            "Model FLOPs one optimizer step executes "
            "(tpumon.workload.flops exact per-matmul accounting).",
        )
        fl.add_metric((), snap["model_flops_per_step"])
        yield fl

    if snap["mfu"] is not None:
        mfu = GaugeMetricFamily(
            "workload_mfu_ratio",
            "Live model FLOPs utilization vs the devices' published bf16 "
            "peak, over the most recent window (absent when the peak is "
            "unknown, e.g. CPU; correlate with "
            "accelerator_duty_cycle_percent).",
        )
        mfu.add_metric((), snap["mfu"])
        yield mfu


class StatsCollector:
    """Registry adapter: ``registry.register(StatsCollector(stats))``."""

    def __init__(self, stats: WorkloadStats) -> None:
        self._stats = stats

    def collect(self):
        return stats_families(self._stats)
