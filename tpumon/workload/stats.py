"""Live workload throughput/MFU/step-phase telemetry for the harness
/metrics port.

The harness already exposes collective-op counters (hlo_counters); this
module adds the *throughput and step-phase* side: steps, loss, windowed
steps/s, live MFU, per-step phase wall times (fwd/bwd/optimizer),
collective-wait fraction, checkpoint save/restore spans, and a
terminating flag — the ``tpu_step_*`` families the node exporter's
lifecycle plane (tpumon/lifecycle) probes to close the monitor↔trainer
loop (ISSUE 10): a step-time regression becomes attributable instead of
an anonymous duty-cycle dip, and a SIGTERM-marked page is the
preemption signature the lifecycle classifier keys on.

Sampling discipline: the harness's fast loop is pipelined — it enqueues
steps without host syncs, which is what makes its traffic realistic. So
stats are recorded on a *window* boundary (every ``stats_every`` steps
the loop blocks on the latest loss and records the window), not per
step: one sync per window keeps the dispatch pipeline full between
samples and makes the windowed steps/s exact rather than estimated from
dispatch cadence. Phase timings are likewise measured at most once per
window (tpumon/workload/harness.py ``--phase-stats``), never inside the
pipelined fast path.
"""

from __future__ import annotations

import threading


class WorkloadStats:
    """Thread-safe run telemetry shared between the train loop (writer)
    and a Prometheus collector on the metrics port (reader)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._steps_total = 0  # guarded-by: self._lock
        self._start_step = 0  # guarded-by: self._lock
        self._last_loss: float | None = None  # guarded-by: self._lock
        self._window_rate: float | None = None  # guarded-by: self._lock
        self._flops_per_step = 0.0  # guarded-by: self._lock
        self._tokens_per_step = 0  # guarded-by: self._lock
        self._peak_flops_total: float | None = None  # guarded-by: self._lock
        self._axes: dict[str, int] = {}  # guarded-by: self._lock
        #: phase -> last measured wall seconds (fwd/bwd/optimizer).
        self._phase_s: dict[str, float] = {}  # guarded-by: self._lock
        #: Collective-wait fraction of step wall time over the last
        #: window (None until the harness computes one).
        self._collective_wait: float | None = None  # guarded-by: self._lock
        #: op -> (count, last span seconds) for checkpoint save/restore.
        self._checkpoints: dict[str, tuple[int, float]] = {}  # guarded-by: self._lock
        #: SIGTERM observed: the preemption signature the lifecycle
        #: classifier keys on (stays 1 for the rest of the process).
        self._terminating = False  # guarded-by: self._lock

    def configure(
        self,
        *,
        flops_per_step: float,
        tokens_per_step: int,
        peak_flops_total: float | None,
        axes: dict[str, int],
        start_step: int = 0,
    ) -> None:
        """Static run facts, set once the model/mesh are known.

        ``peak_flops_total`` is the summed published bf16 peak of the run's
        devices, or None when unknown (CPU dryruns) — MFU is then absent
        from the exposition rather than computed against a made-up peak
        (same rule as workload.flops.mfu). ``start_step`` offsets the
        global step counter after a checkpoint resume, so ``tpu_step_
        counter`` is the training-global step, not the process-local one.
        """
        with self._lock:
            self._flops_per_step = float(flops_per_step)
            self._tokens_per_step = int(tokens_per_step)
            self._peak_flops_total = peak_flops_total
            self._axes = dict(axes)
            self._start_step = int(start_step)

    def set_start_step(self, start_step: int) -> None:
        """Checkpoint-resume offset for the training-global step counter
        (known only after the restore, i.e. after configure())."""
        with self._lock:
            self._start_step = int(start_step)

    def record(self, loss: float, steps: int, seconds: float) -> None:
        """One window: ``steps`` optimizer steps took ``seconds`` wall."""
        with self._lock:
            self._steps_total += int(steps)
            self._last_loss = float(loss)
            if steps > 0 and seconds > 0:
                self._window_rate = steps / seconds

    def record_phases(self, phases: dict[str, float]) -> None:
        """Last measured per-phase wall seconds (phase ∈ fwd/bwd/
        optimizer; harness --phase-stats, one instrumented step per
        window — never the pipelined fast path)."""
        with self._lock:
            self._phase_s = {
                k: float(v) for k, v in phases.items() if v is not None
            }

    def record_collective_wait(self, fraction: float) -> None:
        """Collective-wait fraction of step wall time over the last
        window (clamped to [0, 1] — a measurement artifact must not
        exceed the step it is a fraction of)."""
        with self._lock:
            self._collective_wait = min(1.0, max(0.0, float(fraction)))

    def record_checkpoint(self, op: str, seconds: float) -> None:
        """One checkpoint span (op ∈ save/restore)."""
        with self._lock:
            count, _ = self._checkpoints.get(op, (0, 0.0))
            self._checkpoints[op] = (count + 1, float(seconds))

    def mark_terminating(self) -> None:
        """SIGTERM arrived: flag the page for the grace window — the
        lifecycle classifier's preemption signature."""
        with self._lock:
            self._terminating = True

    def snapshot(self) -> dict:
        with self._lock:
            rate = self._window_rate
            mfu = None
            if (
                rate is not None
                and self._peak_flops_total
                and self._flops_per_step
            ):
                mfu = self._flops_per_step * rate / self._peak_flops_total
            return {
                "steps_total": self._steps_total,
                "step_counter": self._start_step + self._steps_total,
                "last_loss": self._last_loss,
                "steps_per_second": rate,
                "step_seconds": (1.0 / rate) if rate else None,
                "tokens_per_second": (
                    rate * self._tokens_per_step if rate is not None else None
                ),
                "model_flops_per_step": self._flops_per_step,
                "mfu": mfu,
                "axes": dict(self._axes),
                "phases": dict(self._phase_s),
                "collective_wait_fraction": self._collective_wait,
                "checkpoints": dict(self._checkpoints),
                "terminating": self._terminating,
            }


def stats_families(stats: WorkloadStats):
    """Prometheus families for the harness /metrics endpoint. One
    snapshot serves the whole scrape (coherent steps/rate/mfu/phases)."""
    from prometheus_client.core import (
        CounterMetricFamily,
        GaugeMetricFamily,
    )

    snap = stats.snapshot()

    steps = CounterMetricFamily(
        "workload_steps_total",
        "Optimizer steps completed by the harness train loop.",
    )
    steps.add_metric((), snap["steps_total"])
    yield steps

    counter = GaugeMetricFamily(
        "tpu_step_counter",
        "Training-global optimizer step (start step after a checkpoint "
        "resume plus steps completed by this process).",
    )
    counter.add_metric((), snap["step_counter"])
    yield counter

    if snap["axes"]:
        mesh = GaugeMetricFamily(
            "workload_mesh_info",
            "Parallelism degrees of the running workload's mesh.",
            labels=("dp", "tp", "sp", "pp", "ep"),
        )
        mesh.add_metric(
            tuple(str(snap["axes"].get(a, 1)) for a in ("dp", "tp", "sp", "pp", "ep")),
            1,
        )
        yield mesh

    if snap["last_loss"] is not None:
        loss = GaugeMetricFamily(
            "workload_loss",
            "Training loss at the most recent recorded window boundary.",
        )
        loss.add_metric((), snap["last_loss"])
        yield loss

    if snap["steps_per_second"] is not None:
        rate = GaugeMetricFamily(
            "workload_steps_per_second",
            "Optimizer steps per second over the most recent window "
            "(windowed host sync; the loop stays pipelined between windows).",
        )
        rate.add_metric((), snap["steps_per_second"])
        yield rate

    if snap["step_seconds"] is not None:
        dur = GaugeMetricFamily(
            "tpu_step_duration_seconds",
            "Mean wall seconds per optimizer step over the most recent "
            "window (1 / workload_steps_per_second; the lifecycle "
            "plane's step-time-regression input).",
        )
        dur.add_metric((), snap["step_seconds"])
        yield dur

    if snap["phases"]:
        phase = GaugeMetricFamily(
            "tpu_step_phase_seconds",
            "Wall seconds of the last instrumented step's phases "
            "(phase ∈ fwd/bwd/optimizer; measured at most once per "
            "stats window, never inside the pipelined fast path).",
            labels=("phase",),
        )
        for name in sorted(snap["phases"]):
            phase.add_metric((name,), snap["phases"][name])
        yield phase

    if snap["collective_wait_fraction"] is not None:
        wait = GaugeMetricFamily(
            "tpu_step_collective_wait_fraction",
            "Fraction of step wall time spent inside collective ops "
            "over the most recent window (HLO-logger latency sums over "
            "window wall time; ICI-contention signal — correlate with "
            "accelerator_collective_latency_microseconds).",
        )
        wait.add_metric((), snap["collective_wait_fraction"])
        yield wait

    if snap["checkpoints"]:
        spans = GaugeMetricFamily(
            "tpu_step_checkpoint_seconds",
            "Wall seconds of the most recent checkpoint span by op "
            "(save/restore) — restore spans are the restore-storm "
            "signature the lifecycle classifier keys on.",
            labels=("op",),
        )
        totals = CounterMetricFamily(
            "tpu_step_checkpoints",
            "Checkpoint spans completed since process start, by op "
            "(save/restore).",
            labels=("op",),
        )
        for op in sorted(snap["checkpoints"]):
            count, last_s = snap["checkpoints"][op]
            spans.add_metric((op,), last_s)
            totals.add_metric((op,), float(count))
        yield spans
        yield totals

    terminating = GaugeMetricFamily(
        "tpu_step_terminating",
        "1 once SIGTERM reached the harness (preemption grace window "
        "in progress — the lifecycle classifier's preemption "
        "signature); 0 while training normally.",
    )
    terminating.add_metric((), 1.0 if snap["terminating"] else 0.0)
    yield terminating

    if snap["tokens_per_second"] is not None:
        toks = GaugeMetricFamily(
            "workload_tokens_per_second",
            "Training tokens per second over the most recent window.",
        )
        toks.add_metric((), snap["tokens_per_second"])
        yield toks

    if snap["model_flops_per_step"]:
        fl = GaugeMetricFamily(
            "workload_model_flops_per_step",
            "Model FLOPs one optimizer step executes "
            "(tpumon.workload.flops exact per-matmul accounting).",
        )
        fl.add_metric((), snap["model_flops_per_step"])
        yield fl

    if snap["mfu"] is not None:
        mfu = GaugeMetricFamily(
            "workload_mfu_ratio",
            "Live model FLOPs utilization vs the devices' published bf16 "
            "peak, over the most recent window (absent when the peak is "
            "unknown, e.g. CPU; correlate with "
            "accelerator_duty_cycle_percent).",
        )
        mfu.add_metric((), snap["mfu"])
        yield mfu


class StatsCollector:
    """Registry adapter: ``registry.register(StatsCollector(stats))``."""

    def __init__(self, stats: WorkloadStats) -> None:
        self._stats = stats

    def collect(self):
        return stats_families(self._stats)
