"""Flash-vs-XLA attention benchmark (SURVEY.md §6 "measure and record").

Times the pallas flash-attention kernel (ops.flash_attention) against the
XLA einsum attention path (the models.llama default) on whatever platform
jax resolves — the real TPU when present, interpret-mode CPU otherwise —
and prints one JSON line per (impl, seq) with forward and forward+backward
wall times. The numbers land in BASELINE.md; an honest regression is a
result, not a failure.

Run:  python -m tpumon.workload.bench_attention --seq 512 1024 2048
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time


def xla_attention(q, k, v):
    """The models.llama einsum path, isolated (GQA repeat + masked
    softmax), kept numerically identical to models.llama._attention.

    The causal mask is built IN-GRAPH from iota, not closed over as a
    host array: a materialized [S, S] f32 mask at seq 8192 is a 268 MB
    program constant — large enough to be rejected by remote-compile
    transports (observed live: HTTP 413 from the axon tunnel).
    """
    import jax
    import jax.numpy as jnp

    H = q.shape[2]
    KV = k.shape[2]
    rep = H // KV
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(q.shape[-1]))
    pos = jnp.arange(q.shape[1])
    scores = jnp.where(
        pos[None, None, :, None] >= pos[None, None, None, :], scores, -1e9
    )
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _chain(fn, inner: int):
    """Repeat ``fn`` ``inner`` times inside ONE dispatch, serially chained.

    This host reaches its TPU through a tunnel whose per-dispatch
    round-trip (~70 ms, measured) swamps sub-millisecond kernels, so the
    kernel is iterated inside a single ``lax.scan`` and the wall time
    divided by ``inner``. Each iteration's q input carries a vanishing
    contribution from the previous output — a real data dependency, so
    XLA can neither hoist the loop-invariant computation out of the scan
    nor overlap iterations.
    """
    import jax
    import jax.numpy as jnp

    def run(q, k, v):
        def body(carry, _):
            out = fn(q + carry, k, v)
            lead = out[0] if isinstance(out, tuple) else out
            feed = (lead.ravel()[0] * jnp.asarray(1e-8, lead.dtype)).astype(
                q.dtype
            )
            return feed, ()
        feed, _ = jax.lax.scan(
            body, jnp.zeros((), q.dtype), None, length=inner
        )
        return feed

    return jax.jit(run)


def _time(fn, *args, iters: int, inner: int = 1) -> float:
    """Median wall seconds per inner call after a compile+warmup call.

    Two measurement defenses, both needed on remote-dispatch transports
    (established empirically against the axon tunnel at seq 8192, where a
    naive repeat-same-operands + block_until_ready loop read 0.003 ms per
    iteration for a kernel that really takes ~30 ms):

    - **The barrier is a host read** (``jax.device_get``), not
      ``block_until_ready``: through the tunnel, block_until_ready can
      resolve before device execution completes, silently timing dispatch
      instead of compute. A host read of the result cannot return early —
      and with ``inner > 1`` the chained output is a scalar, so the
      forced transfer adds nothing to the measurement.
    - **Every timing iteration uses a distinct first operand** (tiny
      additive perturbation, same shape/dtype so nothing recompiles): the
      transport can serve a repeated (executable, operands) pair from its
      resolved-result cache.

    With both in place, timings match an inline-dependency construction
    to within 2% and scale as S² across 1k→8k, as attention must.

    Each perturbed operand is built just before its iteration and dropped
    after it (never all iters at once — at seq 8192 ten pinned 64 MB
    copies would add real HBM pressure to a bench that probes the OOM
    boundary), and the timed function always returns a SCALAR so the
    barrier's host read transfers nothing: the chain already yields one
    at ``inner > 1``; at ``inner == 1`` the outputs are summed in-graph
    (a reduction XLA cannot dead-code-eliminate — returning a single
    *element* instead would let it skip most of the computation).
    """
    import jax
    import jax.numpy as jnp

    if inner > 1:
        timed = _chain(fn, inner)
    else:
        def timed(*a, _fn=fn):
            out = _fn(*a)
            return sum(
                jnp.sum(leaf.astype(jnp.float32))
                for leaf in jax.tree.leaves(out)
            )

        timed = jax.jit(timed)

    def read(out):
        return jax.device_get(out)

    read(timed(*args))  # compile + warmup
    times = []
    for i in range(iters):
        va = (args[0] + jnp.asarray((i + 1) * 1e-3, args[0].dtype),) + args[1:]
        jax.block_until_ready(va[0])
        t0 = time.perf_counter()
        read(timed(*va))
        times.append(time.perf_counter() - t0)
        del va
    times.sort()
    return times[len(times) // 2] / inner


def _timed_row(base: dict, fwd, bwd, q, k, v, *, iters, inner, attn_flops,
               results, out) -> None:
    """Time one impl (fwd then fwd+bwd) into a result row; an impl that
    cannot run at this configuration yields an error row instead — with
    the already-measured forward kept when only backward fails (backward
    needs strictly more memory, so that is the OOM boundary's shape).
    Shared by the flash-vs-XLA bench and the tiling sweep so the timing
    protocol and error classification cannot drift between modes."""
    row = dict(base)
    try:
        fwd_s = _time(fwd, q, k, v, iters=iters, inner=inner)
        row.update(
            fwd_ms=round(fwd_s * 1e3, 3),
            fwd_tflops=round(attn_flops / fwd_s / 1e12, 2),
        )
        bwd_s = _time(bwd, q, k, v, iters=iters, inner=inner)
        row.update(fwd_bwd_ms=round(bwd_s * 1e3, 3))
    except Exception as exc:
        # An impl failing at a size another configuration handles IS the
        # benchmark's most interesting output (observed live: the XLA
        # path's [B, H, S, S] f32 scores OOM a 16 GB v5e at seq 8192
        # while the flash kernel runs) — report and keep measuring.
        msg = str(exc)
        m = re.search(r"Ran out of memory[^\n]{0,160}", msg)
        row.update(
            error=(m.group(0) if m else msg.strip().split("\n")[0][:200]),
            oom=bool(m or "memory" in msg.lower()),
        )
    results.append(row)
    print(json.dumps(row), file=out, flush=True)


def _bench_setup(batch, heads, kv_heads, head_dim, seq, inner):
    """Shared per-seq setup for both bench modes: platform/inner
    resolution, deterministic q/k/v, and the attention FLOPs count
    (scores + probs·V matmuls; bwd adds 2×) — in one place so the two
    modes' numbers cannot desynchronize."""
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    kind = getattr(jax.devices()[0], "device_kind", platform)
    if inner is None:
        # Amortize the dispatch round-trip on real hardware; interpret
        # mode (CPU) is slow enough per call that inner=1 is right.
        inner = 16 if platform == "tpu" else 1
    kq, kk, kv_ = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (batch, seq, heads, head_dim), jnp.bfloat16)
    k = jax.random.normal(kk, (batch, seq, kv_heads, head_dim), jnp.bfloat16)
    v = jax.random.normal(kv_, (batch, seq, kv_heads, head_dim), jnp.bfloat16)
    attn_flops = 2 * 2 * batch * seq * seq * heads * head_dim
    return platform, kind, inner, q, k, v, attn_flops


def _train_of(fwd):
    """fwd → jitted grad of a scalar loss over it (the timed bwd path)."""
    import jax
    import jax.numpy as jnp

    def loss(q, k, v):
        return jnp.sum(fwd(q, k, v).astype(jnp.float32))

    return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))


def bench(
    batch: int = 4,
    heads: int = 8,
    kv_heads: int = 4,
    head_dim: int = 128,
    seqs: tuple[int, ...] = (512, 1024, 2048),
    iters: int = 10,
    inner: int | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    out=sys.stdout,
) -> list[dict]:
    import jax

    from tpumon.workload.ops.flash_attention import (
        _pick_block,
        default_blocks,
        make_flash_attn,
    )

    flash = make_flash_attn(block_q=block_q, block_k=block_k)
    results = []
    for seq in seqs:
        platform, kind, seq_inner, q, k, v, attn_flops = _bench_setup(
            batch, heads, kv_heads, head_dim, seq, inner
        )
        # Rows record the blocks actually used: explicit overrides, else
        # the tuned per-seq table — in either case clamped exactly as the
        # kernel clamps them (_pick_block), so a seq the block doesn't
        # divide is never attributed to tiles that didn't run.
        tuned = default_blocks(seq, seq)
        row_bq = _pick_block(seq, block_q if block_q is not None else tuned[0])
        row_bk = _pick_block(seq, block_k if block_k is not None else tuned[1])
        impls = {
            "xla": jax.jit(xla_attention),
            "flash": jax.jit(lambda q, k, v: flash(q, k, v)),
        }
        for name, fwd in impls.items():
            base = {
                "impl": name,
                "platform": platform,
                "device_kind": kind,
                "batch": batch,
                "heads": heads,
                "kv_heads": kv_heads,
                "head_dim": head_dim,
                "seq": seq,
                "inner": seq_inner,
            }
            if name == "flash":
                base["block_q"], base["block_k"] = row_bq, row_bk
            _timed_row(
                base, fwd, _train_of(fwd), q, k, v, iters=iters,
                inner=seq_inner, attn_flops=attn_flops, results=results,
                out=out,
            )
    return results


def sweep_blocks(
    batch: int = 4,
    heads: int = 8,
    kv_heads: int = 4,
    head_dim: int = 128,
    seqs: tuple[int, ...] = (4096,),
    iters: int = 3,
    inner: int | None = None,
    blocks: tuple[int, ...] = (128, 256, 512),
    out=sys.stdout,
) -> list[dict]:
    """Flash-kernel tiling sweep: one row per (seq, block_q, block_k).

    Reproduces the BASELINE.md tiling table with one command:
    ``python -m tpumon.workload.bench_attention --sweep-blocks --seq 4096``.
    Forward and forward+backward both timed; a tiling that OOMs or fails
    to compile reports an error row like the main bench. Rows record the
    EFFECTIVE block sizes after ``_pick_block`` clamping (alongside the
    requested ones) and tilings that clamp to an already-timed effective
    pair are skipped — at seq 64 the whole {128,256,512}² grid is one
    (64, 64) kernel, and timing it nine times under nine labels would
    make the table a fiction.
    """
    import jax

    from tpumon.workload.ops.flash_attention import (
        _kv_fits_resident, _pick_block, make_flash_attn,
    )

    results = []
    for seq in seqs:
        platform, kind, seq_inner, q, k, v, attn_flops = _bench_setup(
            batch, heads, kv_heads, head_dim, seq, inner
        )
        # In the streamed-layout regime (K/V bands past the VMEM cliff)
        # the measured winners are much larger tiles, so the sweep grid
        # grows to cover them (BASELINE.md "single-chip long context":
        # square 1024×1024 tiles ranked fastest at seq 16384, 1.6×
        # over the resident-regime 256×512). Regime prediction uses the
        # same itemsize the kernel's own layout selection sees.
        seq_blocks = blocks
        if not _kv_fits_resident(seq, head_dim, k.dtype.itemsize):
            seq_blocks = tuple(blocks) + (1024, 2048)
        seen: set = set()
        for bq in seq_blocks:
            for bk in seq_blocks:
                eff = (_pick_block(seq, bq), _pick_block(seq, bk))
                if eff in seen:
                    continue
                seen.add(eff)
                tiled = make_flash_attn(block_q=bq, block_k=bk)
                fwd = jax.jit(lambda q, k, v, f=tiled: f(q, k, v))
                base = {
                    "impl": "flash",
                    "platform": platform,
                    "device_kind": kind,
                    "batch": batch,
                    "heads": heads,
                    "kv_heads": kv_heads,
                    "head_dim": head_dim,
                    "seq": seq,
                    "block_q": bq,
                    "block_k": bk,
                    "effective_block_q": eff[0],
                    "effective_block_k": eff[1],
                    "inner": seq_inner,
                }
                _timed_row(
                    base, fwd, _train_of(fwd), q, k, v, iters=iters,
                    inner=seq_inner, attn_flops=attn_flops, results=results,
                    out=out,
                )
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="bench_attention")
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--kv-heads", type=int, default=4)
    parser.add_argument("--head-dim", type=int, default=128)
    parser.add_argument("--seq", type=int, nargs="+", default=[512, 1024, 2048])
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument(
        "--inner", type=int, default=None,
        help="kernel iterations chained inside one dispatch (default: 16 "
        "on TPU to amortize dispatch latency, 1 elsewhere)",
    )
    parser.add_argument(
        "--block-q", type=int, default=None,
        help="flash kernel q-block rows (default: the measured tuned "
        "table, ops.flash_attention.default_blocks; rows record the "
        "values used)",
    )
    parser.add_argument(
        "--block-k", type=int, default=None,
        help="flash kernel k-block rows (default: tuned table)",
    )
    parser.add_argument(
        "--sweep-blocks", action="store_true",
        help="tiling sweep mode: time the flash kernel at every "
        "(block_q, block_k) in {128,256,512}^2 per --seq instead of the "
        "flash-vs-XLA comparison (reproduces BASELINE.md's tiling table)",
    )
    parser.add_argument(
        "--platform",
        choices=("auto", "cpu"),
        default="auto",
        help="force the jax platform; 'cpu' avoids a wedged TPU tunnel "
        "(the JAX_PLATFORMS env var is ignored when a TPU plugin is "
        "present, so this must be a flag — same caveat as the harness)",
    )
    args = parser.parse_args(argv)
    if args.platform == "cpu":
        from tpumon.workload.platform import force_cpu_devices

        force_cpu_devices(1)
    if args.sweep_blocks:
        sweep_blocks(
            batch=args.batch,
            heads=args.heads,
            kv_heads=args.kv_heads,
            head_dim=args.head_dim,
            seqs=tuple(args.seq),
            iters=args.iters,
            inner=args.inner,
        )
        return 0
    bench(
        batch=args.batch,
        heads=args.heads,
        kv_heads=args.kv_heads,
        head_dim=args.head_dim,
        seqs=tuple(args.seq),
        iters=args.iters,
        inner=args.inner,
        block_q=args.block_q,
        block_k=args.block_k,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
