"""Flash-vs-XLA attention benchmark (SURVEY.md §6 "measure and record").

Times the pallas flash-attention kernel (ops.flash_attention) against the
XLA einsum attention path (the models.llama default) on whatever platform
jax resolves — the real TPU when present, interpret-mode CPU otherwise —
and prints one JSON line per (impl, seq) with forward and forward+backward
wall times. The numbers land in BASELINE.md; an honest regression is a
result, not a failure.

Run:  python -m tpumon.workload.bench_attention --seq 512 1024 2048
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def xla_attention(q, k, v, mask):
    """The models.llama einsum path, isolated (GQA repeat + masked
    softmax), kept numerically identical to models.llama._attention."""
    import jax
    import jax.numpy as jnp

    H = q.shape[2]
    KV = k.shape[2]
    rep = H // KV
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(q.shape[-1])) + mask
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _chain(fn, inner: int):
    """Repeat ``fn`` ``inner`` times inside ONE dispatch, serially chained.

    This host reaches its TPU through a tunnel whose per-dispatch
    round-trip (~70 ms, measured) swamps sub-millisecond kernels, so the
    kernel is iterated inside a single ``lax.scan`` and the wall time
    divided by ``inner``. Each iteration's q input carries a vanishing
    contribution from the previous output — a real data dependency, so
    XLA can neither hoist the loop-invariant computation out of the scan
    nor overlap iterations.
    """
    import jax
    import jax.numpy as jnp

    def run(q, k, v):
        def body(carry, _):
            out = fn(q + carry, k, v)
            lead = out[0] if isinstance(out, tuple) else out
            feed = (lead.ravel()[0] * jnp.asarray(1e-8, lead.dtype)).astype(
                q.dtype
            )
            return feed, ()
        feed, _ = jax.lax.scan(
            body, jnp.zeros((), q.dtype), None, length=inner
        )
        return feed

    return jax.jit(run)


def _time(fn, *args, iters: int, inner: int = 1) -> float:
    """Median wall seconds per inner call after a compile+warmup call."""
    import jax

    timed = _chain(fn, inner) if inner > 1 else fn
    out = timed(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = timed(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2] / inner


def bench(
    batch: int = 4,
    heads: int = 8,
    kv_heads: int = 4,
    head_dim: int = 128,
    seqs: tuple[int, ...] = (512, 1024, 2048),
    iters: int = 10,
    inner: int | None = None,
    out=sys.stdout,
) -> list[dict]:
    import jax
    import jax.numpy as jnp

    from tpumon.workload.ops.flash_attention import make_flash_attn

    platform = jax.devices()[0].platform
    kind = getattr(jax.devices()[0], "device_kind", platform)
    if inner is None:
        # Amortize the dispatch round-trip on real hardware; interpret
        # mode (CPU) is slow enough per call that inner=1 is right.
        inner = 16 if platform == "tpu" else 1
    flash = make_flash_attn()
    results = []
    for seq in seqs:
        kq, kk, kv_ = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (batch, seq, heads, head_dim), jnp.bfloat16)
        k = jax.random.normal(kk, (batch, seq, kv_heads, head_dim), jnp.bfloat16)
        v = jax.random.normal(kv_, (batch, seq, kv_heads, head_dim), jnp.bfloat16)
        mask = jnp.triu(jnp.full((seq, seq), -1e9, jnp.float32), k=1)

        impls = {
            "xla": jax.jit(lambda q, k, v: xla_attention(q, k, v, mask)),
            "flash": jax.jit(lambda q, k, v: flash(q, k, v)),
        }

        def train_of(fwd):
            def loss(q, k, v):
                return jnp.sum(fwd(q, k, v).astype(jnp.float32))

            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        # Attention matmul FLOPs (scores + probs·V), fwd; bwd adds 2×.
        attn_flops = 2 * 2 * batch * seq * seq * heads * head_dim
        for name, fwd in impls.items():
            fwd_s = _time(fwd, q, k, v, iters=iters, inner=inner)
            bwd_s = _time(train_of(fwd), q, k, v, iters=iters, inner=inner)
            row = {
                "impl": name,
                "platform": platform,
                "device_kind": kind,
                "batch": batch,
                "heads": heads,
                "kv_heads": kv_heads,
                "head_dim": head_dim,
                "seq": seq,
                "inner": inner,
                "fwd_ms": round(fwd_s * 1e3, 3),
                "fwd_bwd_ms": round(bwd_s * 1e3, 3),
                "fwd_tflops": round(attn_flops / fwd_s / 1e12, 2),
            }
            results.append(row)
            print(json.dumps(row), file=out, flush=True)
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="bench_attention")
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--kv-heads", type=int, default=4)
    parser.add_argument("--head-dim", type=int, default=128)
    parser.add_argument("--seq", type=int, nargs="+", default=[512, 1024, 2048])
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument(
        "--inner", type=int, default=None,
        help="kernel iterations chained inside one dispatch (default: 16 "
        "on TPU to amortize dispatch latency, 1 elsewhere)",
    )
    parser.add_argument(
        "--platform",
        choices=("auto", "cpu"),
        default="auto",
        help="force the jax platform; 'cpu' avoids a wedged TPU tunnel "
        "(the JAX_PLATFORMS env var is ignored when a TPU plugin is "
        "present, so this must be a flag — same caveat as the harness)",
    )
    args = parser.parse_args(argv)
    if args.platform == "cpu":
        from tpumon.workload.platform import force_cpu_devices

        force_cpu_devices(1)
    bench(
        batch=args.batch,
        heads=args.heads,
        kv_heads=args.kv_heads,
        head_dim=args.head_dim,
        seqs=tuple(args.seq),
        iters=args.iters,
        inner=args.inner,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
