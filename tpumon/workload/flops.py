"""Model-FLOPs accounting → MFU (SURVEY.md §6 "measure and record").

``train_flops_per_step`` counts the matmul FLOPs one optimizer step
actually executes in this repo's models (models.llama / models.moe) —
not a 6·N·D approximation: attention scores/weighted-sum are counted at
the full S×S the additive-mask implementation really computes, GQA's
narrow KV projections are counted at KV heads, and the MoE FFN is scaled
by top_k routed experts. Backward is the standard 2× forward, so train =
3× forward.

``peak_flops_per_chip`` maps ``jax.Device.device_kind`` to the chip's
published peak dense bf16 FLOP/s; MFU = model FLOPs/s ÷ (peak × chips).
Unknown kinds (CPU hosts, future chips) return None and MFU is reported
as None rather than a number computed against a made-up peak.
"""

from __future__ import annotations

#: device_kind (as reported by jax) → peak dense bf16 FLOP/s per chip.
#: Public spec-sheet numbers: v4 275 TF, v5e 197 TF, v5p 459 TF,
#: v6e (Trillium) 918 TF.
PEAK_BF16_FLOPS: dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_chip(device) -> float | None:
    """Peak dense bf16 FLOP/s for a jax.Device, or None when unknown."""
    kind = getattr(device, "device_kind", "")
    if kind in PEAK_BF16_FLOPS:
        return PEAK_BF16_FLOPS[kind]
    # Prefix match tolerates suffixed kinds ("TPU v5 lite0" style).
    for known, peak in PEAK_BF16_FLOPS.items():
        if kind.startswith(known):
            return peak
    return None


def forward_flops(cfg, batch: int, seq: int) -> float:
    """Matmul FLOPs of one forward pass of models.llama / models.moe.

    Counts 2·m·n·k per matmul as executed: dense QKV/O projections (GQA
    narrow K/V), full-S² attention einsums (the additive-mask
    implementation computes the whole matrix), SwiGLU FFN (top_k-scaled
    + router for MoE), and the unembed projection.
    """
    B, S = batch, seq
    D = cfg.dim
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    F = cfg.ffn_dim
    L = cfg.n_layers

    qkvo = 2 * B * S * D * (H * HD) * 2 + 2 * B * S * D * (KV * HD) * 2
    attn = 2 * B * S * S * H * HD * 2  # scores + probs·V
    n_experts_active = getattr(cfg, "top_k", None)
    if n_experts_active is not None:  # MoE: routed SwiGLU + router
        ffn = 6 * B * S * D * F * n_experts_active
        ffn += 2 * B * S * D * cfg.n_experts  # router logits
    else:
        ffn = 6 * B * S * D * F
    unembed = 2 * B * S * D * cfg.vocab
    return float(L * (qkvo + attn + ffn) + unembed)


def train_flops_per_step(cfg, batch: int, seq: int) -> float:
    """One optimizer step: forward + backward (2× forward) = 3× forward."""
    return 3.0 * forward_flops(cfg, batch, seq)


def mfu(
    cfg, batch: int, seq: int, steps_per_sec: float, devices
) -> float | None:
    """Model FLOPs utilization in [0, 1], or None when the devices' peak
    is unknown (CPU dryruns) or throughput wasn't measured."""
    import math

    if not steps_per_sec or steps_per_sec <= 0 or not math.isfinite(steps_per_sec):
        return None
    peaks = [peak_flops_per_chip(d) for d in devices]
    if not peaks or any(p is None for p in peaks):
        return None
    model_flops = train_flops_per_step(cfg, batch, seq) * steps_per_sec
    return model_flops / sum(peaks)


__all__ = [
    "PEAK_BF16_FLOPS",
    "peak_flops_per_chip",
    "forward_flops",
    "train_flops_per_step",
    "mfu",
]
