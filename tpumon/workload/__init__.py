"""Workload harness (SURVEY.md §2.4, §3.5).

The monitor's measurement target: a compact JAX/pjit Llama-style training
step that generates real MXU work and ICI collective traffic so the
``collective_e2e_latency`` / ``ici_link_health`` / ``hlo_*`` metric
families light up in benchmarks and on dashboards. This is deliberately a
*workload generator*, not a training framework — the reference genre is a
telemetry stack and implements no parallelism of its own; it observes it.
"""
