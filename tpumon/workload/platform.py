"""Platform forcing for the workload CLIs.

NOTE (probed live on this jax build): with the axon TPU plugin
installed, the ``JAX_PLATFORMS`` *env var* is ignored — only the config
API sticks, and only before the backend initializes. Every workload CLI
therefore exposes ``--platform cpu`` as a flag and routes through this
one helper, so the workaround lives in exactly one place
(tests/conftest.py keeps its own copy because it must run before this
package is importable under a fresh interpreter).
"""

from __future__ import annotations

import os


def force_cpu_devices(n: int) -> None:
    """Pin jax to a virtual ``n``-device CPU platform.

    Must run before jax's backend initializes in this process; sets the
    host-platform device count via XLA_FLAGS (idempotent: an existing
    count in the env wins, matching the conftest behavior).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={max(n, 1)}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
