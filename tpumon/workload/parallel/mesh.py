"""Device mesh + shardings for the workload harness (SURVEY.md §3.5).

Idiomatic JAX SPMD: pick a Mesh, annotate shardings with PartitionSpecs,
let XLA insert the collectives — the all-reduces (data axis), all-gathers /
reduce-scatters (model axis), neighbor ppermutes (seq axis, ring
attention), and all-to-alls (expert axis, MoE dispatch) this generates over
ICI are exactly the traffic ``collective_e2e_latency`` / ``ici_link_health``
measure.

Axes (outermost → innermost; the most latency-sensitive collectives ride
the innermost, fastest ICI dimension):

- ``data``   — batch (DP): gradients all-reduce across it.
- ``stage``  — pipeline parallelism (PP): layers split into stages,
  activations hop stage→stage via ppermute (see parallel.pipeline).
- ``expert`` — expert parallelism (EP): MoE expert weights sharded,
  token dispatch/combine become all-to-alls (see models.moe).
- ``seq``    — sequence/context parallelism (SP): ring attention rotates
  K/V blocks around this axis (see parallel.ring).
- ``model``  — Megatron-style tensor parallelism: attention heads and FFN
  hidden dim column-sharded (…, "model"), output projections row-sharded
  ("model", …), vocab sharded in embed/unembed.

Unused axes are kept at size 1 so every PartitionSpec in the tree is valid
on every mesh shape.

Layer weights are stacked on a leading layer axis (lax.scan), so every
per-layer spec carries a leading ``None``.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "stage", "expert", "seq", "model")


def make_mesh(
    dp: int,
    tp: int,
    sp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices=None,
) -> Mesh:
    """A dp×pp×ep×sp×tp mesh over the given (default: all) devices."""
    devices = list(jax.devices()) if devices is None else list(devices)
    total = dp * tp * sp * pp * ep
    if total > len(devices):
        raise ValueError(
            f"mesh dp={dp} pp={pp} ep={ep} sp={sp} tp={tp} needs {total} "
            f"devices, have {len(devices)}"
        )
    grid = np.asarray(devices[:total]).reshape(dp, pp, ep, sp, tp)
    return Mesh(grid, axis_names=AXES)


def param_specs() -> dict:
    """PartitionSpec tree matching models.llama.init_params' structure."""
    return {
        "embed": P("model", None),  # vocab-sharded embedding
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "model"),
            "wk": P(None, None, "model"),
            "wv": P(None, None, "model"),
            "wo": P(None, "model", None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "model"),
            "w_up": P(None, None, "model"),
            "w_down": P(None, "model", None),
        },
        "final_norm": P(None),
        "unembed": P(None, "model"),
    }


def moe_param_specs() -> dict:
    """PartitionSpec tree matching models.moe.init_params' structure.

    Expert banks are sharded over the ``expert`` axis (EP) AND the
    ``model`` axis (TP within each expert) — dispatch/combine einsums
    against data-sharded activations then lower to all-to-alls.
    """
    return {
        "embed": P("model", None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "model"),
            "wk": P(None, None, "model"),
            "wv": P(None, None, "model"),
            "wo": P(None, "model", None),
            "mlp_norm": P(None, None),
            "router": P(None, None, None),
            "w_gate": P(None, "expert", None, "model"),
            "w_up": P(None, "expert", None, "model"),
            "w_down": P(None, "expert", "model", None),
        },
        "final_norm": P(None),
        "unembed": P(None, "model"),
    }


def make_expert_sharder(mesh: Mesh):
    """[E, B, C, D] expert-major activations → experts over 'expert' axis."""
    return _make_sharder(mesh, P("expert", "data", None, None))


def batch_spec() -> P:
    """Token sharding: batch over the data axis."""
    return P("data", None)


def activation_spec(sp: bool = False) -> P:
    """[B, S, D] activations: batch over data, seq over seq (SP)."""
    return P("data", "seq", None) if sp else P("data", None, None)


def make_act_sharder(mesh: Mesh, sp: bool = False):
    """x → x constrained to the activation sharding (for use under jit)."""
    return _make_sharder(mesh, activation_spec(sp))


def _make_sharder(mesh: Mesh, spec: P):
    sharding = NamedSharding(mesh, spec)

    def constrain(x):
        return jax.lax.with_sharding_constraint(x, sharding)

    return constrain


def zero1_shard_opt_state(opt_state, mesh: Mesh):
    """ZeRO-1: shard optimizer-state leaves over the ``data`` axis.

    Params stay replicated across DP (plain data parallelism), but the
    Adam moments — two full f32 copies of the model — need not be: each
    data shard keeps 1/dp of every moment leaf, the (replicated-over-dp)
    gradients update the local shard, and GSPMD inserts one all-gather
    of the *updates* when they are applied to the replicated params.
    That is the ZeRO-1 exchange, expressed entirely as shardings.

    Each leaf inherits its existing spec (tp/pp axes from the params it
    was ``optimizer.init``-ed from) and gains ``data`` on the first axis
    that is unsharded and divisible by the dp size; leaves with no such
    axis (scalars like the Adam step count, odd shapes) stay as they
    are. Returns the resharded state + the sharding tree (for the jit's
    ``out_shardings`` / donation round-trip).
    """
    dp = mesh.shape["data"]

    def reshard(leaf):
        # Every leaf lands on a mesh-wide NamedSharding (scalars and
        # non-divisible shapes replicated) so the tree is usable as the
        # jit's out_shardings — a leaf left on its eager single-device
        # sharding would conflict with the mesh.
        ndim = getattr(leaf, "ndim", 0)
        spec = list(getattr(getattr(leaf, "sharding", None), "spec", ()) or ())
        spec += [None] * (ndim - len(spec))
        if dp > 1:
            for i, (axis_entry, dim) in enumerate(zip(spec, leaf.shape)):
                if axis_entry is None and dim % dp == 0:
                    spec[i] = "data"
                    break
        return jax.device_put(leaf, NamedSharding(mesh, P(*spec)))

    state = jax.tree.map(reshard, opt_state)
    shardings = jax.tree.map(lambda x: x.sharding, state)
    return state, shardings


def shard_tree(tree, specs, mesh: Mesh):
    """Shard a pytree according to a matching PartitionSpec tree.

    Single-process: plain device_put. Multi-process (jax.distributed —
    the SURVEY §3.5 multi-host boundary): every process holds the full
    host array (identical PRNG seed), and make_array_from_callback hands
    each process exactly its addressable shards of the global Array.
    """

    def put(x, spec):
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() > 1:
            arr = np.asarray(x)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx]
            )
        return jax.device_put(x, sharding)

    return jax.tree.map(put, tree, specs)
