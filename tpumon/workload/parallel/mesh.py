"""Device mesh + shardings for the workload harness (SURVEY.md §3.5).

Idiomatic JAX SPMD: pick a Mesh, annotate shardings with PartitionSpecs,
let XLA insert the collectives — the all-reduces (data axis) and
all-gathers/reduce-scatters (model axis) this generates over ICI are
exactly the traffic ``collective_e2e_latency`` / ``ici_link_health``
measure.

Axes:

- ``data``  — batch (DP): gradients all-reduce across it.
- ``model`` — Megatron-style tensor parallelism: attention heads and FFN
  hidden dim are column-sharded (…, "model"), output projections
  row-sharded ("model", …), vocab sharded in embed/unembed.

Layer weights are stacked on a leading layer axis (lax.scan), so every
per-layer spec carries a leading ``None``.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp: int, tp: int, devices=None) -> Mesh:
    """A dp×tp mesh over the given (default: all) devices."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if dp * tp > len(devices):
        raise ValueError(
            f"mesh {dp}x{tp} needs {dp * tp} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[: dp * tp]).reshape(dp, tp)
    return Mesh(grid, axis_names=("data", "model"))


def param_specs() -> dict:
    """PartitionSpec tree matching models.llama.init_params' structure."""
    return {
        "embed": P("model", None),  # vocab-sharded embedding
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "model"),
            "wk": P(None, None, "model"),
            "wv": P(None, None, "model"),
            "wo": P(None, "model", None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "model"),
            "w_up": P(None, None, "model"),
            "w_down": P(None, "model", None),
        },
        "final_norm": P(None),
        "unembed": P(None, "model"),
    }


def batch_spec() -> P:
    return P("data", None)


def shard_tree(tree, specs, mesh: Mesh):
    """Shard a pytree according to a matching PartitionSpec tree.

    Single-process: plain device_put. Multi-process (jax.distributed —
    the SURVEY §3.5 multi-host boundary): every process holds the full
    host array (identical PRNG seed), and make_array_from_callback hands
    each process exactly its addressable shards of the global Array.
    """

    def put(x, spec):
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() > 1:
            arr = np.asarray(x)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx]
            )
        return jax.device_put(x, sharding)

    return jax.tree.map(put, tree, specs)
