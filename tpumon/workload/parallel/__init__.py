from tpumon.workload.parallel.mesh import (
    batch_spec,
    make_mesh,
    param_specs,
    shard_tree,
)

__all__ = ["batch_spec", "make_mesh", "param_specs", "shard_tree"]
