"""Ring attention: sequence/context parallelism over an ICI ring.

Long-context story for the workload harness (SURVEY.md §2.4/§5.7): the
sequence axis is sharded over the mesh's ``seq`` axis and K/V blocks rotate
around the ring with ``lax.ppermute`` while each device accumulates its
queries' attention with an online (flash-style) softmax. Every hop is a
neighbor-exchange on ICI — exactly the traffic ``ici_link_health`` /
``collective_e2e_latency`` measure, and the communication pattern scales to
sequence lengths no single chip's HBM could hold.

Numerics: accumulation is float32 throughout (running max ``m``, running
denominator ``l``, weighted-value accumulator ``o``); blocks that are fully
causally masked contribute exp(-BIG) ≈ 0 rather than NaN-producing -inf.

Composes under ``jit``: callers wrap :func:`ring_attention` in a
``shard_map`` over the mesh (see :func:`make_ring_attn`) and XLA overlaps
the ppermute with the per-block einsums.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# Finite stand-in for -inf: masked logits become exp(x - m) == 0 without
# ever forming inf - inf when an entire block is masked out.
_NEG_BIG = -1e30


def _block_attn(q32, k, v, mask, m, l, o, scale):
    """One online-softmax accumulation step against a single K/V block.

    q32 [B,S,H,D] f32; k/v [B,Skv,H,D]; mask [S,Skv] bool (True = attend);
    m/l [B,H,S] f32 running max/denominator; o [B,H,S,D] f32 accumulator.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q32, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    s = jnp.where(mask[None, None, :, :], s, _NEG_BIG)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)  # rescale factor for previous accumulators
    p = jnp.exp(s - m_new[..., None])
    l = l * alpha + jnp.sum(p, axis=-1)
    o = o * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l, o


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = True,
) -> jnp.ndarray:
    """Per-shard body: runs INSIDE shard_map, q/k/v are the local seq blocks.

    q [B, S_local, H, D]; k/v [B, S_local, KV, D] with the global sequence
    sharded over ``axis_name``. K/V may carry fewer (grouped-query) heads —
    the ring carries and ppermutes the KV-headed blocks and each device
    expands the block it just received right before its local attention
    step, so only KV-head bytes ever cross the ICI ring.
    """
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    rep = H // k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    q32 = q.astype(jnp.float32)
    pos = jnp.arange(S, dtype=jnp.int32)
    q_pos = my * S + pos  # global positions of the local queries

    m = jnp.full((B, H, S), _NEG_BIG, jnp.float32)
    l = jnp.zeros((B, H, S), jnp.float32)
    o = jnp.zeros((B, H, S, D), jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        m, l, o, k, v = carry
        # After i hops this device holds the block that started on (my - i).
        src = (my - i) % n
        kv_pos = src * S + pos
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
        else:
            mask = jnp.ones((S, S), bool)
        # Grouped-query expansion is local: the hop moved KV heads only.
        kh = jnp.repeat(k, rep, axis=2) if rep > 1 else k
        vh = jnp.repeat(v, rep, axis=2) if rep > 1 else v
        m, l, o = _block_attn(q32, kh, vh, mask, m, l, o, scale)
        # Rotate K/V one hop; the final rotation returns blocks to their
        # owners, keeping the loop body uniform for lax.fori_loop.
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return m, l, o, k, v

    m, l, o, k, v = jax.lax.fori_loop(0, n, step, (m, l, o, k, v))
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,S,H,D]


def make_ring_attn(
    mesh: Mesh, *, data_axis="data", seq_axis="seq", head_axis=None, causal=True
):
    """An attention callable q,k,v → out with the sequence axis ring-sharded.

    Returned fn takes q [B, S, H, D] and (possibly grouped-query) k/v
    [B, S, KV, D] under jit; shard_map splits batch over ``data_axis`` and
    sequence over ``seq_axis``. Pass ``head_axis="model"`` to compose with
    tensor parallelism: heads are independent in attention, so sharding
    them over the model axis keeps the TP layout through the ring with
    zero extra communication. K/V stay KV-headed on the ring (expansion is
    local, after each hop) unless the model axis doesn't divide KV — then
    they are pre-expanded to H so any tp ≤ H still shards.
    """
    spec = P(data_axis, seq_axis, head_axis, None)
    local = partial(ring_attention_local, axis_name=seq_axis, causal=causal)
    sharded = partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(lambda q, k, v: local(q, k, v))

    def attn(q, k, v):
        H, KV = q.shape[2], k.shape[2]
        if head_axis is not None and KV % mesh.shape[head_axis]:
            k = jnp.repeat(k, H // KV, axis=2)
            v = jnp.repeat(v, H // KV, axis=2)
        return sharded(q, k, v)

    return attn


def reference_attention(q, k, v, *, causal=True):
    """Dense O(S²) attention, same layout — numerics oracle for tests."""
    B, S, H, D = q.shape
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) / jnp.sqrt(jnp.float32(D))
    if causal:
        pos = jnp.arange(S)
        s = jnp.where(pos[:, None] >= pos[None, :], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
