"""Ring attention: sequence/context parallelism over an ICI ring.

Long-context story for the workload harness (SURVEY.md §2.4/§5.7): the
sequence axis is sharded over the mesh's ``seq`` axis and K/V blocks rotate
around the ring with ``lax.ppermute`` while each device accumulates its
queries' attention with an online (flash-style) softmax. Every hop is a
neighbor-exchange on ICI — exactly the traffic ``ici_link_health`` /
``collective_e2e_latency`` measure, and the communication pattern scales to
sequence lengths no single chip's HBM could hold.

Numerics: accumulation is float32 throughout (running max ``m``, running
denominator ``l``, weighted-value accumulator ``o``); blocks that are fully
causally masked contribute exp(-BIG) ≈ 0 rather than NaN-producing -inf.

Composes under ``jit``: callers wrap :func:`ring_attention` in a
``shard_map`` over the mesh (see :func:`make_ring_attn`) and XLA overlaps
the ppermute with the per-block einsums.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# Finite stand-in for -inf: masked logits become exp(x - m) == 0 without
# ever forming inf - inf when an entire block is masked out.
_NEG_BIG = -1e30


def _block_attn(q32, k, v, mask, m, l, o, scale):
    """One online-softmax accumulation step against a single K/V block.

    q32 [B,S,H,D] f32; k/v [B,Skv,H,D]; mask [S,Skv] bool (True = attend);
    m/l [B,H,S] f32 running max/denominator; o [B,H,S,D] f32 accumulator.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q32, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    s = jnp.where(mask[None, None, :, :], s, _NEG_BIG)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)  # rescale factor for previous accumulators
    p = jnp.exp(s - m_new[..., None])
    l = l * alpha + jnp.sum(p, axis=-1)
    o = o * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l, o


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = True,
) -> jnp.ndarray:
    """Per-shard body: runs INSIDE shard_map, q/k/v are the local seq blocks.

    q [B, S_local, H, D]; k/v [B, S_local, KV, D] with the global sequence
    sharded over ``axis_name``. K/V may carry fewer (grouped-query) heads —
    the ring carries and ppermutes the KV-headed blocks and each device
    expands the block it just received right before its local attention
    step, so only KV-head bytes ever cross the ICI ring.
    """
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    rep = H // k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    q32 = q.astype(jnp.float32)
    pos = jnp.arange(S, dtype=jnp.int32)
    q_pos = my * S + pos  # global positions of the local queries

    m = jnp.full((B, H, S), _NEG_BIG, jnp.float32)
    l = jnp.zeros((B, H, S), jnp.float32)
    o = jnp.zeros((B, H, S, D), jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        m, l, o, k, v = carry
        # After i hops this device holds the block that started on (my - i).
        src = (my - i) % n
        kv_pos = src * S + pos
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
        else:
            mask = jnp.ones((S, S), bool)
        # Grouped-query expansion is local: the hop moved KV heads only.
        kh = jnp.repeat(k, rep, axis=2) if rep > 1 else k
        vh = jnp.repeat(v, rep, axis=2) if rep > 1 else v
        m, l, o = _block_attn(q32, kh, vh, mask, m, l, o, scale)
        # Rotate K/V one hop; the final rotation returns blocks to their
        # owners, keeping the loop body uniform for lax.fori_loop.
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return m, l, o, k, v

    m, l, o, k, v = jax.lax.fori_loop(0, n, step, (m, l, o, k, v))
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,S,H,D]


def _zigzag_perms(n: int) -> tuple[list, list, list, list]:
    """Static ppermute pair lists for contiguous↔zigzag redistribution.

    Stripe g (of 2n stripes) lives contiguously on device g//2; zigzag
    places it on device g (lo slot) when g < n, else device 2n-1-g (hi
    slot). One ppermute can deliver at most one array per device, so the
    exchange rides two: ``fwd_even`` carries each device's even stripe
    (its first half, stripe 2d), ``fwd_odd`` the odd one. Each is a
    permutation (destinations 2d / 2n-1-2d and 2d+1 / 2n-2-2d are
    pairwise distinct), and the inverses are the reversed pairs.
    """
    fwd_even = []
    fwd_odd = []
    for d in range(n):
        g_even, g_odd = 2 * d, 2 * d + 1
        fwd_even.append((d, g_even if g_even < n else 2 * n - 1 - g_even))
        fwd_odd.append((d, g_odd if g_odd < n else 2 * n - 1 - g_odd))
    inv_even = [(dst, src) for src, dst in fwd_even]
    inv_odd = [(dst, src) for src, dst in fwd_odd]
    return fwd_even, fwd_odd, inv_even, inv_odd


def _to_zigzag(x, axis_name: str):
    """Contiguous local block [B, 2s, ...] → zigzag block [stripe_d;
    stripe_{2n-1-d}]. Runs inside shard_map; two neighbor ppermutes."""
    n = jax.lax.axis_size(axis_name)
    d = jax.lax.axis_index(axis_name)
    fwd_even, fwd_odd, _, _ = _zigzag_perms(n)
    s = x.shape[1] // 2
    recv_even = jax.lax.ppermute(x[:, :s], axis_name, fwd_even)
    recv_odd = jax.lax.ppermute(x[:, s:], axis_name, fwd_odd)
    # Device d's lo slot holds stripe d — delivered by the even carrier
    # iff d is even; the hi slot holds stripe 2n-1-d, even iff d is odd.
    even_here = (d % 2) == 0
    lo = jnp.where(even_here, recv_even, recv_odd)
    hi = jnp.where(even_here, recv_odd, recv_even)
    return jnp.concatenate([lo, hi], axis=1)


def _from_zigzag(x, axis_name: str):
    """Inverse of :func:`_to_zigzag` (zigzag block → contiguous block)."""
    n = jax.lax.axis_size(axis_name)
    d = jax.lax.axis_index(axis_name)
    _, _, inv_even, inv_odd = _zigzag_perms(n)
    s = x.shape[1] // 2
    lo, hi = x[:, :s], x[:, s:]
    # The even-stripe carrier needs this device's even stripe: stripe d
    # (lo slot) when d is even, stripe 2n-1-d (hi slot) when d is odd.
    even_here = (d % 2) == 0
    send_even = jnp.where(even_here, lo, hi)
    send_odd = jnp.where(even_here, hi, lo)
    recv_first = jax.lax.ppermute(send_even, axis_name, inv_even)
    recv_second = jax.lax.ppermute(send_odd, axis_name, inv_odd)
    return jnp.concatenate([recv_first, recv_second], axis=1)


def zigzag_ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
) -> jnp.ndarray:
    """Causal ring attention over ZIGZAG-laid-out sequence shards.

    With contiguous shards, causal ring attention computes every arriving
    K/V block branchlessly and masks the future ones away — half the
    attention FLOPs are spent on fully-masked work, and the *useful* work
    is maximally imbalanced (device n-1 needs n blocks, device 0 one).
    The zigzag layout (device d holds stripes d and 2n-1-d of 2n) makes
    every off-diagonal hop need exactly TWO fully-unmasked stripe pairs:

    - arriving block older than ours (o < d): our lo and hi stripes both
      attend the sender's lo stripe in full;
    - arriving block newer (o > d): only our hi stripe attends — the
      sender's lo and hi stripes, both in full.

    So each hop runs two stripe-size attention steps with no mask at all
    (half the branchless-contiguous FLOPs), identical on every device.
    The self block (step 0) pays one causally-masked local pass. Wire
    cost is unchanged: one K/V block rotates per hop.

    q [B, 2s, H, D], k/v [B, 2s, KV, D] in zigzag layout (use
    :func:`_to_zigzag` / :func:`_from_zigzag` to redistribute).
    """
    n = jax.lax.axis_size(axis_name)
    d = jax.lax.axis_index(axis_name)
    B, S2, H, Dh = q.shape
    s = S2 // 2
    rep = H // k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.float32(Dh))

    q32 = q.astype(jnp.float32)
    pos = jnp.arange(s, dtype=jnp.int32)
    pos_lo = d * s + pos
    pos_hi = (2 * n - 1 - d) * s + pos
    q_pos = jnp.concatenate([pos_lo, pos_hi])

    def expand(b):
        return jnp.repeat(b, rep, axis=2) if rep > 1 else b

    # Step 0: the local block attends itself, causally, at global
    # positions (the only masked compute in the whole schedule).
    m = jnp.full((B, H, S2), _NEG_BIG, jnp.float32)
    l = jnp.zeros((B, H, S2), jnp.float32)
    o = jnp.zeros((B, H, S2, Dh), jnp.float32)
    self_mask = q_pos[:, None] >= q_pos[None, :]
    m, l, o = _block_attn(q32, expand(k), expand(v), self_mask, m, l, o, scale)

    # Split accumulators per query stripe for the unmasked hop updates.
    m_lo, m_hi = m[..., :s], m[..., s:]
    l_lo, l_hi = l[..., :s], l[..., s:]
    o_lo, o_hi = o[..., :s, :], o[..., s:, :]
    q_lo32, q_hi32 = q32[:, :s], q32[:, s:]
    full = jnp.ones((s, s), bool)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        m_lo, l_lo, o_lo, m_hi, l_hi, o_hi, k, v = carry
        # Rotate first: at iteration i we hold the block that started on
        # device (d - i) mod n.
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        src = (d - i) % n
        older = src < d  # sender's lo stripe is older than both of ours
        k_lo, k_hi = expand(k[:, :s]), expand(k[:, s:])
        v_lo, v_hi = expand(v[:, :s]), expand(v[:, s:])

        # Slot 1: (lo if older else hi) × sender's lo — always unmasked.
        q1 = jnp.where(older, q_lo32, q_hi32)
        m1 = jnp.where(older, m_lo, m_hi)
        l1 = jnp.where(older, l_lo, l_hi)
        o1 = jnp.where(older, o_lo, o_hi)
        m1, l1, o1 = _block_attn(q1, k_lo, v_lo, full, m1, l1, o1, scale)
        m_lo = jnp.where(older, m1, m_lo)
        l_lo = jnp.where(older, l1, l_lo)
        o_lo = jnp.where(older, o1, o_lo)
        m_hi = jnp.where(older, m_hi, m1)
        l_hi = jnp.where(older, l_hi, l1)
        o_hi = jnp.where(older, o_hi, o1)

        # Slot 2: hi × (sender's lo if older else sender's hi) — always
        # unmasked (an older sender's lo is older than our hi; a newer
        # sender's hi stripe 2n-1-src is still older than ours 2n-1-d).
        k2 = jnp.where(older, k_lo, k_hi)
        v2 = jnp.where(older, v_lo, v_hi)
        m_hi, l_hi, o_hi = _block_attn(
            q_hi32, k2, v2, full, m_hi, l_hi, o_hi, scale
        )
        return m_lo, l_lo, o_lo, m_hi, l_hi, o_hi, k, v

    m_lo, l_lo, o_lo, m_hi, l_hi, o_hi, k, v = jax.lax.fori_loop(
        1, n, step, (m_lo, l_lo, o_lo, m_hi, l_hi, o_hi, k, v)
    )
    l_full = jnp.concatenate([l_lo, l_hi], axis=-1)
    o_full = jnp.concatenate([o_lo, o_hi], axis=-2)
    out = o_full / l_full[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _make_flash_partial(block_q, block_k, interpret):
    """The (q, k, v, causal) → (o f32, lse) kernel call both flash rings
    share: one definition so the partial-output convention (f32
    accumulator layout + composable lse) cannot drift between the
    contiguous and zigzag schedules."""
    from tpumon.workload.ops.flash_attention import flash_attention_with_lse

    def flash(q, k, v, causal):
        o, lse = flash_attention_with_lse(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
        return o.astype(jnp.float32), lse

    return flash


def _merge_partials(o_a, lse_a, o_b, lse_b):
    """Merge two normalized flash partials over the same query stripe.

    ``o`` is model-layout [B, s, H, D] (float32), ``lse`` is [B, H, s].
    Exact softmax combination: the partial with the larger log-sum-exp
    dominates, the other is rescaled — the same online-softmax algebra as
    inside the kernel, applied between kernel calls.
    """
    lse = jnp.logaddexp(lse_a, lse_b)
    w_a = jnp.transpose(jnp.exp(lse_a - lse), (0, 2, 1))[..., None]
    w_b = jnp.transpose(jnp.exp(lse_b - lse), (0, 2, 1))[..., None]
    return o_a * w_a + o_b * w_b, lse


def zigzag_ring_flash_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Causal zigzag ring attention with the pallas flash kernel inside.

    Same schedule as :func:`zigzag_ring_attention_local`, but every stripe
    pair runs :func:`ops.flash_attention.flash_attention_with_lse` instead
    of the XLA online-softmax block, and partial results merge via
    :func:`_merge_partials`. The zigzag layout is what makes this
    composition possible at all: a flash kernel wants a *static* mask
    (causal or none, baked into its grid schedule), and zigzag is exactly
    the layout under which every cross-hop stripe pair is statically
    unmasked — only the hop-0 self block needs the causal triangle, which
    decomposes into three static calls:

    - lo × lo, causal (the triangle is offset-invariant);
    - hi × hi, causal;
    - hi × lo, full (stripe 2n−1−d is always newer than stripe d).

    Hops 1..n−1 are the two fully-unmasked slot updates of the zigzag
    schedule, each one flash call. The ring still moves one KV-headed
    block per hop (GQA expansion happens inside the kernel's index maps —
    it is never materialized, unlike the XLA path's ``jnp.repeat``).

    q [B, 2s, H, D], k/v [B, 2s, KV, D] in zigzag layout.
    """
    n = jax.lax.axis_size(axis_name)
    d = jax.lax.axis_index(axis_name)
    s = q.shape[1] // 2
    flash = _make_flash_partial(block_q, block_k, interpret)

    q_lo, q_hi = q[:, :s], q[:, s:]

    # Hop 0: the self block, as three statically-masked kernel calls.
    o_lo, lse_lo = flash(q_lo, k[:, :s], v[:, :s], True)
    o_hh, lse_hh = flash(q_hi, k[:, s:], v[:, s:], True)
    o_hl, lse_hl = flash(q_hi, k[:, :s], v[:, :s], False)
    o_hi, lse_hi = _merge_partials(o_hh, lse_hh, o_hl, lse_hl)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        o_lo, lse_lo, o_hi, lse_hi, k, v = carry
        # Rotate first: at iteration i we hold the block that started on
        # device (d - i) mod n.
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        src = (d - i) % n
        older = src < d  # sender's lo stripe is older than both of ours
        k_lo, k_hi = k[:, :s], k[:, s:]
        v_lo, v_hi = v[:, :s], v[:, s:]

        # Slot 1: (lo if older else hi) × sender's lo — always unmasked.
        # Select the target accumulator pair, run ONE merge, select back
        # (same pattern as zigzag_ring_attention_local's slot 1).
        q1 = jnp.where(older, q_lo, q_hi)
        o_t = jnp.where(older, o_lo, o_hi)
        lse_t = jnp.where(older, lse_lo, lse_hi)
        o1, lse1 = flash(q1, k_lo, v_lo, False)
        o_t, lse_t = _merge_partials(o_t, lse_t, o1, lse1)
        o_lo = jnp.where(older, o_t, o_lo)
        lse_lo = jnp.where(older, lse_t, lse_lo)
        o_hi = jnp.where(older, o_hi, o_t)
        lse_hi = jnp.where(older, lse_hi, lse_t)

        # Slot 2: hi × (sender's lo if older else sender's hi) — always
        # unmasked (same argument as zigzag_ring_attention_local).
        k2 = jnp.where(older, k_lo, k_hi)
        v2 = jnp.where(older, v_lo, v_hi)
        o2, lse2 = flash(q_hi, k2, v2, False)
        o_hi, lse_hi = _merge_partials(o_hi, lse_hi, o2, lse2)
        return o_lo, lse_lo, o_hi, lse_hi, k, v

    o_lo, lse_lo, o_hi, lse_hi, k, v = jax.lax.fori_loop(
        1, n, step, (o_lo, lse_lo, o_hi, lse_hi, k, v)
    )
    return jnp.concatenate([o_lo, o_hi], axis=1).astype(q.dtype)


def ring_flash_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    *,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Ring attention over CONTIGUOUS sequence shards with the pallas
    flash kernel per hop.

    The kernel wants a static mask, and under the contiguous layout each
    hop's mask is one of exactly three static cases, selected by the
    (traced) source index:

    - ``src == d`` (hop 0): the local block attends itself causally —
      one ``causal=True`` kernel call;
    - ``src < d``: the arriving block is entirely older — one unmasked
      call, merged into the accumulator via the composable log-sum-exp
      (:func:`_merge_partials`);
    - ``src > d``: entirely newer — fully masked, so a ``lax.cond``
      skips the kernel (the device idles that hop instead of computing
      masked work, which is the contiguous layout's load imbalance —
      the zigzag layout exists to fix that, not this).

    Same wire cost as the XLA contiguous ring (one KV-headed block per
    hop; GQA expansion stays inside the kernel's index maps). With
    ``causal=False`` every hop attends in full and the cond disappears.
    """
    n = jax.lax.axis_size(axis_name)
    d = jax.lax.axis_index(axis_name)
    flash = _make_flash_partial(block_q, block_k, interpret)

    o, lse = flash(q, k, v, causal)  # hop 0: the self block
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        o, lse, k, v = carry
        # Rotate first: at iteration i we hold the block from (d - i).
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        src = (d - i) % n

        def attend(args):
            o, lse = args
            o2, lse2 = flash(q, k, v, False)
            return _merge_partials(o, lse, o2, lse2)

        if causal:
            o, lse = jax.lax.cond(src < d, attend, lambda a: a, (o, lse))
        else:
            o, lse = attend((o, lse))
        return o, lse, k, v

    o, lse, k, v = jax.lax.fori_loop(1, n, step, (o, lse, k, v))
    return o.astype(q.dtype)


def make_ring_attn(
    mesh: Mesh, *, data_axis="data", seq_axis="seq", head_axis=None, causal=True,
    zigzag=False, flash=False, block_q=None, block_k=None, interpret=None,
):
    """An attention callable q,k,v → out with the sequence axis ring-sharded.

    Returned fn takes q [B, S, H, D] and (possibly grouped-query) k/v
    [B, S, KV, D] under jit; shard_map splits batch over ``data_axis`` and
    sequence over ``seq_axis``. Pass ``head_axis="model"`` to compose with
    tensor parallelism: heads are independent in attention, so sharding
    them over the model axis keeps the TP layout through the ring with
    zero extra communication. K/V stay KV-headed on the ring (expansion is
    local, after each hop) unless the model axis doesn't divide KV — then
    they are pre-expanded to H so any tp ≤ H still shards.

    ``zigzag=True`` (causal only) redistributes each shard into the
    balanced zigzag stripe layout before the ring and back after —
    halving the attention FLOPs (see zigzag_ring_attention_local). The
    redistribution costs eight stripe-size ppermutes per call (two each
    for q/k/v in, two for the output back), all neighbor-or-near ICI
    hops; worth it as soon as S²-attention dominates, i.e. at the long
    contexts sequence parallelism exists for. Activations outside
    attention stay contiguous, so RoPE/positions and the residual stream
    are untouched.

    ``flash=True`` runs the pallas flash kernel instead of the XLA
    online-softmax block — ring over ICI outside, MXU-tiled kernel
    inside. Under zigzag, every stripe pair is one kernel call
    (:func:`zigzag_ring_flash_local`); under the contiguous layout each
    hop is one of three static cases selected per device
    (:func:`ring_flash_local` — same FLOPs as zigzag, the contiguous
    layout's usual load imbalance). ``block_q``/``block_k``/
    ``interpret`` pass through to the kernel.
    """
    if zigzag and not causal:
        raise ValueError(
            "zigzag layout only pays off for causal attention (non-causal "
            "ring attention has no masked compute to eliminate)"
        )
    spec = P(data_axis, seq_axis, head_axis, None)
    if zigzag:
        def local(q, k, v):
            q = _to_zigzag(q, seq_axis)
            k = _to_zigzag(k, seq_axis)
            v = _to_zigzag(v, seq_axis)
            if flash:
                out = zigzag_ring_flash_local(
                    q, k, v, seq_axis,
                    block_q=block_q, block_k=block_k, interpret=interpret,
                )
            else:
                out = zigzag_ring_attention_local(q, k, v, seq_axis)
            return _from_zigzag(out, seq_axis)
    elif flash:
        def local(q, k, v):
            return ring_flash_local(
                q, k, v, seq_axis, causal=causal,
                block_q=block_q, block_k=block_k, interpret=interpret,
            )
    else:
        def local(q, k, v):
            return ring_attention_local(
                q, k, v, axis_name=seq_axis, causal=causal
            )
    sharded = partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(local)

    def attn(q, k, v):
        H, KV = q.shape[2], k.shape[2]
        if head_axis is not None and KV % mesh.shape[head_axis]:
            k = jnp.repeat(k, H // KV, axis=2)
            v = jnp.repeat(v, H // KV, axis=2)
        return sharded(q, k, v)

    return attn


def reference_attention(q, k, v, *, causal=True):
    """Dense O(S²) attention, same layout — numerics oracle for tests."""
    B, S, H, D = q.shape
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) / jnp.sqrt(jnp.float32(D))
    if causal:
        pos = jnp.arange(S)
        s = jnp.where(pos[:, None] >= pos[None, :], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
