"""Pipeline parallelism over the mesh's ``stage`` axis: GPipe and the
circular/interleaved schedule, one scan.

Layer-stacked weights are sharded on their leading (layer) axis, so each
device holds ``n_layers / pp`` layers. Microbatches march through the
stages with one ``lax.ppermute`` hop per schedule tick — the
neighbor-to-neighbor ICI traffic a pipeline-parallel trainer actually
produces, which is what the monitor's ``ici_link_health`` /
``collective_e2e_latency`` panels display (SURVEY.md §2.4).

Two schedules, selected by ``interleave``:

- ``interleave=1`` — GPipe: each device holds one contiguous block of
  layers; bubble fraction ``(pp-1)/(M+pp-1)`` for M microbatches.
- ``interleave=v>1`` — the circular (Megatron-interleaved-style)
  schedule: each device holds ``v`` non-adjacent layer chunks ("virtual
  stages"); microbatches loop around the stage ring ``v`` times, so the
  bubble shrinks to ``(pp-1)/(M·v+pp-1)`` — the same pipeline-depth win
  the 1F1B/interleaved schedules buy on GPU stacks. The backward pass is
  not a hand-scheduled state machine: the schedule is a ``lax.scan``,
  XLA's autodiff reverses it tick-for-tick (backward naturally runs the
  interleaved schedule mirrored), and ``remat=True`` bounds the stashed
  activations by recomputing stage bodies — together covering what 1F1B
  exists to do (small bubble, bounded activation memory) in compiler
  terms instead of runtime-scheduler terms.

Written the XLA way:

- the schedule is a ``lax.scan`` over ticks (bubble included), so it is
  reverse-differentiable and the SAME code path runs forward and backward;
- stages compute on zero-padding during bubble ticks (branchless; a
  ``where`` on the stage index selects real inputs), trading a few wasted
  FLOPs for a single fused program with static shapes;
- the finished microbatches live on the last stage; one masked ``psum``
  over the stage axis replicates them back (the gradient of that psum is
  the identity into the last stage, so backward stays cheap).

Composes with DP (batch over ``data``), TP (Megatron column/row shards
*inside* each stage body), SP (ring attention over the ``seq`` axis
*inside* each stage body — contiguous or zigzag layout), and MoE EP×TP
(expert banks sharded over ``expert`` AND Megatron column/row-split
over ``model`` inside each stage body, combined in one fused psum over
both axes; see :func:`_moe_mlp_local` for why the aux-loss statistics
ride token SUMS across microbatch ticks): the whole
pipe runs in one ``shard_map``, so
the collectives XLA inserts automatically on the non-pipelined path are
written out manually here — one ``psum`` over ``model`` after the
row-sharded ``wo`` and ``w_down`` projections (the classic Megatron "g"
collective), and the K/V ``ppermute`` ring over ``seq``
(parallel.ring.ring_attention_local, which is built to run inside an
enclosing shard_map). Head counts are divided per model shard (a local
LlamaConfig), so attention runs on its head slice and GQA grouping is
preserved (``n_heads/tp ÷ n_kv_heads/tp`` = the global ratio); RoPE on a
sequence shard uses globally-offset positions (the shard's
``axis_index("seq") · S_local`` base).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpumon.workload.models import llama as _llama
from tpumon.workload.ops.core import rms_norm, rope_freqs
from tpumon.workload.parallel.ring import (
    _from_zigzag,
    _to_zigzag,
    ring_attention_local,
    ring_flash_local,
    zigzag_ring_attention_local,
    zigzag_ring_flash_local,
)


def _stage_layer_specs() -> dict:
    """Per-layer param specs: leading (layer) axis on ``stage``, Megatron
    column/row sharding on ``model`` (no-op at tp=1)."""
    return {
        "attn_norm": P("stage", None),
        "wq": P("stage", None, "model"),
        "wk": P("stage", None, "model"),
        "wv": P("stage", None, "model"),
        "wo": P("stage", "model", None),
        "mlp_norm": P("stage", None),
        "w_gate": P("stage", None, "model"),
        "w_up": P("stage", None, "model"),
        "w_down": P("stage", "model", None),
    }


def pipeline_param_specs() -> dict:
    """Full param-tree specs for the pipelined model (layers → stages)."""
    return {
        "embed": P("model", None),
        "layers": _stage_layer_specs(),
        "final_norm": P(None),
        "unembed": P(None, "model"),
    }


def _moe_stage_layer_specs() -> dict:
    """MoE per-layer specs under pp: layer axis on ``stage``, expert
    banks sharded over ``expert`` AND Megatron column/row-sharded over
    ``model`` (no-op at tp=1); attention projections shard over
    ``model`` exactly like the dense stage specs."""
    return {
        "attn_norm": P("stage", None),
        "wq": P("stage", None, "model"),
        "wk": P("stage", None, "model"),
        "wv": P("stage", None, "model"),
        "wo": P("stage", "model", None),
        "mlp_norm": P("stage", None),
        "router": P("stage", None, None),
        "w_gate": P("stage", "expert", None, "model"),
        "w_up": P("stage", "expert", None, "model"),
        "w_down": P("stage", "expert", "model", None),
    }


def moe_pipeline_param_specs() -> dict:
    """Full param-tree specs for the pipelined MoE model."""
    return {
        "embed": P("model", None),
        "layers": _moe_stage_layer_specs(),
        "final_norm": P(None),
        "unembed": P(None, "model"),
    }


def _moe_mlp_local(x, layer, cfg, tp=1):
    """One MoE FFN inside the stage shard_map: expert banks are sharded
    over ``expert`` (this layer's slice is [E/ep, D, F/tp]); activations
    and routing are expert- and model-replicated, so each shard computes
    its experts' (column-sliced) partial output and one psum over the
    ``expert`` (+ ``model``, at tp > 1) axes combines — EP's memory win
    with an all-reduce combine (the monitored EP collective on this
    path), chosen over token all-to-alls because the dispatch tensors
    are already local to every shard. The F axis sharding is the classic
    Megatron column(gate/up)/row(down) split, so the tp partial sums
    fold into the same psum.

    Returns (out [B,S,D], (frac_sum [E], prob_sum [E])): per-expert TOKEN
    SUMS, not means — sums are linear across microbatches, so the caller
    can accumulate them over schedule ticks and compute the GShard aux
    loss on the full batch exactly as the unpipelined model does
    (means-of-means would diverge from dense parity).
    """
    from tpumon.workload.models.moe import expert_ffn, route_tokens

    dispatch, combine, probs = route_tokens(x, layer, cfg)
    frac_sum = jnp.sum(dispatch, axis=(0, 1, 3))  # routed tokens per expert
    prob_sum = jnp.sum(probs, axis=(0, 1))

    ep = jax.lax.axis_size("expert")
    e_loc = cfg.n_experts // ep
    start = jax.lax.axis_index("expert") * e_loc
    disp = jax.lax.dynamic_slice_in_dim(dispatch, start, e_loc, axis=2)
    comb = jax.lax.dynamic_slice_in_dim(combine, start, e_loc, axis=2)

    out = expert_ffn(x, disp, comb, layer, cfg)
    axes = ("expert", "model") if tp > 1 else ("expert",)
    return jax.lax.psum(out, axes), (frac_sum, prob_sum)


def _attn_sublayer(h, layer, cfg, freqs, mask, tp, attn_impl):
    """Attention + residual for one stage-body layer: the Megatron psum
    after the row-sharded ``wo`` lives here, shared by the dense and MoE
    stage bodies so the tp collective cannot drift between them."""
    a = _llama._attention(
        rms_norm(h, layer["attn_norm"]), layer, cfg, freqs, mask, attn_impl
    )
    if tp > 1:
        a = jax.lax.psum(a, "model")
    return h + a


def _moe_stage_body(layers_local, x, cfg, freqs, mask, tp, attn_impl=None):
    """MoE counterpart of :func:`_stage_body`: returns per-layer aux-loss
    statistics [lpg, E] alongside the activations. ``cfg`` carries
    per-model-shard head counts at tp > 1."""

    def block(h, layer):
        h = _attn_sublayer(h, layer, cfg, freqs, mask, tp, attn_impl)
        out, stats = _moe_mlp_local(
            rms_norm(h, layer["mlp_norm"]), layer, cfg, tp
        )
        return h + out, stats

    return jax.lax.scan(block, x, layers_local)


def _stage_body(layers_local, x, cfg, freqs, mask, tp, attn_impl=None):
    """Run this stage's layer block on one microbatch [mb, S, D].

    ``cfg`` carries *per-model-shard* head counts (see
    make_pipelined_forward); with tp > 1 the row-sharded output
    projections produce partial sums, reduced with an explicit psum over
    ``model`` — inside shard_map, Megatron's collectives are manual.
    ``attn_impl`` swaps the attention core (ring attention when the seq
    axis is live).
    """

    def block(h, layer):
        h = _attn_sublayer(h, layer, cfg, freqs, mask, tp, attn_impl)
        m = _llama._mlp(rms_norm(h, layer["mlp_norm"]), layer, cfg)
        if tp > 1:
            m = jax.lax.psum(m, "model")
        h = h + m
        return h, None

    h, _ = jax.lax.scan(block, x, layers_local)
    return h


def _schedule(microbatches: int, pp: int, v: int) -> tuple[np.ndarray, np.ndarray, int]:
    """Static tick schedule: (in_ticks, out_ticks, total_ticks).

    Microbatches flow in rounds of ``pp``; within a round each microbatch
    traverses all ``v`` chunks (one full ring lap per chunk) before the
    next round enters. Microbatch ``m`` enters stage 0 chunk 0 at tick
    ``(m//pp)·pp·v + m%pp`` and leaves stage pp-1 chunk v-1 ``(v-1)·pp +
    (pp-1)`` ticks later. At v=1 this degenerates to exactly GPipe:
    in at ``m``, out at ``m + pp - 1``.
    """
    m = np.arange(microbatches)
    in_ticks = (m // pp) * pp * v + (m % pp)
    out_ticks = in_ticks + (v - 1) * pp + (pp - 1)
    return in_ticks, out_ticks, int(out_ticks[-1]) + 1


def make_pipelined_forward(
    mesh: Mesh,
    cfg,
    *,
    microbatches: int = 2,
    interleave: int = 1,
    remat: bool = False,
    sp_layout: str = "contiguous",
    attn: str = "xla",
):
    """logits = f(params, tokens): pipeline over the mesh's ``stage`` axis.

    params is the models.llama tree sharded with pipeline_param_specs();
    tokens [B, S] with B divisible by data-shards × microbatches.
    ``interleave=v`` selects the circular schedule (v layer chunks per
    stage, bubble ÷ v); ``remat=True`` recomputes stage bodies in the
    backward pass, bounding stashed activations (the memory half of the
    1F1B story). When the mesh's ``seq`` axis is >1, activations are
    sequence-sharded and attention runs as a K/V ring inside the stage
    body (SP×PP composition); ``sp_layout="zigzag"`` runs that ring over
    the balanced zigzag stripe layout instead (half the attention FLOPs —
    parallel.ring.zigzag_ring_attention_local). The redistribution is
    attention-internal (zigzag in, ring, contiguous out), so the stage
    schedule, RoPE offsets, and residual stream are untouched — the same
    transparency that lets zigzag compose with dp/tp/ep on the
    non-pipelined path.

    ``attn="flash"`` swaps the stage bodies' attention core for the
    pallas flash kernel: plain :func:`ops.flash_attention` when the seq
    axis is 1 (each stage sees the full sequence), the
    flash-inside-ring composition under sp — zigzag stripe pairs
    (:func:`parallel.ring.zigzag_ring_flash_local`) or the contiguous
    layout's three-static-case hops
    (:func:`parallel.ring.ring_flash_local`).
    """
    pp = mesh.shape["stage"]
    tp = mesh.shape["model"]
    spn = mesh.shape["seq"]
    is_moe = hasattr(cfg, "n_experts")
    v = interleave
    if v < 1:
        raise ValueError(f"interleave must be >= 1, got {v}")
    if sp_layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown sp_layout: {sp_layout!r}")
    if attn not in ("xla", "flash"):
        raise ValueError(f"unknown attn impl: {attn!r}")
    if is_moe and spn > 1:
        raise ValueError(
            "pp×MoE composes with dp/ep/tp, not sp: routing's capacity "
            "cumsum runs over the whole sequence, which a seq-sharded "
            "stage body cannot compute locally"
        )
    if is_moe and cfg.n_experts % mesh.shape["expert"]:
        raise ValueError(
            f"n_experts ({cfg.n_experts}) must divide by the mesh expert "
            f"axis ({mesh.shape['expert']})"
        )
    if cfg.n_layers % (pp * v):
        raise ValueError(
            f"n_layers ({cfg.n_layers}) must divide by pp*interleave "
            f"({pp}*{v})"
        )
    if v > 1 and microbatches % pp:
        raise ValueError(
            f"the circular schedule feeds microbatches in rounds of pp: "
            f"microbatches ({microbatches}) must divide by pp ({pp})"
        )
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError(
            f"n_heads ({cfg.n_heads}) and n_kv_heads ({cfg.n_kv_heads}) "
            f"must divide by tp ({tp})"
        )
    # Per-shard view of the model: each model shard owns n_heads/tp query
    # heads (dim scales with it, so head_dim is unchanged). At tp=1 this
    # is cfg itself.
    local_cfg = (
        dataclasses.replace(
            cfg,
            dim=cfg.dim // tp,
            n_heads=cfg.n_heads // tp,
            n_kv_heads=cfg.n_kv_heads // tp,
        )
        if tp > 1
        else cfg
    )
    sp = spn > 1
    spec_x = P("data", "seq", None) if sp else P("data", None, None)
    in_ticks, out_ticks, total_ticks = _schedule(microbatches, pp, v)

    # Per-layer aux-loss statistics leave the shard_map per (data shard,
    # stage): local [1, v, lpg, E] → global [dp, pp·v, lpg, E]; the
    # caller sums data shards and computes the GShard aux on full-batch
    # token sums (dense-parity exact — see _moe_mlp_local).
    spec_stats = P("data", "stage", None, None)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            (_moe_stage_layer_specs() if is_moe else _stage_layer_specs()),
            spec_x,
        ),
        out_specs=(
            (spec_x, (spec_stats, spec_stats)) if is_moe else spec_x
        ),
        check_vma=False,
    )
    def pipe(layers_local, x):
        stage = jax.lax.axis_index("stage")
        b_loc, S, D = x.shape
        M = microbatches
        mb = b_loc // M
        freqs_full = rope_freqs(cfg.head_dim, cfg.max_seq)
        if sp:
            # RoPE positions are global: offset this shard's block.
            # (Zigzag redistribution happens inside the attention call,
            # after RoPE — activations stay contiguous at stage level.)
            six = jax.lax.axis_index("seq")
            freqs = jax.lax.dynamic_slice_in_dim(freqs_full, six * S, S)
            mask = None  # ring attention masks by global position itself
            if sp_layout == "zigzag":
                # NOTE: bound to its own name — `ring` below is the
                # ppermute pair list, and closures capture by reference.
                zz_ring = (
                    zigzag_ring_flash_local if attn == "flash"
                    else zigzag_ring_attention_local
                )

                def attn_impl(q, k, v_):
                    q = _to_zigzag(q, "seq")
                    k = _to_zigzag(k, "seq")
                    v_ = _to_zigzag(v_, "seq")
                    return _from_zigzag(zz_ring(q, k, v_, "seq"), "seq")
            elif attn == "flash":
                attn_impl = lambda q, k, v_: ring_flash_local(  # noqa: E731
                    q, k, v_, "seq"
                )
            else:
                attn_impl = lambda q, k, v_: ring_attention_local(  # noqa: E731
                    q, k, v_, "seq"
                )
        else:
            freqs = freqs_full
            mask = jnp.triu(
                jnp.full((cfg.max_seq, cfg.max_seq), -1e9, jnp.float32), k=1
            )
            if attn == "flash":
                from tpumon.workload.ops.flash_attention import make_flash_attn

                # Each stage sees the full sequence: the pallas kernel
                # drops in as-is (GQA via its index maps, tuned tiles).
                attn_impl = make_flash_attn()
            else:
                attn_impl = None

        # Local layer stack [v·lpg, ...] → v chunks of lpg layers. Storage
        # is schedule-ordered (see forward()): local chunk c = rows
        # [c·lpg, (c+1)·lpg).
        chunks = jax.tree.map(
            lambda a: a.reshape(v, a.shape[0] // v, *a.shape[1:]),
            layers_local,
        )

        inps = x.reshape(M, mb, S, D)
        xs = (
            jnp.zeros((total_ticks, mb, S, D), x.dtype)
            .at[jnp.asarray(in_ticks)]
            .set(inps)
        )
        # Full ring: the pp-1 → 0 wrap carries a microbatch into its next
        # chunk (circular schedule). At v=1 stage 0 always reads the
        # schedule, so the wrap hop is dead weight XLA keeps overlapped.
        ring = [(i, (i + 1) % pp) for i in range(pp)]
        period = pp * v

        if is_moe:
            def run_body(chunk, x_in, freqs, mask):
                return _moe_stage_body(
                    chunk, x_in, local_cfg, freqs, mask, tp, attn_impl
                )
        else:
            def run_body(chunk, x_in, freqs, mask):
                y = _stage_body(
                    chunk, x_in, local_cfg, freqs, mask, tp, attn_impl
                )
                return y, None

        body = jax.checkpoint(run_body) if remat else run_body

        def tick(x_cur, xt):
            inp_t, t = xt
            u = t - stage  # this stage's logical time (u<0 → bubble)
            c = jnp.floor_divide(u, pp) % v  # chunk index; in [0, v)
            # Stage 0 reads the schedule on chunk-0 ticks (fresh
            # microbatch), the ring wrap otherwise. Other stages always
            # read their left neighbor.
            take_fresh = (stage == 0) & (jnp.mod(u, period) < pp)
            x_in = jnp.where(take_fresh, inp_t, x_cur)
            chunk = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, c, axis=0, keepdims=False
                ),
                chunks,
            )
            y, stats = body(chunk, x_in, freqs, mask)
            x_next = jax.lax.ppermute(y, "stage", ring)
            if not is_moe:
                return x_next, y
            # Aux statistics count only REAL ticks (bubble ticks route
            # zero-padding — uniform router probs would poison the sums),
            # scattered to this tick's chunk row so each (chunk, layer)
            # slot accumulates exactly its own microbatches. Microbatch
            # index from the schedule algebra: u ticks into the stage,
            # rounds of pp·v, pp microbatches per round, one chunk lap
            # per round segment.
            m_idx = jnp.floor_divide(u, period) * pp + jnp.mod(u, pp)
            real = (u >= 0) & (m_idx < M)
            c_hot = jax.nn.one_hot(c, v, dtype=jnp.float32)
            stats = jax.tree.map(
                lambda s: jnp.where(real, 1.0, 0.0)
                * c_hot[:, None, None]
                * s[None],  # [v, lpg, E]
                stats,
            )
            return x_next, (y, stats)

        _, ys = jax.lax.scan(
            tick,
            jnp.zeros((mb, S, D), x.dtype),
            (xs, jnp.arange(total_ticks)),
        )
        if is_moe:
            ys, tick_stats = ys
            # Sum over ticks → this stage's [v, lpg, E] token sums, with
            # the leading size-1 data axis the out_spec stacks over.
            stats = jax.tree.map(
                lambda s: jnp.sum(s, axis=0)[None], tick_stats
            )

        # Microbatch m finishes on the last stage (chunk v-1) at its
        # statically known out-tick.
        outs = ys[jnp.asarray(out_ticks)]
        outs = jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, "stage")
        outs = outs.reshape(b_loc, S, D)
        return (outs, stats) if is_moe else outs

    lpg = cfg.n_layers // (pp * v)
    if v > 1:
        # Schedule-order the layer stack: model block j (executed j-th)
        # lives on stage j%pp as its chunk j//pp, and stage shards are
        # contiguous — so storage position (s, c) holds model block
        # c·pp+s. Identity at v=1. Done under jit each step: a weight
        # gather XLA lowers into the resharding; negligible at
        # traffic-generator scale, and keeping checkpoints in model
        # order is worth it.
        order = np.concatenate(
            [
                np.arange(lpg) + (c * pp + s) * lpg
                for s in range(pp)
                for c in range(v)
            ]
        )
    else:
        order = None

    def forward(params, tokens):
        per_shard = tokens.shape[0] // mesh.shape["data"]
        if per_shard % microbatches:
            raise ValueError(
                f"per-data-shard batch ({per_shard}) must divide by "
                f"microbatches ({microbatches})"
            )
        if sp and tokens.shape[1] % spn:
            raise ValueError(
                f"seq ({tokens.shape[1]}) must divide by the mesh seq "
                f"axis ({spn})"
            )
        if sp and sp_layout == "zigzag" and tokens.shape[1] % (2 * spn):
            raise ValueError(
                f"zigzag needs an even local shard: seq "
                f"({tokens.shape[1]}) must divide by 2*sp ({2 * spn})"
            )
        layers = params["layers"]
        if order is not None:
            layers = jax.tree.map(lambda a: a[order], layers)
        x = params["embed"].astype(cfg.dtype)[tokens]
        if is_moe:
            x, (frac, prob) = pipe(layers, x)
            # Token sums: [dp, pp·v, lpg, E] → per-layer [n_layers, E]
            # (row order is schedule order — irrelevant under the layer
            # sum). GShard aux per layer from full-batch means, averaged
            # over layers: identical to models.moe.forward.
            n_tok = tokens.shape[0] * tokens.shape[1]
            f = jnp.sum(frac, axis=0).reshape(-1, cfg.n_experts) / n_tok
            p = jnp.sum(prob, axis=0).reshape(-1, cfg.n_experts) / n_tok
            aux = jnp.float32(cfg.n_experts) * jnp.sum(f / cfg.top_k * p)
            aux = aux / cfg.n_layers
        else:
            x = pipe(layers, x)
            aux = None
        x = rms_norm(x, params["final_norm"])
        logits = (x @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)
        return (logits, aux) if is_moe else logits

    return forward
