"""Pipeline parallelism: GPipe schedule over the mesh's ``stage`` axis.

Layer-stacked weights are sharded on their leading (layer) axis, so each
device holds ``n_layers / pp`` contiguous layers. Microbatches march
through the stages with one ``lax.ppermute`` hop per schedule tick — the
neighbor-to-neighbor ICI traffic a pipeline-parallel trainer actually
produces, which is what the monitor's ``ici_link_health`` /
``collective_e2e_latency`` panels display (SURVEY.md §2.4).

Written the XLA way:

- the schedule is a ``lax.scan`` over ``microbatches + pp - 1`` ticks
  (bubble included), so it is reverse-differentiable and the SAME code
  path runs forward and backward — no hand-scheduled 1F1B state machine;
- stages compute on zero-padding during bubble ticks (branchless; a
  ``where`` on the stage index selects real inputs), trading a few wasted
  FLOPs for a single fused program with static shapes;
- the finished microbatches live on the last stage; one masked ``psum``
  over the stage axis replicates them back (the gradient of that psum is
  the identity into the last stage, so backward stays cheap).

Composes with DP (batch over ``data``) and TP (Megatron column/row shards
*inside* each stage body): the whole pipe runs in one ``shard_map``, so
the all-reduces XLA inserts automatically on the non-pipelined path are
written out manually here — one ``psum`` over ``model`` after the
row-sharded ``wo`` and ``w_down`` projections, the classic Megatron "g"
collective. Head counts are divided per model shard (a local
LlamaConfig), so attention runs on its head slice and GQA grouping is
preserved (``n_heads/tp ÷ n_kv_heads/tp`` = the global ratio).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpumon.workload.models import llama as _llama
from tpumon.workload.ops.core import rms_norm, rope_freqs


def _stage_layer_specs() -> dict:
    """Per-layer param specs: leading (layer) axis on ``stage``, Megatron
    column/row sharding on ``model`` (no-op at tp=1)."""
    return {
        "attn_norm": P("stage", None),
        "wq": P("stage", None, "model"),
        "wk": P("stage", None, "model"),
        "wv": P("stage", None, "model"),
        "wo": P("stage", "model", None),
        "mlp_norm": P("stage", None),
        "w_gate": P("stage", None, "model"),
        "w_up": P("stage", None, "model"),
        "w_down": P("stage", "model", None),
    }


def pipeline_param_specs() -> dict:
    """Full param-tree specs for the pipelined model (layers → stages)."""
    return {
        "embed": P("model", None),
        "layers": _stage_layer_specs(),
        "final_norm": P(None),
        "unembed": P(None, "model"),
    }


def _stage_body(layers_local, x, cfg, freqs, mask, tp):
    """Run this stage's layer block on one microbatch [mb, S, D].

    ``cfg`` carries *per-model-shard* head counts (see
    make_pipelined_forward); with tp > 1 the row-sharded output
    projections produce partial sums, reduced with an explicit psum over
    ``model`` — inside shard_map, Megatron's collectives are manual.
    """

    def block(h, layer):
        a = _llama._attention(
            rms_norm(h, layer["attn_norm"]), layer, cfg, freqs, mask
        )
        if tp > 1:
            a = jax.lax.psum(a, "model")
        h = h + a
        m = _llama._mlp(rms_norm(h, layer["mlp_norm"]), layer, cfg)
        if tp > 1:
            m = jax.lax.psum(m, "model")
        h = h + m
        return h, None

    h, _ = jax.lax.scan(block, x, layers_local)
    return h


def make_pipelined_forward(mesh: Mesh, cfg, *, microbatches: int = 2):
    """logits = f(params, tokens): GPipe over the mesh's ``stage`` axis.

    params is the models.llama tree sharded with pipeline_param_specs();
    tokens [B, S] with B divisible by data-shards × microbatches.
    """
    pp = mesh.shape["stage"]
    tp = mesh.shape["model"]
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers ({cfg.n_layers}) must divide by pp ({pp})")
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError(
            f"n_heads ({cfg.n_heads}) and n_kv_heads ({cfg.n_kv_heads}) "
            f"must divide by tp ({tp})"
        )
    # Per-shard view of the model: each model shard owns n_heads/tp query
    # heads (dim scales with it, so head_dim is unchanged). At tp=1 this
    # is cfg itself.
    local_cfg = (
        dataclasses.replace(
            cfg,
            dim=cfg.dim // tp,
            n_heads=cfg.n_heads // tp,
            n_kv_heads=cfg.n_kv_heads // tp,
        )
        if tp > 1
        else cfg
    )

    spec_x = P("data", None, None)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(_stage_layer_specs(), spec_x),
        out_specs=spec_x,
        check_vma=False,
    )
    def pipe(layers_local, x):
        stage = jax.lax.axis_index("stage")
        b_loc, S, D = x.shape
        M = microbatches
        mb = b_loc // M
        freqs = rope_freqs(cfg.head_dim, cfg.max_seq)
        mask = jnp.triu(
            jnp.full((cfg.max_seq, cfg.max_seq), -1e9, jnp.float32), k=1
        )

        inps = x.reshape(M, mb, S, D)
        bubble = jnp.zeros((pp - 1, mb, S, D), x.dtype)
        xs = jnp.concatenate([inps, bubble], axis=0)  # [M + pp - 1, ...]

        fwd = [(i, i + 1) for i in range(pp - 1)]  # stage i → i+1

        def tick(x_cur, inp_t):
            x_in = jnp.where(stage == 0, inp_t, x_cur)
            y = _stage_body(layers_local, x_in, local_cfg, freqs, mask, tp)
            # Hop to the next stage; stage 0 receives zeros (it always
            # reads from the schedule, never from the wire).
            x_next = jax.lax.ppermute(y, "stage", fwd)
            return x_next, y

        _, ys = jax.lax.scan(tick, jnp.zeros((mb, S, D), x.dtype), xs)

        # Microbatch m finishes on the last stage at tick m + pp - 1.
        outs = ys[pp - 1 :]
        outs = jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, "stage")
        return outs.reshape(b_loc, S, D)

    def forward(params, tokens):
        per_shard = tokens.shape[0] // mesh.shape["data"]
        if per_shard % microbatches:
            raise ValueError(
                f"per-data-shard batch ({per_shard}) must divide by "
                f"microbatches ({microbatches})"
            )
        x = params["embed"].astype(cfg.dtype)[tokens]
        x = pipe(params["layers"], x)
        x = rms_norm(x, params["final_norm"])
        return (x @ params["unembed"].astype(cfg.dtype)).astype(jnp.float32)

    return forward
