"""Ring-attention layout benchmark: contiguous vs zigzag (SURVEY.md §6).

Times one causal ring-attention forward (and forward+backward) per
sequence length on a dp×sp mesh, for four configurations: the
branchless contiguous ring, the contiguous ring with the flash kernel
(ring_flash_local — same useful FLOPs as zigzag but hop-imbalanced; the
bench measures that claimed trade-off), the zigzag layout (which
computes exactly half the stripe pairs —
parallel.ring.zigzag_ring_attention_local, at the price of eight
stripe-size ppermutes per call), and the zigzag ring with the pallas
flash kernel running every stripe pair (zigzag_ring_flash_local;
interpret mode off-TPU, so only its TPU numbers are about speed).
Zigzag should win once S²-attention compute
dominates the redistribution, which is the regime sequence parallelism
exists for. The numbers land in BASELINE.md; an honest crossover point
(below which contiguous wins) is a result.

Run:  python -m tpumon.workload.bench_ring --sp 4 --seq 1024 2048 4096
      (add --platform cpu off-TPU; the mesh is dp×sp over all devices)
"""

from __future__ import annotations

import argparse
import json
import sys


# One timing harness for all workload benches: bench_attention's timer at
# inner=1 is exactly the warmup+median loop this bench needs, and a fix to
# the methodology there must apply here too.
from tpumon.workload.bench_attention import _time


def _validate(n: int, sp: int, batch: int, seqs: tuple[int, ...]) -> int:
    """Check mesh/shape divisibility up front; returns dp.

    Raises ValueError with the real constraint instead of letting the
    run die deep inside shard_map: batch splits over the data axis, and
    the zigzag leg needs an even per-device sequence shard.
    """
    if n % sp:
        raise ValueError(f"device count {n} must divide by sp {sp}")
    dp = n // sp
    if batch % dp:
        raise ValueError(
            f"batch ({batch}) must divide by dp ({dp} = {n} devices / "
            f"sp {sp}); pass --batch {dp} or reduce --sp"
        )
    bad = [s for s in seqs if s % (2 * sp)]
    if bad:
        raise ValueError(
            f"seq values {bad} must divide by 2*sp ({2 * sp}) for the "
            "zigzag layout's lo/hi stripes"
        )
    return dp


def bench(
    sp: int = 4,
    batch: int = 2,
    heads: int = 8,
    kv_heads: int = 4,
    head_dim: int = 128,
    seqs: tuple[int, ...] = (1024, 2048, 4096),
    iters: int = 5,
    out=sys.stdout,
) -> list[dict]:
    import jax
    import jax.numpy as jnp

    from tpumon.workload.parallel.mesh import make_mesh
    from tpumon.workload.parallel.ring import make_ring_attn

    n = len(jax.devices())
    dp = _validate(n, sp, batch, seqs)
    mesh = make_mesh(dp, 1, sp)
    platform = jax.devices()[0].platform
    results = []
    for seq in seqs:
        kq, kk, kv_ = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (batch, seq, heads, head_dim), jnp.bfloat16)
        k = jax.random.normal(
            kk, (batch, seq, kv_heads, head_dim), jnp.bfloat16
        )
        v = jax.random.normal(
            kv_, (batch, seq, kv_heads, head_dim), jnp.bfloat16
        )
        for layout in (
            "contiguous", "contiguous-flash", "zigzag", "zigzag-flash"
        ):
            attn = make_ring_attn(
                mesh,
                zigzag=layout.startswith("zigzag"),
                flash=layout.endswith("flash"),
            )
            fwd = jax.jit(attn)

            def loss(q, k, v):
                return jnp.sum(attn(q, k, v).astype(jnp.float32))

            bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            fwd_s = _time(fwd, q, k, v, iters=iters)
            bwd_s = _time(bwd, q, k, v, iters=iters)
            row = {
                "layout": layout,
                "platform": platform,
                "dp": dp,
                "sp": sp,
                "batch": batch,
                "heads": heads,
                "kv_heads": kv_heads,
                "head_dim": head_dim,
                "seq": seq,
                "fwd_ms": round(fwd_s * 1e3, 3),
                "fwd_bwd_ms": round(bwd_s * 1e3, 3),
            }
            results.append(row)
            print(json.dumps(row), file=out, flush=True)
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="bench_ring")
    parser.add_argument("--sp", type=int, default=4)
    parser.add_argument("--batch", type=int, default=2)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--kv-heads", type=int, default=4)
    parser.add_argument("--head-dim", type=int, default=128)
    parser.add_argument(
        "--seq", type=int, nargs="+", default=[1024, 2048, 4096]
    )
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument(
        "--platform",
        choices=("auto", "cpu"),
        default="auto",
        help="force the jax platform; 'cpu' sizes a virtual device mesh "
        "and avoids a wedged TPU tunnel (flag, not env — the "
        "JAX_PLATFORMS env var is ignored when a TPU plugin is present)",
    )
    parser.add_argument(
        "--devices",
        type=int,
        default=8,
        help="virtual device count when --platform cpu",
    )
    args = parser.parse_args(argv)
    if args.platform == "cpu":
        from tpumon.workload.platform import force_cpu_devices

        force_cpu_devices(args.devices)
    import jax

    try:
        # Pre-flight only: a ValueError out of the benchmark itself is a
        # real bug and must keep its traceback, not masquerade as a
        # usage error.
        _validate(len(jax.devices()), args.sp, args.batch, tuple(args.seq))
    except ValueError as exc:
        parser.error(str(exc))
    bench(
        sp=args.sp,
        batch=args.batch,
        heads=args.heads,
        kv_heads=args.kv_heads,
        head_dim=args.head_dim,
        seqs=tuple(args.seq),
        iters=args.iters,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
