"""The energy/cost plane wired into the poll loop.

One :meth:`EnergyPlane.cycle` call per poll, fed the PollStats the
collector already computed. The pass:

1. reads per-chip power where the device library exposed it this cycle
   (``accelerator_power_watts`` — sampled by the ordinary poll loop,
   **zero queries added by this plane**) and models it everywhere else
   (duty-cycle × TDP, HBM-adjusted — tpumon/energy/model.py), labeling
   every sample ``source="measured"|"modeled"``;
2. integrates power into monotonic per-chip joules counters
   (``tpu_energy_joules_total``) with gap honesty: a poll gap longer
   than ``TPUMON_ENERGY_MAX_GAP_S`` is integrated only up to the cap —
   the uncounted remainder is surfaced in the /debug/vars energy block
   instead of invented;
3. splits each chip's energy across the pods holding it (the existing
   pod-attribution plane's ``accelerator_pod_info`` join) into
   ``tpu_pod_energy_joules_total``;
4. joins node power with the lifecycle plane's step telemetry
   (``tpu_step_*`` feeds, same cycle) into the headline efficiency
   families — ``tpu_step_energy_joules``, ``tpu_step_tokens_per_joule``,
   ``tpu_step_cost_dollars`` (``TPUMON_ENERGY_DOLLARS_PER_KWH``);
5. injects an ``energy`` block into ``PollStats.snapshot`` so the
   efficiency-regression detector (tpumon/energy/detectors.py) sees
   tokens/joule on the same bus every other detector rides.

Source honesty: the joined step/efficiency families read ``measured``
only when EVERY contributing chip's power was a device reading; one
modeled chip makes the join ``modeled``.
"""

from __future__ import annotations

import logging
import threading

from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily

from tpumon.energy.model import (
    SOURCE_MEASURED,
    SOURCE_MODELED,
    env_thresholds,
    model_power_w,
    tdp_for,
)

log = logging.getLogger(__name__)

#: Joules per kilowatt-hour.
_J_PER_KWH = 3.6e6


class EnergyPlane:
    """Thread model: ``cycle`` runs on the poller thread only;
    ``snapshot`` may be called from HTTP threads — the totals dicts are
    guarded by one lock held for dict work only."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: (chip, source) -> accumulated joules. Keyed per source so a
        #: backend flapping between exposing and hiding power telemetry
        #: moves accumulation between two series, EACH monotonic —
        #: never a counter that jumps when the meaning of its value
        #: changed under it.
        self._joules: dict[tuple[str, str], float] = {}  # guarded-by: self._lock
        #: (namespace, pod, source) -> accumulated joules.
        self._pod_joules: dict[tuple[str, str, str], float] = {}  # guarded-by: self._lock
        self._cycles = 0  # guarded-by: self._lock
        #: Wall seconds NOT integrated because a poll gap exceeded
        #: max_gap_s (+ how many gaps were clamped) — the honesty ledger.
        self._gap_skipped_s = 0.0  # guarded-by: self._lock
        self._gaps_clamped = 0  # guarded-by: self._lock
        self._last: dict | None = None  # guarded-by: self._lock
        #: Poller thread only.
        self._last_ts: float | None = None

    # -- poll-loop integration --------------------------------------------

    def cycle(self, now: float, stats) -> list:
        """One Poller cycle: read/model power, integrate, split, join."""
        t = env_thresholds()
        snap = stats.snapshot if stats.snapshot is not None else {}
        chips = snap.get("chips") or {}
        accel = (snap.get("identity") or {}).get("accelerator")
        tdp_w, tdp_key = tdp_for(accel, t)

        # Per-chip power, source-labeled. A chip reporting neither a
        # device power reading nor a duty cycle contributes nothing
        # (absent-not-zero) — modeling power for a chip we cannot see
        # working would be a guess about a guess.
        power: dict[str, tuple[float, str]] = {}
        for chip in sorted(chips):
            row = chips[chip]
            measured = row.get("power_w")
            if measured is not None:
                power[chip] = (measured, SOURCE_MEASURED)
                continue
            duty = row.get("duty_pct")
            if duty is None:
                continue
            used, total = row.get("hbm_used"), row.get("hbm_total")
            hbm_ratio = used / total if used is not None and total else None
            power[chip] = (
                model_power_w(duty, hbm_ratio, tdp_w, t), SOURCE_MODELED
            )

        # Integration window with gap honesty.
        dt = 0.0
        skipped = 0.0
        if self._last_ts is not None and now > self._last_ts:
            gap = now - self._last_ts
            dt = min(gap, max(0.0, t.max_gap_s)) if t.max_gap_s > 0 else gap
            skipped = gap - dt
        self._last_ts = now

        # Pod split universe: accelerator_pod_info rows joined on the
        # chip index label (the attribution plane already did the
        # kubelet work; this is a dict walk).
        pod_map: dict[str, list[tuple[str, str]]] = snap.get("pods") or {}

        node_w = 0.0
        source_counts = {SOURCE_MEASURED: 0, SOURCE_MODELED: 0}
        #: Pods attributed THIS cycle (the cumulative _pod_joules keys
        #: never drop — they are counters — so the /debug/vars "last"
        #: block must not count them as current state).
        cycle_pods: set[tuple[str, str]] = set()
        with self._lock:
            self._cycles += 1
            if skipped > 0:
                self._gap_skipped_s += skipped
                self._gaps_clamped += 1
            for chip, (watts, source) in power.items():
                node_w += watts
                source_counts[source] += 1
                if dt > 0:
                    key = (chip, source)
                    self._joules[key] = self._joules.get(key, 0.0) + watts * dt
                    pods = pod_map.get(chip) or ()
                    if pods:
                        share = watts * dt / len(pods)
                        for ns, pod in pods:
                            cycle_pods.add((ns, pod))
                            pkey = (ns, pod, source)
                            self._pod_joules[pkey] = (
                                self._pod_joules.get(pkey, 0.0) + share
                            )
            joules = dict(self._joules)
            pod_joules = dict(self._pod_joules)

        # One label for the joined families: measured only when every
        # contributing chip was measured.
        join_source = (
            SOURCE_MEASURED
            if power and source_counts[SOURCE_MODELED] == 0
            else SOURCE_MODELED
        )

        # Step/efficiency join from the lifecycle block (the plane runs
        # after lifecycle in the poll cycle, same snapshot bus). The
        # joined means are the lifecycle plane's CANONICAL merge — read,
        # never re-derived, so the two planes cannot silently diverge
        # on how feeds combine.
        lc = snap.get("lifecycle") or {}
        feeds = lc.get("feeds") or {}
        tokens_per_s = lc.get("tokens_per_second")
        step_seconds = lc.get("step_seconds")
        # Each host of a dp job reports the JOB-global token rate
        # (lifecycle's documented merge), while the watts below are
        # THIS node's. Split the rate across the slice's hosts so
        # tokens/J is node-tokens over node-joules — comparable across
        # jobs of any host count instead of inflated by it. (Slice
        # hosts is the best available job-span estimate: lifecycle
        # feeds are localhost probes of jobs laid out one-harness-per-
        # host across the slice.)
        slice_hosts = max(1, int((snap.get("identity") or {}).get("hosts") or 1))
        if tokens_per_s is not None:
            tokens_per_s = tokens_per_s / slice_hosts

        step_energy_j = (
            node_w * step_seconds
            if power and step_seconds is not None
            else None
        )
        tokens_per_joule = (
            tokens_per_s / node_w
            if power and node_w > 0 and tokens_per_s is not None
            else None
        )
        step_cost = (
            step_energy_j / _J_PER_KWH * t.dollars_per_kwh
            if step_energy_j is not None and t.dollars_per_kwh > 0
            else None
        )

        last = {
            "ts": now,
            "node_power_w": round(node_w, 3) if power else None,
            "source": join_source if power else None,
            "chips": {
                SOURCE_MEASURED: source_counts[SOURCE_MEASURED],
                SOURCE_MODELED: source_counts[SOURCE_MODELED],
            },
            "tdp_w": tdp_w,
            "tdp_key": tdp_key,
            "tokens_per_joule": tokens_per_joule,
            "step_energy_joules": step_energy_j,
            "step_cost_dollars": step_cost,
            "attributed_pods": len(cycle_pods),
        }
        with self._lock:
            self._last = last

        if stats.snapshot is not None:
            # The efficiency-regression detector reads this block from
            # the snapshot the anomaly engine is fed anyway — the
            # tokens/joule series and the workload signature travel on
            # the same bus as every other detector input.
            stats.snapshot["energy"] = {
                "available": bool(power),
                "source": join_source if power else None,
                "node_power_w": node_w if power else None,
                "tokens_per_joule": tokens_per_joule,
                "step_energy_joules": step_energy_j,
                # Baseline identity for "same workload preset": the feed
                # set plus each feed's mesh axes — a changed preset must
                # re-warm the efficiency baseline, not alert against the
                # old workload's tokens/J.
                "workload_sig": tuple(
                    (url, tuple(sorted((feeds[url].get("axes") or {}).items())))
                    for url in sorted(feeds)
                ),
            }
        return self._families(
            stats.base_keys, stats.base_vals, power, joules, pod_joules,
            join_source, step_energy_j, tokens_per_joule, step_cost,
        )

    # -- exposition --------------------------------------------------------

    def _families(
        self, base_keys, base_vals, power, joules, pod_joules,
        join_source, step_energy_j, tokens_per_joule, step_cost,
    ) -> list:
        from tpumon.families import ENERGY_FAMILIES

        labels = tuple(base_keys)
        vals = tuple(base_vals)

        def fam(name, cls):
            _, help_text, extra = ENERGY_FAMILIES[name]
            return cls(name, help_text, labels=labels + extra)

        out: list = []
        if power:
            watts = fam("tpu_energy_power_watts", GaugeMetricFamily)
            for chip in sorted(power):
                w, source = power[chip]
                watts.add_metric(vals + (chip, source), w)
            out.append(watts)
        if joules:
            total = fam("tpu_energy_joules_total", CounterMetricFamily)
            for chip, source in sorted(joules):
                total.add_metric(
                    vals + (chip, source), joules[(chip, source)]
                )
            out.append(total)
        if pod_joules:
            pod_total = fam(
                "tpu_pod_energy_joules_total", CounterMetricFamily
            )
            for ns, pod, source in sorted(pod_joules):
                pod_total.add_metric(
                    vals + (ns, pod, source),
                    pod_joules[(ns, pod, source)],
                )
            out.append(pod_total)
        if step_energy_j is not None:
            step = fam("tpu_step_energy_joules", GaugeMetricFamily)
            step.add_metric(vals + (join_source,), step_energy_j)
            out.append(step)
        if tokens_per_joule is not None:
            tpj = fam("tpu_step_tokens_per_joule", GaugeMetricFamily)
            tpj.add_metric(vals + (join_source,), tokens_per_joule)
            out.append(tpj)
        if step_cost is not None:
            cost = fam("tpu_step_cost_dollars", GaugeMetricFamily)
            cost.add_metric(vals + (join_source,), step_cost)
            out.append(cost)
        return out

    # -- query surfaces ----------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/vars "energy" block: O(1) occupancy + the last
        cycle's join, including the gap-honesty ledger."""
        with self._lock:
            doc = {
                "cycles": self._cycles,
                "chip_series": len(self._joules),
                "pod_series": len(self._pod_joules),
                "total_joules": round(sum(self._joules.values()), 3),
                "gap_skipped_seconds": round(self._gap_skipped_s, 3),
                "gaps_clamped": self._gaps_clamped,
            }
            if self._last is not None:
                doc["last"] = dict(self._last)
            return doc
