"""The efficiency-regression detector: same preset, worse tokens/joule.

Consumes the ``energy`` block the EnergyPlane injects into
PollStats.snapshot (tokens/joule + the workload signature) with the
tpumon.anomaly observe() contract, so efficiency regressions ride the
existing engine: onset/clear events, /anomalies replay, bounded rings,
and the 1 Hz history window of ``tpu_step_tokens_per_joule``.

Design points (ISSUE 12):

- **One-sided**: only *lower* tokens/J onsets — an efficiency
  improvement re-baselines silently (nobody pages on cheaper training).
- **Same workload preset**: the EWMA baseline is keyed to the energy
  block's workload signature (feed set + mesh axes). A different preset
  starting is a different efficiency regime, not a regression — the
  baseline re-warms instead of comparing across workloads.
- **Lifecycle-suppression aware**: during a recognized
  preemption/resize/restore window the detector resets and stays
  silent (and ``efficiency_regression`` rides SUPPRESSIBLE_DETECTORS,
  so anything that does slip through is counted into
  ``tpu_anomaly_suppressed_total``, never raised) — a preempted slice's
  duty collapse at constant step accounting must not read as an
  efficiency cliff.
"""

from __future__ import annotations

import math

from tpumon.energy.model import env_thresholds
from tpumon.health import WARN


class EfficiencyRegressionDetector:
    """EWMA z-score on node tokens/joule, one-sided (lower is worse)."""

    name = "efficiency_regression"
    _family = "tpu_step_tokens_per_joule"

    def __init__(self) -> None:
        #: [mean, var, n] EWMA state on tokens/joule.
        self._state: list[float] = [0.0, 0.0, 0]
        self._sig: tuple | None = None
        self._active = False

    def reset(self) -> None:
        """Lifecycle-suppression re-baseline: the transition explains
        the efficiency move; post-event data re-warms the baseline."""
        self._state = [0.0, 0.0, 0]
        self._active = False

    def observe(self, ts: float, snap: dict, t) -> list:
        from tpumon.anomaly.detectors import Reading

        lc = snap.get("lifecycle") or {}
        if lc.get("transition"):
            self.reset()
            return []
        block = snap.get("energy") or {}
        tpj = block.get("tokens_per_joule")
        if tpj is None or tpj <= 0:
            return []
        et = env_thresholds()
        sig = block.get("workload_sig")
        if sig != self._sig:
            # A different preset (or feed set) is a different efficiency
            # regime — never compare its tokens/J to the old baseline.
            self._sig = sig
            self.reset()
        mean, var, n = self._state
        out: list[Reading] = []
        if n >= et.eff_warmup:
            std = max(
                math.sqrt(max(var, 0.0)),
                et.eff_min_rel_std * max(mean, 1e-12),
            )
            z = (mean - tpj) / std  # positive = WORSE than baseline
            was = self._active
            active = z >= (et.eff_z_clear if was else et.eff_z_warn)
            if active or was:
                source = block.get("source") or "modeled"
                out.append(
                    Reading(
                        "node",
                        active,
                        WARN,
                        tpj,
                        f"tokens/joule {tpj:.4g} is {z:.1f}σ below its "
                        f"{mean:.4g} baseline for the same workload "
                        f"preset ({source} power) — efficiency "
                        "regression",
                        self._family,
                        (),
                    )
                )
            self._active = active
            if active:
                return out  # freeze the baseline while regressed
        # EWMA update (unfrozen path), alpha matching the step detector.
        if n == 0:
            self._state = [tpj, 0.0, 1]
        else:
            d = tpj - mean
            mean += 0.1 * d
            var = (1.0 - 0.1) * (var + 0.1 * d * d)
            self._state = [mean, var, n + 1]
        return out


def energy_detectors() -> list:
    """The efficiency detector roster appended to the anomaly engine
    when the energy plane is enabled."""
    return [EfficiencyRegressionDetector()]


ENERGY_DETECTOR_NAMES: tuple[str, ...] = ("efficiency_regression",)


__all__ = [
    "ENERGY_DETECTOR_NAMES",
    "EfficiencyRegressionDetector",
    "energy_detectors",
]
