"""Power modeling and tuning for the energy/cost plane.

Two power sources, always labeled (``source`` ∈ ``measured`` /
``modeled``) so a dashboard can never pass a model off as a reading:

- **measured** — the device library exposes instantaneous per-chip
  power (the ``device_power`` metric → ``accelerator_power_watts``
  family, tpumon/schema.py). Sampled by the ordinary poll loop like any
  other device metric: the energy plane adds **zero** device queries.
- **modeled** — no power telemetry: per-chip power is estimated as
  duty-cycle × the accelerator generation's TDP envelope, adjusted for
  HBM activity (container-level energy observability per PAPERS.md
  2504.10702 models exactly this way when RAPL-style counters are
  absent). The model is deliberately simple and *maintained*: the TDP
  table below is the contract, ``TPUMON_ENERGY_TDP_W`` overrides it per
  deployment, and docs/OPERATIONS.md carries the maintenance runbook.

Tuning follows the AnomalyThresholds pattern: every field is a
``TPUMON_ENERGY_<FIELD>`` env var, malformed values keep the default,
re-parsed only when the env changes. ``TPUMON_ENERGY_DOLLARS_PER_KWH``
(the cost knob) rides the same dataclass.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, fields

log = logging.getLogger(__name__)

SOURCE_MEASURED = "measured"
SOURCE_MODELED = "modeled"

#: Nominal per-chip power envelope in watts by accelerator-type prefix
#: (longest prefix wins; matched against the lowercased identity label).
#: These are NOMINAL board-level envelopes for capacity math, not
#: measurements — a fleet with power telemetry never consults this
#: table, and one without it can pin exact values via
#: TPUMON_ENERGY_TDP_W. Maintenance: add a row per new generation
#: (docs/OPERATIONS.md "TDP table maintenance").
TDP_TABLE_W: dict[str, float] = {
    "v2": 280.0,
    "v3": 450.0,
    "v4": 275.0,
    "v5litepod": 205.0,  # v5e market name; identity labels say v5litepod
    "v5e": 205.0,
    "v5p": 470.0,
    "v6e": 185.0,
}

#: Fallback for accelerator types the table does not know (the fake
#: bench shapes, future generations before their row lands).
DEFAULT_TDP_W = 250.0


@dataclass(frozen=True)
class EnergyTuning:
    """Energy-plane tuning, overridable per deployment via TPUMON_ENERGY_*."""

    #: Electricity price driving tpu_step_cost_dollars; 0 (the default)
    #: keeps the cost family absent — a made-up price is worse than none.
    dollars_per_kwh: float = 0.0
    #: Per-chip TDP override in watts; 0 = the TDP table above.
    tdp_w: float = 0.0
    #: Idle power as a fraction of TDP (chips draw real power at duty 0:
    #: HBM refresh, ICI SerDes, clocks).
    idle_fraction: float = 0.15
    #: Fraction of the active (TDP - idle) envelope attributed to HBM
    #: activity; the rest follows duty cycle alone. 0 = pure duty model.
    hbm_weight: float = 0.2
    #: Longest poll gap integrated into the joules counters: past this,
    #: the remainder of the gap is NOT integrated (counted in the
    #: /debug/vars energy block instead) — holding the last power
    #: reading across a long outage would invent energy.
    max_gap_s: float = 30.0
    #: Efficiency-regression detector (tokens/joule EWMA, one-sided):
    #: samples before arming, onset/clear z, and the relative std floor.
    eff_warmup: float = 20.0
    eff_z_warn: float = 4.0
    eff_z_clear: float = 2.0
    eff_min_rel_std: float = 0.05

    @classmethod
    def from_env(cls, environ=None) -> "EnergyTuning":
        env = os.environ if environ is None else environ
        kwargs = {}
        for f in fields(cls):
            raw = env.get("TPUMON_ENERGY_" + f.name.upper())
            if raw is None:
                continue
            try:
                kwargs[f.name] = float(raw)
            except ValueError:
                log.warning(
                    "ignoring malformed TPUMON_ENERGY_%s=%r",
                    f.name.upper(), raw,
                )
        return cls(**kwargs)


#: (env-values key, parsed tuning) — re-parse only when the env changed,
#: same cache shape as anomaly/hostcorr/lifecycle env_thresholds.
_env_cache: tuple | None = None


def env_thresholds() -> EnergyTuning:
    global _env_cache
    key = tuple(
        os.environ.get("TPUMON_ENERGY_" + f.name.upper())
        for f in fields(EnergyTuning)
    )
    if _env_cache is None or _env_cache[0] != key:
        _env_cache = (key, EnergyTuning.from_env())
    return _env_cache[1]


def tdp_for(accelerator_type: str | None, t: EnergyTuning) -> tuple[float, str]:
    """(per-chip TDP watts, provenance) for an identity label.

    Provenance is the matched table key, ``"override"`` for the env
    knob, or ``"default"`` — surfaced by doctor so an operator can see
    which row their fleet's model rides on.
    """
    if t.tdp_w > 0:
        return t.tdp_w, "override"
    ident = (accelerator_type or "").lower()
    best: tuple[int, float, str] | None = None
    for prefix, watts in TDP_TABLE_W.items():
        if ident.startswith(prefix) and (
            best is None or len(prefix) > best[0]
        ):
            best = (len(prefix), watts, prefix)
    if best is not None:
        return best[1], best[2]
    return DEFAULT_TDP_W, "default"


def model_power_w(
    duty_pct: float, hbm_ratio: float | None, tdp_w: float, t: EnergyTuning
) -> float:
    """Modeled per-chip power: idle floor plus the active envelope
    scaled by duty cycle, HBM-activity adjusted.

    ``activity = duty × ((1 - hbm_weight) + hbm_weight × hbm_ratio)``:
    a chip at 100% duty with near-empty HBM (a spin loop, a tiny model)
    draws less than one streaming a full HBM — the adjustment is bounded
    by ``hbm_weight`` so a missing ratio degrades to the pure duty model
    rather than guessing.
    """
    idle = t.idle_fraction * tdp_w
    duty = min(max(duty_pct, 0.0), 100.0) / 100.0
    if hbm_ratio is None:
        activity = duty
    else:
        hbm = min(max(hbm_ratio, 0.0), 1.0)
        activity = duty * ((1.0 - t.hbm_weight) + t.hbm_weight * hbm)
    return idle + (tdp_w - idle) * activity


__all__ = [
    "DEFAULT_TDP_W",
    "EnergyTuning",
    "SOURCE_MEASURED",
    "SOURCE_MODELED",
    "TDP_TABLE_W",
    "env_thresholds",
    "model_power_w",
    "tdp_for",
]
