"""Energy & cost plane (ISSUE 12; PAPERS.md 2504.10702, 2605.20799).

Joules per chip, tokens per joule, dollars per step — power sampled
where the device library exposes it, modeled (duty × TDP,
HBM-adjusted) where it doesn't, every family ``source``-labeled so a
model is never passed off as a reading. See tpumon/energy/plane.py for
the poll-cycle pass and docs/OPERATIONS.md for the efficiency-triage
runbook.
"""

from tpumon.energy.detectors import (
    ENERGY_DETECTOR_NAMES,
    EfficiencyRegressionDetector,
    energy_detectors,
)
from tpumon.energy.model import (
    DEFAULT_TDP_W,
    EnergyTuning,
    SOURCE_MEASURED,
    SOURCE_MODELED,
    TDP_TABLE_W,
    env_thresholds,
    model_power_w,
    tdp_for,
)
from tpumon.energy.plane import EnergyPlane

__all__ = [
    "DEFAULT_TDP_W",
    "ENERGY_DETECTOR_NAMES",
    "EfficiencyRegressionDetector",
    "EnergyPlane",
    "EnergyTuning",
    "SOURCE_MEASURED",
    "SOURCE_MODELED",
    "TDP_TABLE_W",
    "energy_detectors",
    "env_thresholds",
    "model_power_w",
    "tdp_for",
]
