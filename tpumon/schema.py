"""Unified ``accelerator_*`` metric schema (SURVEY.md §1 L3, §5.5).

One schema serves a mixed GPU+TPU node pool (BASELINE.json config 5): each
device-library metric (libtpu today, NVML-compat in
:mod:`tpumon.backends.nvml_backend`) maps to a vendor-neutral Prometheus
family, so one Grafana dashboard covers both. The wire formats encoded in
``shape`` were captured verbatim from live
``libtpu.sdk.tpumonitoring.get_metric(...).description()`` probes on
libtpu 0.0.34 (SURVEY.md §2.2).

Shapes:

- ``PER_CHIP`` — one numeric string per chip: ``["0.00", "20.00", ...]``
- ``PER_CORE`` — one numeric string per TensorCore
- ``KEYED`` — ``"key: value"`` strings, e.g. ``"tray1.chip3.ici0.int: 0"``
  (ICI links) or ``"tensorcore_0: 10"`` (HLO queue)
- ``PCTL_KEYED`` — rows ``key, mean, p50, p90, p95, p999``; the key is a
  buffer size (``8MB+``), ``bufsize-COLLECTIVE`` pair, or a core id
- ``PCTL_PLAIN`` — a single ``mean, p50, p90, p95, p999`` row
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class Shape(enum.Enum):
    PER_CHIP = "per_chip"
    PER_CORE = "per_core"
    KEYED = "keyed"
    PCTL_KEYED = "pctl_keyed"
    PCTL_PLAIN = "pctl_plain"


class KeyKind(enum.Enum):
    """How a KEYED/PCTL_KEYED row key translates into labels."""

    NONE = "none"
    BUFFER_SIZE = "buffer_size"  # "8MB+"
    BUFFER_OP = "buffer_op"  # "2MB+-ALL_REDUCE"
    CORE = "core"  # "tensorcore_0"
    ICI_LINK = "ici_link"  # "tray1.chip3.ici0.int"


#: Percentile column names for PCTL_* shapes, in wire order.
STATS: tuple[str, ...] = ("mean", "p50", "p90", "p95", "p999")


@dataclass(frozen=True)
class FamilySpec:
    """One device metric → one Prometheus family."""

    #: Device-library metric name (libtpu.sdk.tpumonitoring name).
    source: str
    #: Prometheus family name in the unified accelerator_* namespace.
    family: str
    shape: Shape
    help: str
    key_kind: KeyKind = KeyKind.NONE
    #: Metric-specific label keys, beyond the host-level base labels.
    labels: tuple[str, ...] = ()

    @property
    def label_keys(self) -> tuple[str, ...]:
        return self.labels


#: The 14 libtpu runtime metrics of libtpu 0.0.34 → unified families,
#: plus forward-looking specs (device_power) for metrics newer runtimes
#: expose. Coverage denominator for the ≥95% BASELINE target is whatever
#: the runtime actually lists (BASELINE.md) — extra specs never inflate it.
LIBTPU_SPECS: tuple[FamilySpec, ...] = (
    FamilySpec(
        "duty_cycle_pct",
        "accelerator_duty_cycle_percent",
        Shape.PER_CHIP,
        "Percent of the sample period the accelerator was executing "
        "(TPU duty cycle; GPU SM-activity analogue).",
        labels=("chip",),
    ),
    FamilySpec(
        "tensorcore_util",
        "accelerator_core_utilization_percent",
        Shape.PER_CORE,
        "Per-core compute utilization percent (TPU TensorCore; GPU SM-util "
        "analogue).",
        labels=("core",),
    ),
    FamilySpec(
        "hbm_capacity_total",
        "accelerator_memory_total_bytes",
        Shape.PER_CHIP,
        "Total device memory per chip in bytes (TPU HBM; GPU framebuffer "
        "analogue).",
        labels=("chip",),
    ),
    FamilySpec(
        "hbm_capacity_usage",
        "accelerator_memory_used_bytes",
        Shape.PER_CHIP,
        "Allocated device memory per chip in bytes.",
        labels=("chip",),
    ),
    FamilySpec(
        "device_power",
        "accelerator_power_watts",
        Shape.PER_CHIP,
        "Instantaneous per-chip power draw in watts, where the device "
        "library exposes power telemetry (GPU nvmlDeviceGetPowerUsage "
        "analogue). Absent on runtimes without it — the energy plane "
        "(tpumon/energy) then models power from duty cycle × TDP and "
        "labels it source=modeled.",
        labels=("chip",),
    ),
    FamilySpec(
        "tpu_throttle_score",
        "accelerator_throttle_score",
        Shape.PER_CHIP,
        "Device throttling score: 0 = none, 1-10 = throttled by 10-100% "
        "(GPU thermal/power-throttle analogue).",
        labels=("chip",),
    ),
    FamilySpec(
        "ici_link_health",
        "accelerator_interconnect_link_health",
        Shape.KEYED,
        "Interconnect link health: 0 healthy, 1-5 transient, 6-9 persistent "
        "minor, 10 unusable (TPU ICI; GPU NVLink-error analogue).",
        key_kind=KeyKind.ICI_LINK,
        labels=("link", "tray", "chip", "port", "dir"),
    ),
    FamilySpec(
        "hlo_queue_size",
        "accelerator_queue_size",
        Shape.KEYED,
        "Enqueued-but-not-dequeued device programs per core (HLO queue "
        "depth).",
        key_kind=KeyKind.CORE,
        labels=("core",),
    ),
    FamilySpec(
        "hlo_execution_timing",
        "accelerator_op_latency_microseconds",
        Shape.PCTL_KEYED,
        "Device program (HLO) enqueue-to-dequeue latency percentiles per "
        "core, microseconds.",
        key_kind=KeyKind.CORE,
        labels=("core", "stat"),
    ),
    FamilySpec(
        "collective_e2e_latency",
        "accelerator_collective_latency_microseconds",
        Shape.PCTL_KEYED,
        "End-to-end collective-operation latency percentiles by buffer size "
        "and collective type, microseconds (rides ICI intra-slice).",
        key_kind=KeyKind.BUFFER_OP,
        labels=("buffer_size", "op", "stat"),
    ),
    FamilySpec(
        "buffer_transfer_latency",
        "accelerator_dcn_transfer_latency_microseconds",
        Shape.PCTL_KEYED,
        "Cross-slice (DCN) buffer-transfer latency percentiles by buffer "
        "size, microseconds.",
        key_kind=KeyKind.BUFFER_SIZE,
        labels=("buffer_size", "stat"),
    ),
    FamilySpec(
        "host_to_device_transfer_latency",
        "accelerator_h2d_transfer_latency_microseconds",
        Shape.PCTL_KEYED,
        "Host-to-device transfer latency percentiles by buffer size, "
        "microseconds.",
        key_kind=KeyKind.BUFFER_SIZE,
        labels=("buffer_size", "stat"),
    ),
    FamilySpec(
        "device_to_host_transfer_latency",
        "accelerator_d2h_transfer_latency_microseconds",
        Shape.PCTL_KEYED,
        "Device-to-host transfer latency percentiles by buffer size, "
        "microseconds.",
        key_kind=KeyKind.BUFFER_SIZE,
        labels=("buffer_size", "stat"),
    ),
    FamilySpec(
        "tcp_min_rtt",
        "accelerator_network_min_rtt_microseconds",
        Shape.PCTL_PLAIN,
        "Minimum TCP round-trip-time percentiles on the DCN path, "
        "microseconds.",
        labels=("stat",),
    ),
    FamilySpec(
        "tcp_delivery_rate",
        "accelerator_network_delivery_rate_mbps",
        Shape.PCTL_PLAIN,
        "TCP delivery-rate percentiles on the DCN path, Mbps.",
        labels=("stat",),
    ),
)

SPECS_BY_SOURCE: dict[str, FamilySpec] = {s.source: s for s in LIBTPU_SPECS}
SPECS_BY_FAMILY: dict[str, FamilySpec] = {s.family: s for s in LIBTPU_SPECS}


def spec_for(source: str) -> FamilySpec | None:
    return SPECS_BY_SOURCE.get(source)


def coverage(supported: tuple[str, ...] | list[str]) -> float:
    """Fraction of the device library's supported metrics we map.

    This is the BASELINE headline 'libtpu metric coverage (%)': the
    denominator is whatever ``list_supported_metrics()`` reports at runtime,
    so new libtpu releases that add metrics lower the score until specs are
    added here.
    """
    if not supported:
        return 1.0
    mapped = sum(1 for name in supported if name in SPECS_BY_SOURCE)
    return mapped / len(supported)
