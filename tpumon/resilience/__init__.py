"""Fault-tolerance policy layer (the "survives them" half of the trace plane).

The paper's core promise is an exporter that keeps answering scrapes no
matter what the device runtime does. PR 2's trace plane made slow/stuck
cycles *visible*; this package makes the exporter *survive* them:

- :mod:`tpumon.resilience.policy` — bounded exponential backoff with
  jitter (:class:`Backoff`), per-call retry with an overall deadline
  (:class:`RetryPolicy` / :func:`retry_call`).
- :mod:`tpumon.resilience.breaker` — per-query circuit breaker
  (closed → open → half-open → closed) with a throttled probe schedule,
  so a dead runtime costs one probe per window instead of a timeout per
  poll (:class:`CircuitBreaker` / :class:`BreakerRegistry`).
- :mod:`tpumon.resilience.degrade` — the last-good family cache backing
  stale-but-served degradation: when a query fails or its breaker is
  open, the exporter serves the last good sample with explicit
  freshness metadata instead of dropping the family
  (:class:`PollResilience`).
- :mod:`tpumon.resilience.watchdog` — poll-loop hang detection +
  recovery by backend interrupt/channel teardown
  (:class:`PollWatchdog`).
- :mod:`tpumon.resilience.faults` — deterministic fault injection
  (:class:`FaultInjectingBackend`, ``TPUMON_FAULTS``) so every failure
  mode above is exercised in CI rather than asserted in prose.

Degradation is always *observable*: ``tpumon_up`` / ``tpumon_degraded``
/ ``tpumon_family_staleness_seconds`` / ``tpumon_breaker_state`` ride
the self-telemetry registry (tpumon/families.py, docs/METRICS.md).
"""

from __future__ import annotations

from tpumon.resilience.breaker import BreakerRegistry, CircuitBreaker
from tpumon.resilience.degrade import PollResilience
from tpumon.resilience.faults import FaultInjectingBackend, FaultSpec
from tpumon.resilience.policy import (
    Backoff,
    RetryCounter,
    RetryPolicy,
    retry_call,
)
from tpumon.resilience.watchdog import PollWatchdog

__all__ = [
    "Backoff",
    "BreakerRegistry",
    "CircuitBreaker",
    "FaultInjectingBackend",
    "FaultSpec",
    "PollResilience",
    "PollWatchdog",
    "RetryCounter",
    "RetryPolicy",
    "retry_call",
]
