"""Backoff and retry policy: the lowest layer of the resilience stack.

Design rules, distilled from what actually bites in a 1 Hz poll loop:

- **Bounded.** Delays cap at ``max_s`` and attempts at ``attempts``; a
  retry storm can never outlive its caller's budget, and an overall
  ``deadline_s`` stops a retry sequence even when individual calls are
  fast-failing.
- **Jittered.** Full deterministic backoff synchronizes every exporter
  in a DaemonSet against a shared dependency (the kubelet socket, a
  slice-wide runtime restart); each delay is multiplied by a uniform
  factor in ``[1 - jitter, 1 + jitter]``.
- **Observable.** ``retry_call`` reports each retry through an optional
  callback; the poller folds those counts into
  ``tpumon_retries_total{call}``.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with jitter, shared by every caller.

    ``attempts`` counts total tries (1 = no retry). The k-th retry waits
    ``min(base_s * 2**k, max_s)`` scaled by the jitter factor.
    ``deadline_s`` (when set) bounds the whole sequence: no retry starts
    after the deadline has elapsed since the first attempt.
    """

    attempts: int = 2
    base_s: float = 0.05
    max_s: float = 1.0
    jitter: float = 0.5
    deadline_s: float | None = None

    def delay(self, retry_index: int, rng: random.Random | None = None) -> float:
        """Delay before the ``retry_index``-th retry (0-based), jittered."""
        roll = rng.random() if rng is not None else random.random()
        capped = min(self.base_s * (2.0 ** retry_index), self.max_s)
        lo = max(0.0, 1.0 - self.jitter)
        return capped * (lo + (1.0 + self.jitter - lo) * roll)

    def delay_bounds(self, retry_index: int) -> tuple[float, float]:
        """[lo, hi] envelope of :meth:`delay` — the testable contract."""
        capped = min(self.base_s * (2.0 ** retry_index), self.max_s)
        return capped * max(0.0, 1.0 - self.jitter), capped * (1.0 + self.jitter)


def retry_call(
    fn,
    policy: RetryPolicy,
    *,
    rng: random.Random | None = None,
    clock=time.monotonic,
    sleep=time.sleep,
    on_retry=None,
    retryable=Exception,
):
    """Call ``fn()`` under ``policy``; re-raises the last failure.

    ``on_retry(attempt_index, exc)`` fires before each retry sleep (the
    counting hook). ``retryable`` narrows which exceptions are retried —
    anything else propagates immediately.
    """
    t0 = clock()
    attempts = max(1, int(policy.attempts))
    last_exc: BaseException | None = None
    for attempt in range(attempts):
        try:
            return fn()
        except retryable as exc:
            last_exc = exc
            if attempt + 1 >= attempts:
                break
            delay = policy.delay(attempt, rng)
            if (
                policy.deadline_s is not None
                and clock() - t0 + delay > policy.deadline_s
            ):
                break
            if on_retry is not None:
                on_retry(attempt, exc)
            if delay > 0:
                sleep(delay)
    assert last_exc is not None
    raise last_exc


class RetryCounter:
    """Retry accounting shared by the transport backends.

    Wraps :func:`retry_call` and tallies retries by call kind — the
    ``tpumon_retries_total{call}`` feed, delta-read by the poller via
    each backend's ``retry_counts()``.
    """

    def __init__(self) -> None:
        self._counts: dict[str, int] = {}

    def call(self, call: str, fn, policy: RetryPolicy):
        def note(_attempt, _exc) -> None:
            self._counts[call] = self._counts.get(call, 0) + 1

        return retry_call(fn, policy, on_retry=note)

    def counts(self) -> dict[str, int]:
        return dict(self._counts)


class Backoff:
    """Stateful bounded exponential backoff for poll-by-poll callers.

    For code that decides "should I try again *this cycle*" rather than
    retrying inline (pod attribution, stream reopen): each failure
    advances the delay ``base_s, 2*base_s, ... max_s`` (jittered), a
    success resets it. Never sleeps — callers schedule themselves.
    """

    def __init__(
        self,
        base_s: float = 5.0,
        max_s: float = 300.0,
        jitter: float = 0.25,
        rng: random.Random | None = None,
    ) -> None:
        self.base_s = base_s
        self.max_s = max_s
        self.jitter = jitter
        self._rng: random.Random | None = rng
        self.failures = 0

    def next_delay(self) -> float:
        """Register one failure and return the delay before the next try."""
        # Exponent clamped: 2.0**1024 raises OverflowError, and a
        # years-long outage must keep backing off, not start storming.
        capped = min(self.base_s * (2.0 ** min(self.failures, 32)), self.max_s)
        self.failures += 1
        roll = self._rng.random() if self._rng is not None else random.random()
        lo = max(0.0, 1.0 - self.jitter)
        return capped * (lo + (1.0 + self.jitter - lo) * roll)

    def reset(self) -> None:
        self.failures = 0


__all__ = ["Backoff", "RetryCounter", "RetryPolicy", "retry_call"]
