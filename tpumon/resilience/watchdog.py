"""Poll-loop watchdog: detect a stuck device call, trigger recovery.

Python cannot kill a thread blocked inside a native device call, so the
watchdog recovers the *call*, not the thread: when a poll cycle runs
past ``hang_budget_s``, it fires ``on_hang()``, which the exporter wires
to backend teardown — ``interrupt()`` (fault-injection hangs release
immediately) and ``reset()`` (the gRPC backend closes its channel, which
fails any in-flight RPC at the transport layer and forces a clean
re-dial on the next cycle). The blocked call then raises, the cycle
completes as a counted backend error, and stale-but-served degradation
carries ``/metrics`` throughout.

The monitor thread wakes at ``hang_budget_s / 4`` granularity (floored
at 50 ms) and fires at most once per budget overrun — a cycle stuck for
``3 * hang_budget_s`` gets three recovery attempts, not a busy loop.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

log = logging.getLogger(__name__)


class PollWatchdog:
    def __init__(
        self,
        hang_budget_s: float,
        on_hang: Callable[[], None],
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if hang_budget_s <= 0:
            raise ValueError(f"hang budget must be > 0, got {hang_budget_s}")
        self.hang_budget_s = hang_budget_s
        self._on_hang = on_hang
        self._clock = clock
        self._lock = threading.Lock()
        self._cycle_started: float | None = None  # guarded-by: self._lock
        self._fired_for: float | None = None  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tpumon-watchdog", daemon=True
        )
        #: Recoveries triggered since start (mirrored into
        #: tpumon_watchdog_recoveries_total by the exporter's hook).
        self.recoveries = 0

    # -- heartbeat (called from the poller thread) ------------------------

    def cycle_started(self) -> None:
        with self._lock:
            self._cycle_started = self._clock()
            self._fired_for = None

    def beat(self) -> None:
        """Progress heartbeat: each completed device call resets the
        hang timer. A cycle that is slow because every call fails at its
        bounded per-call deadline (black-holed endpoint) is *progressing*
        — that outage belongs to the breakers; the watchdog must only
        fire when one call is actually stuck past the budget."""
        with self._lock:
            if self._cycle_started is not None:
                self._cycle_started = self._clock()
                self._fired_for = None

    def cycle_finished(self) -> None:
        with self._lock:
            self._cycle_started = None
            self._fired_for = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    # -- monitor -----------------------------------------------------------

    def check(self) -> bool:
        """One monitor evaluation; fires on_hang when the current cycle
        overran the budget (and hasn't been fired for yet). Public so
        tests can drive the state machine without the thread."""
        with self._lock:
            started = self._cycle_started
            if started is None:
                return False
            now = self._clock()
            overrun = now - started
            if overrun < self.hang_budget_s:
                return False
            if self._fired_for is not None and (
                now - self._fired_for < self.hang_budget_s
            ):
                return False
            self._fired_for = now
            self.recoveries += 1
        log.warning(
            "poll cycle stuck for %.1fs (budget %.1fs); recovering backend",
            overrun,
            self.hang_budget_s,
        )
        try:
            self._on_hang()
        except Exception:
            log.exception("watchdog recovery hook failed")
        return True

    def _run(self) -> None:
        tick = max(0.05, self.hang_budget_s / 4.0)
        while not self._stop.wait(timeout=tick):
            self.check()


__all__ = ["PollWatchdog"]
