"""Deterministic fault injection: the chaos backend.

:class:`FaultInjectingBackend` wraps any real backend and injects the
failure modes the resilience plane claims to survive — so the claim is
exercised in CI (tests/test_chaos.py, ``tools/soak.py --chaos``) rather
than asserted in prose:

- ``error_rate`` / ``list_error_rate`` — BackendError on that fraction
  of sample / enumeration calls (seeded RNG: deterministic given the
  call sequence);
- ``latency_ms`` — added latency per device call (GIL-holding runtime
  stalls in miniature);
- ``hang_every`` / ``hang_s`` — every Nth sample call blocks for
  ``hang_s`` seconds, releasable early by :meth:`interrupt` (what the
  poll watchdog calls on recovery);
- ``garbage_rate`` / ``partial_rate`` — malformed rows the parser must
  skip-and-count, and half-dropped payloads;
- ``flap_start`` / ``flap_end`` — a poll-cycle window in which the
  runtime flaps attached/detached every cycle (empty vectors on the
  detached beats — absent, not zero).

Every injected call is counted in :attr:`calls` (by query) so tests can
assert the breaker's probe schedule caps device-query attempts during
an outage.

Configured via the ``TPUMON_FAULTS`` spec string, e.g.::

    TPUMON_FAULTS="error_rate=0.3,hang_every=20,hang_s=10,flap_start=30,flap_end=45"

Unknown or malformed tokens warn and are skipped — a typo'd chaos spec
must degrade the chaos, never the exporter.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import Counter
from dataclasses import dataclass, fields

from tpumon.backends.base import BackendError, RawMetric

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class FaultSpec:
    """Parsed ``TPUMON_FAULTS`` spec; all rates in [0, 1], times as noted."""

    #: Fraction of sample() calls that raise BackendError.
    error_rate: float = 0.0
    #: Fraction of list_metrics() calls that raise BackendError.
    list_error_rate: float = 0.0
    #: Added latency per device call, milliseconds.
    latency_ms: float = 0.0
    #: Every Nth sample() call hangs (0 disables).
    hang_every: float = 0.0
    #: Hang duration in seconds (interrupt() releases early).
    hang_s: float = 10.0
    #: Fraction of sample() payloads corrupted with unparseable rows.
    garbage_rate: float = 0.0
    #: Fraction of sample() payloads truncated to half their rows.
    partial_rate: float = 0.0
    #: Poll-cycle window [start, end) in which the runtime flaps
    #: attached/detached every cycle (0/0 disables).
    flap_start: float = 0.0
    flap_end: float = 0.0
    #: RNG seed for deterministic injection.
    seed: float = 0.0

    @classmethod
    def parse(cls, spec: str) -> "FaultSpec":
        """``key=value,key=value`` → FaultSpec; bad tokens warn + skip."""
        known = {f.name for f in fields(cls)}
        kwargs: dict[str, float] = {}
        for token in (spec or "").split(","):
            token = token.strip()
            if not token:
                continue
            key, sep, raw = token.partition("=")
            key = key.strip()
            if not sep or key not in known:
                log.warning("ignoring unknown TPUMON_FAULTS token %r", token)
                continue
            try:
                kwargs[key] = float(raw.strip())
            except ValueError:
                log.warning("ignoring malformed TPUMON_FAULTS token %r", token)
        return cls(**kwargs)

    def describe(self) -> str:
        """Compact non-default-fields form (doctor / soak records)."""
        base = type(self)()
        parts = [
            f"{f.name}={getattr(self, f.name):g}"
            for f in fields(self)
            if getattr(self, f.name) != getattr(base, f.name)
        ]
        return ",".join(parts) or "none"


class FaultInjectingBackend:
    """Backend wrapper injecting the configured faults deterministically.

    Everything not overridden (topology, version, core_states, sources,
    watch_states, ...) passes through to the wrapped backend.
    """

    def __init__(self, inner, spec: FaultSpec, sleep=time.sleep, retry=None) -> None:
        from tpumon.resilience.policy import RetryCounter

        self._inner = inner
        self.spec = spec
        self.name = f"{inner.name}+faults"
        self._sleep = sleep
        #: Optional transport-style retry around the *injected* faults:
        #: with it, chaos exercises the retry plane for real (injected
        #: errors get retried and tpumon_retries_total moves) — the
        #: layer a wrapped-outside fault injector would otherwise never
        #: reach. None = raw injection (unit-test determinism).
        self.retry = retry
        self._retries = RetryCounter()
        self._rng = random.Random(int(spec.seed))
        self._lock = threading.Lock()
        self._hang_release = threading.Event()
        self._sample_calls = 0
        self._cycle = 0
        #: Device-call attempts by query key ("sample:<metric>",
        #: "list_metrics") — the breaker-probe-schedule evidence.
        self.calls: Counter = Counter()
        #: Injected-fault tallies, by kind.
        self.injected: Counter = Counter()

    # -- chaos controls ----------------------------------------------------

    def interrupt(self) -> None:
        """Release any in-progress injected hang immediately (the poll
        watchdog's recovery hook)."""
        self._hang_release.set()

    def reset(self) -> None:
        """Watchdog teardown: release hangs, forward to the inner backend."""
        self.interrupt()
        inner_reset = getattr(self._inner, "reset", None)
        if inner_reset is not None:
            inner_reset()

    def _flapping_detached(self) -> bool:
        s, e = self.spec.flap_start, self.spec.flap_end
        if not (s or e) or not (s <= self._cycle < e):
            return False
        return (self._cycle - int(s)) % 2 == 0

    def _maybe_hang(self) -> None:
        every = int(self.spec.hang_every)
        if every <= 0:
            return
        with self._lock:
            self._sample_calls += 1
            due = self._sample_calls % every == 0
        if not due:
            return
        self.injected["hang"] += 1
        # A fresh hang ignores interrupts aimed at an earlier one.
        self._hang_release.clear()
        released = self._hang_release.wait(self.spec.hang_s)
        if released:
            self._hang_release.clear()
            self.injected["hang_interrupted"] += 1
            raise BackendError("injected hang interrupted by recovery")
        # An uninterrupted hang is just a very slow call: proceed.

    def _maybe_latency(self) -> None:
        if self.spec.latency_ms > 0:
            self._sleep(self.spec.latency_ms / 1e3)

    def _corrupt(self, raw: RawMetric) -> RawMetric:
        data = raw.data
        if data and self.spec.partial_rate > 0 and (
            self._rng.random() < self.spec.partial_rate
        ):
            self.injected["partial"] += 1
            data = data[: max(1, len(data) // 2)]
        if data and self.spec.garbage_rate > 0 and (
            self._rng.random() < self.spec.garbage_rate
        ):
            self.injected["garbage"] += 1
            data = ("not-a-number",) + data[1:] + ("trailing: garbage: x",)
        return RawMetric(raw.name, data)

    # -- Backend protocol --------------------------------------------------

    def list_metrics(self):
        if self.retry is None:
            return self._list_once()
        return self._retries.call("faults:list", self._list_once, self.retry)

    def _list_once(self):
        self.calls["list_metrics"] += 1
        self._maybe_latency()
        if self.spec.list_error_rate > 0 and (
            self._rng.random() < self.spec.list_error_rate
        ):
            self.injected["list_error"] += 1
            raise BackendError("injected enumeration failure")
        return self._inner.list_metrics()

    def sample(self, name: str) -> RawMetric:
        if self.retry is None:
            return self._sample_once(name)
        return self._retries.call(
            "faults:sample", lambda: self._sample_once(name), self.retry
        )

    def _sample_once(self, name: str) -> RawMetric:
        self.calls[f"sample:{name}"] += 1
        self._maybe_hang()
        self._maybe_latency()
        if self._flapping_detached():
            self.injected["flap_detach"] += 1
            return RawMetric(name, ())
        if self.spec.error_rate > 0 and (
            self._rng.random() < self.spec.error_rate
        ):
            self.injected["error"] += 1
            raise BackendError(f"injected failure for {name}")
        return self._corrupt(self._inner.sample(name))

    def retry_counts(self) -> dict[str, int]:
        out = self._retries.counts()
        inner_counts = getattr(self._inner, "retry_counts", None)
        if inner_counts is not None:
            for call, n in inner_counts().items():
                out[call] = out.get(call, 0) + n
        return out

    def advance(self, steps: int = 1) -> None:
        """Poll-cycle clock for the flap window; forwards to backends
        that have a time dimension (the fake)."""
        self._cycle += steps
        inner_advance = getattr(self._inner, "advance", None)
        if inner_advance is not None:
            inner_advance(steps)

    def topology(self):
        return self._inner.topology()

    def version(self) -> str:
        return self._inner.version()

    def close(self) -> None:
        self.interrupt()
        self._inner.close()

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


__all__ = ["FaultInjectingBackend", "FaultSpec"]
