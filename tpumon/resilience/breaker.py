"""Per-query circuit breaker: closed → open → half-open → closed.

One breaker per device query (plus one for metric enumeration), owned by
the poll loop via :class:`tpumon.resilience.degrade.PollResilience`. The
contract that matters operationally:

- **Closed** — calls flow; ``failures`` consecutive failures open it.
- **Open** — calls are refused for ``open_s`` seconds. The exporter
  serves last-good data meanwhile (stale-but-served), so an open breaker
  costs *zero* device calls per poll instead of a timeout per poll.
- **Half-open** — after ``open_s``, exactly one probe call is admitted
  per poll; ``probes`` consecutive probe successes close the breaker,
  any probe failure re-opens it (restarting the window). Device-query
  attempts during an outage are therefore capped by the probe schedule:
  at most ``ceil(outage / open_s)`` probes.

Thread model: used from the poller thread; ``state``/``snapshot`` may be
read from HTTP threads — a lock guards the tiny state transitions.
"""

from __future__ import annotations

import threading
import time

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: Numeric encoding for the tpumon_breaker_state gauge (docs/METRICS.md).
STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitBreaker:
    def __init__(
        self,
        failures: int = 5,
        open_s: float = 15.0,
        probes: int = 2,
        clock=time.monotonic,
    ) -> None:
        self.failures = max(1, int(failures))
        self.open_s = open_s
        self.probes = max(1, int(probes))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED  # guarded-by: self._lock
        self._consecutive_failures = 0  # guarded-by: self._lock
        self._probe_successes = 0  # guarded-by: self._lock
        self._opened_at = 0.0  # guarded-by: self._lock
        #: Monotonic transition counter (observability, never reset).
        self.opens = 0  # guarded-by: self._lock

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May the caller attempt the guarded call right now?

        Open → half-open happens here (time-driven), so the first call
        after the window elapses is the probe.
        """
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.open_s:
                    self._state = HALF_OPEN
                    self._probe_successes = 0
                    return True
                return False
            # Half-open: one probe per allow() — the poll loop calls once
            # per cycle per query, so this throttles probes to poll cadence.
            return True

    def record(self, ok: bool) -> None:
        with self._lock:
            if ok:
                if self._state == HALF_OPEN:
                    self._probe_successes += 1
                    if self._probe_successes >= self.probes:
                        self._state = CLOSED
                        self._consecutive_failures = 0
                elif self._state == CLOSED:
                    self._consecutive_failures = 0
                return
            if self._state == HALF_OPEN:
                self._trip()
                return
            self._consecutive_failures += 1
            if self._state == CLOSED and (
                self._consecutive_failures >= self.failures
            ):
                self._trip()

    def _trip(self) -> None:  # holds: self._lock
        self._state = OPEN
        self._opened_at = self._clock()
        self._consecutive_failures = 0
        self._probe_successes = 0
        self.opens += 1


class BreakerRegistry:
    """Lazily-created breakers keyed by query name, shared settings."""

    def __init__(
        self,
        failures: int = 5,
        open_s: float = 15.0,
        probes: int = 2,
        clock=time.monotonic,
    ) -> None:
        self._failures = failures
        self._open_s = open_s
        self._probes = probes
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}  # guarded-by: self._lock

    def get(self, key: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = CircuitBreaker(
                    self._failures, self._open_s, self._probes, self._clock
                )
                self._breakers[key] = br
            return br

    def states(self) -> dict[str, str]:
        with self._lock:
            items = list(self._breakers.items())
        return {key: br.state for key, br in items}

    def open_count(self) -> int:
        return sum(1 for s in self.states().values() if s != CLOSED)


__all__ = [
    "CLOSED",
    "HALF_OPEN",
    "OPEN",
    "STATE_VALUES",
    "BreakerRegistry",
    "CircuitBreaker",
]
