"""Stale-but-served degradation state for the poll loop.

:class:`PollResilience` is the bridge between the policy layer and the
collector: it owns one circuit breaker per device query plus the
last-good cache that keeps ``/metrics`` populated while the device
runtime misbehaves. The semantics encode the SURVEY distinctions:

- a **failed** query (BackendError, breaker open) serves the last good
  family for up to ``stale_serve_s`` seconds, flagged via
  ``tpumon_degraded`` and ``tpumon_family_staleness_seconds{family}`` —
  stale data labeled stale beats a silent gap;
- an **empty** query (runtime detached) is truth, not failure: the
  family goes absent AND the last-good entry is dropped, so a detach
  can never be masked by stale serving (absent ≠ zero, SURVEY §2.2);
- a failed **enumeration** keeps sampling from the last good metric
  list (data keeps flowing) while coverage still reads 0.0 — the
  enumeration-outage alert fires exactly then (collector contract).

Thread model: mutation happens on the poller thread only; ``snapshot``
is read from HTTP threads (doctor, /debug/vars) under the same lock.
"""

from __future__ import annotations

import threading
import time

from tpumon.resilience.breaker import BreakerRegistry


class PollResilience:
    def __init__(
        self,
        *,
        breaker_failures: int = 5,
        breaker_open_s: float = 15.0,
        breaker_probes: int = 2,
        stale_serve_s: float = 300.0,
        clock=time.time,
        breaker_clock=time.monotonic,
    ) -> None:
        self.stale_serve_s = stale_serve_s
        self.breakers = BreakerRegistry(
            failures=breaker_failures,
            open_s=breaker_open_s,
            probes=breaker_probes,
            clock=breaker_clock,
        )
        self._clock = clock
        self._lock = threading.Lock()
        #: metric name -> (family object, family name, stored-at ts)
        self._last_good: dict[str, tuple[object, str, float]] = {}  # guarded-by: self._lock
        self._supported: tuple[tuple[str, ...], float] | None = None  # guarded-by: self._lock

    # -- last-good families -----------------------------------------------

    def store(self, metric: str, family, ts: float | None = None) -> None:
        with self._lock:
            self._last_good[metric] = (
                family,
                getattr(family, "name", metric),
                ts if ts is not None else self._clock(),
            )

    def forget(self, metric: str) -> None:
        """Empty sample = runtime detached: absent is the truth now."""
        with self._lock:
            self._last_good.pop(metric, None)

    def stale(self, metric: str, now: float | None = None):
        """(family, family_name, age_s) if a servable last-good exists."""
        now = now if now is not None else self._clock()
        with self._lock:
            entry = self._last_good.get(metric)
        if entry is None:
            return None
        family, fam_name, ts = entry
        age = max(0.0, now - ts)
        # stale_serve_s <= 0 disables stale serving entirely (the
        # documented TPUMON_STALE_SERVE_S=0 opt-out) — never "no cap".
        if self.stale_serve_s <= 0 or age > self.stale_serve_s:
            return None
        return family, fam_name, age

    # -- last-good enumeration --------------------------------------------

    def store_supported(self, supported, ts: float | None = None) -> None:
        with self._lock:
            self._supported = (
                tuple(supported),
                ts if ts is not None else self._clock(),
            )

    def stale_supported(self, now: float | None = None):
        """The last good metric list (with age), if still servable."""
        now = now if now is not None else self._clock()
        with self._lock:
            entry = self._supported
        if entry is None:
            return None
        supported, ts = entry
        age = max(0.0, now - ts)
        if self.stale_serve_s <= 0 or age > self.stale_serve_s:
            return None
        return supported, age

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/vars + doctor surface: breaker states and the ages
        of every last-good entry (O(queries), no device calls)."""
        now = self._clock()
        with self._lock:
            ages = {
                fam_name: round(max(0.0, now - ts), 3)
                for _, fam_name, ts in self._last_good.values()
            }
            supported = self._supported
        return {
            "stale_serve_s": self.stale_serve_s,
            "breakers": self.breakers.states(),
            "breakers_open": self.breakers.open_count(),
            "last_good_age_s": ages,
            "last_good_enumeration_age_s": (
                round(max(0.0, now - supported[1]), 3) if supported else None
            ),
        }


__all__ = ["PollResilience"]
