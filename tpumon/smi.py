"""`tpumon smi` — the nvidia-smi / tpu-smi analogue for this stack.

GPU-monitor stacks of the reference genre ship an operator CLI that prints
a per-device status table (nvidia-smi; `dcgmi dmon`). This is the
TPU-native equivalent: one table per chip (duty cycle, HBM, throttle,
queue depth), core utilization, ICI link health, and — when a running
exporter's /history endpoint is reachable — 60 s min/avg/max trends from
the 1 Hz flight recorder (tpumon.history), which a plain scrape cannot
show.

Two data sources:

- ``--url http://node:9400`` scrapes a running exporter (/metrics for
  current values + identity, /history for trends). This is the normal
  operator path: the CLI never touches the device, so it is safe on a
  node whose runtime is busy.
- ``--backend fake|libtpu|stub|...`` builds a backend in-process and
  polls it once (no exporter required; used by the doctor flow and
  air-gapped debugging).

``--watch N`` refreshes every N seconds; ``--json`` emits the machine
-readable form of the same snapshot.
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import time
import urllib.error
import urllib.request

from prometheus_client.parser import text_string_to_metric_families

#: Everything a dying — or simply non-exporter — listener can throw
#: mid-request: connect failures (URLError/OSError), torn connections
#: mid-body (IncompleteRead and friends are HTTPException, not OSError),
#: non-exposition response text (parser ValueError). Shared by the
#: fleet fetcher, the first-snapshot probe, and the watch loop, so an
#: unrelated service on 9400 degrades to the in-process fallback (or an
#: UNREACHABLE fleet row) instead of crashing smi. Same curated set as
#: tpumon/fleet/ingest.FETCH_ERRORS.
FETCH_ERRORS: tuple = (
    urllib.error.URLError,
    OSError,
    http.client.HTTPException,
    ValueError,
)

# Families rendered into the table, keyed by their per-chip label.
_F_DUTY = "accelerator_duty_cycle_percent"
_F_HBM_USED = "accelerator_memory_used_bytes"
_F_HBM_TOTAL = "accelerator_memory_total_bytes"
_F_THROTTLE = "accelerator_throttle_score"
_F_CORE_UTIL = "accelerator_core_utilization_percent"
_F_QUEUE = "accelerator_queue_size"
_F_ICI = "accelerator_interconnect_link_health"
_F_INFO = "accelerator_info"
_F_COUNT = "accelerator_device_count"
_F_COVERAGE = "exporter_metric_coverage_ratio"
_F_WATCH = "accelerator_monitor_watch_streams"
_F_NET_RATE = "accelerator_network_delivery_rate_mbps"
_F_DEGRADED = "tpumon_degraded"
_F_STALENESS = "tpumon_family_staleness_seconds"
_F_BREAKER = "tpumon_breaker_state"
_F_GUARD_STATE = "tpumon_guard_state"
#: The parser strips the _total suffix from counter families.
_F_SHED = "tpumon_shed_requests"
_F_CARDINALITY = "tpumon_cardinality_dropped_series"
_F_HOSTCORR_AVAILABLE = "tpu_hostcorr_available"
_F_STRAGGLER_SKEW = "tpu_straggler_skew_pct"
_F_STRAGGLER_VERDICT = "tpu_straggler_verdict"
_F_POWER = "accelerator_power_watts"
_F_POD_INFO = "accelerator_pod_info"
_F_ENERGY_WATTS = "tpu_energy_power_watts"
_F_TOKENS_PER_JOULE = "tpu_step_tokens_per_joule"
_F_STEP_COST = "tpu_step_cost_dollars"


def _fetch(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def _human_bytes(n: float) -> str:
    for unit in ("B", "Ki", "Mi", "Gi", "Ti"):
        if abs(n) < 1024 or unit == "Ti":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024
    return f"{n:.1f}Ti"


def snapshot_from_text(text: str) -> dict:
    """Parse a /metrics page into the structured snapshot smi renders."""
    return snapshot_from_families(text_string_to_metric_families(text))


def snapshot_from_families(families) -> dict:
    """Build the snapshot from metric-family objects directly.

    Works on both parser output and prometheus_client core families (the
    exporter's poll-cycle output) — same ``.name``/``.samples`` shape — so
    in-process consumers (/health/devices, doctor) skip the text
    render+parse roundtrip.
    """
    fams = {f.name: f for f in families}

    snap: dict = {
        "identity": {},
        "chips": {},
        "cores": {},
        "ici": {"healthy": 0, "total": 0, "worst": None},
        "coverage": None,
        "device_count": None,
    }

    info = fams.get(_F_INFO)
    if info is not None and info.samples:
        s0 = info.samples[0]
        for key in ("slice", "host", "accelerator", "worker"):
            if key in s0.labels:
                snap["identity"][key] = s0.labels[key]
        for s in info.samples:
            chip = s.labels.get("chip", "?")
            snap["chips"].setdefault(chip, {})["coords"] = s.labels.get(
                "coords", ""
            )

    count = fams.get(_F_COUNT)
    if count is not None and count.samples:
        snap["device_count"] = int(count.samples[0].value)

    hosts = fams.get("accelerator_slice_host_count")
    if hosts is not None and hosts.samples:
        # Slice host count, lifted for consumers that must split
        # job-global feed rates across the job's hosts (the energy
        # plane's tokens/joule join).
        snap["identity"]["hosts"] = int(hosts.samples[0].value)

    cov = fams.get(_F_COVERAGE)
    if cov is not None and cov.samples:
        snap["coverage"] = cov.samples[0].value

    watch = fams.get(_F_WATCH)
    if watch is not None and watch.samples:
        # Push/poll transport state (grpc backend only — absent
        # elsewhere, and the renderers skip an absent key).
        snap["watch_streams"] = {
            s.labels.get("state", "?"): int(s.value) for s in watch.samples
        }

    deg = fams.get(_F_DEGRADED)
    if deg is not None and deg.samples:
        # Fault-tolerance plane (tpumon/resilience): degraded-serving
        # state + which families ride the last-good cache and how old
        # they are. Absent on pre-resilience exporters and in-process
        # snapshots (self-telemetry families live off the device page).
        degraded: dict = {"active": deg.samples[0].value > 0, "families": {}}
        stale = fams.get(_F_STALENESS)
        if stale is not None:
            degraded["families"] = {
                s.labels.get("family", "?"): s.value for s in stale.samples
            }
        breaker = fams.get(_F_BREAKER)
        if breaker is not None:
            open_queries = [
                s.labels.get("query", "?")
                for s in breaker.samples
                if s.value >= 2
            ]
            if open_queries:
                degraded["breakers_open"] = sorted(open_queries)
        snap["degraded"] = degraded

    guard_state = fams.get(_F_GUARD_STATE)
    if guard_state is not None and guard_state.samples:
        # Self-protection plane (tpumon/guard): memory watermark state
        # plus shed/cardinality-drop tallies. Absent on pre-guard
        # exporters and in-process snapshots.
        guard: dict = {"state": int(guard_state.samples[0].value)}
        shed = fams.get(_F_SHED)
        if shed is not None:
            by_key = {
                f"{s.labels.get('endpoint', '?')}:"
                f"{s.labels.get('reason', '?')}": s.value
                for s in shed.samples
                if s.value > 0 and not s.name.endswith("_created")
            }
            if by_key:
                guard["shed"] = by_key
                guard["shed_total"] = sum(by_key.values())
        dropped = fams.get(_F_CARDINALITY)
        if dropped is not None:
            collapsed = {
                s.labels.get("family", "?"): s.value
                for s in dropped.samples
                if s.value > 0 and not s.name.endswith("_created")
            }
            if collapsed:
                guard["cardinality_dropped"] = collapsed
        snap["guard"] = guard

    hc_avail = fams.get(_F_HOSTCORR_AVAILABLE)
    if hc_avail is not None and hc_avail.samples:
        # Host-correlation plane (tpumon/hostcorr): present iff the
        # plane is enabled on the exporter; 0 = host signals unreadable
        # (device-only verdicts).
        snap["hostcorr_available"] = hc_avail.samples[0].value > 0
    skew = fams.get(_F_STRAGGLER_SKEW)
    if skew is not None and skew.samples:
        snap["straggler"] = {
            "skew_pct": skew.samples[0].value, "active": False
        }
    verdict = fams.get(_F_STRAGGLER_VERDICT)
    if verdict is not None and verdict.samples:
        s0 = verdict.samples[0]
        snap.setdefault("straggler", {}).update(
            {
                "active": True,
                "cause": s0.labels.get("cause", "unknown"),
                "chip": s0.labels.get("chip", "?"),
            }
        )

    net = fams.get(_F_NET_RATE)
    if net is not None:
        # DCN-path bandwidth (mean percentile row): the load signal the
        # anomaly engine's CUSUM drift detector consumes.
        for s in net.samples:
            if s.labels.get("stat") == "mean":
                snap["network"] = {"delivery_rate_mbps": s.value}
                break

    pods = fams.get(_F_POD_INFO)
    if pods is not None and pods.samples:
        # chip -> [(namespace, pod)] — the energy plane's attribution
        # join (and any consumer wanting the chip→pod ownership map)
        # reads it straight off the snapshot instead of re-walking the
        # family. Chips without an attribution row stay absent.
        pod_map: dict = {}
        for s in pods.samples:
            chip = s.labels.get("chip", "")
            if not chip:
                continue  # unjoinable kubelet ID: visible in the family
            pod_map.setdefault(chip, []).append(
                (s.labels.get("namespace", ""), s.labels.get("pod", ""))
            )
        if pod_map:
            snap["pods"] = pod_map

    # Energy plane (tpumon/energy) — present only when scraping a live
    # exporter page (the in-process snapshot is built BEFORE the energy
    # pass, which is how the plane reads its device inputs from here).
    energy_watts = fams.get(_F_ENERGY_WATTS)
    if energy_watts is not None and energy_watts.samples:
        watts = 0.0
        sources = set()
        for s in energy_watts.samples:
            watts += s.value
            sources.add(s.labels.get("source", "?"))
        snap["energy"] = {
            "watts": watts,
            "source": "measured" if sources == {"measured"} else "modeled",
        }
    tpj = fams.get(_F_TOKENS_PER_JOULE)
    if tpj is not None and tpj.samples:
        snap.setdefault("energy", {})["tokens_per_joule"] = (
            tpj.samples[0].value
        )
    cost = fams.get(_F_STEP_COST)
    if cost is not None and cost.samples:
        snap.setdefault("energy", {})["step_cost_dollars"] = (
            cost.samples[0].value
        )

    per_chip = {
        _F_DUTY: "duty_pct",
        _F_HBM_USED: "hbm_used",
        _F_HBM_TOTAL: "hbm_total",
        _F_THROTTLE: "throttle",
        _F_POWER: "power_w",
    }
    for fam_name, field in per_chip.items():
        fam = fams.get(fam_name)
        if fam is None:
            continue
        for s in fam.samples:
            chip = s.labels.get("chip", "?")
            snap["chips"].setdefault(chip, {})[field] = s.value

    util = fams.get(_F_CORE_UTIL)
    if util is not None:
        for s in util.samples:
            snap["cores"][s.labels.get("core", "?")] = s.value

    queue = fams.get(_F_QUEUE)
    if queue is not None:
        snap["queues"] = {
            s.labels.get("core", "?"): s.value for s in queue.samples
        }

    ici = fams.get(_F_ICI)
    if ici is not None:
        worst = None
        healthy = total = 0
        links: dict[str, float] = {}
        for s in ici.samples:
            total += 1
            links[s.labels.get("link", "?")] = s.value
            if s.value == 0:
                healthy += 1
            if worst is None or s.value > worst[1]:
                # List, matching the fleet line parser's shape: both
                # snapshots must survive a JSON round-trip (the compact
                # binary exposition) structurally unchanged.
                worst = [s.labels.get("link", "?"), s.value]
        snap["ici"] = {
            "healthy": healthy,
            "total": total,
            "worst": worst if worst and worst[1] > 0 else None,
            "links": links,
        }
    return snap


def workload_snapshot_from_text(text: str) -> dict:
    """Parse a workload /metrics page (harness --metrics-port) into the
    summary smi renders: throughput, loss, MFU, mesh, collective counts."""
    fams = {f.name: f for f in text_string_to_metric_families(text)}
    snap: dict = {}

    def scalar(name, key, cast=float):
        fam = fams.get(name)
        if fam is not None and fam.samples:
            snap[key] = cast(fam.samples[0].value)

    scalar("workload_steps", "steps_total", int)
    scalar("workload_loss", "loss")
    scalar("workload_steps_per_second", "steps_per_sec")
    scalar("workload_tokens_per_second", "tokens_per_sec")
    scalar("workload_mfu_ratio", "mfu")
    mesh = fams.get("workload_mesh_info")
    if mesh is not None and mesh.samples:
        snap["mesh"] = {
            k: int(v)
            for k, v in mesh.samples[0].labels.items()
            if k in ("dp", "tp", "sp", "pp", "ep")
        }
    ops = fams.get("workload_collective_ops")
    if ops is not None:
        snap["collectives"] = {
            s.labels.get("op", "?"): int(s.value) for s in ops.samples
        }
    return snap


def render_workload(wl: dict, p) -> None:
    """Append the workload summary lines to a rendered snapshot."""
    if "error" in wl:
        p(f"workload: {wl.get('url', '?')} unreachable ({wl['error']})")
        return
    parts = []
    if "steps_total" in wl:
        parts.append(f"step {wl['steps_total']}")
    if "loss" in wl:
        parts.append(f"loss {wl['loss']:.4g}")
    if "steps_per_sec" in wl:
        parts.append(f"{wl['steps_per_sec']:.2f} steps/s")
    if "tokens_per_sec" in wl:
        parts.append(f"{wl['tokens_per_sec']:.0f} tok/s")
    if "mfu" in wl:
        parts.append(f"MFU {wl['mfu']:.1%}")
    if "mesh" in wl:
        axes = " ".join(
            f"{k}={v}" for k, v in wl["mesh"].items() if v and v > 1
        )
        parts.append(f"mesh[{axes}]" if axes else "mesh[single]")
    if parts:
        p("workload: " + "  ".join(parts))
    if wl.get("collectives"):
        top = sorted(
            wl["collectives"].items(), key=lambda kv: -kv[1]
        )[:4]
        p(
            "workload collectives: "
            + " ".join(f"{op}={n}" for op, n in top)
        )


def attach_trends(snap: dict, history_doc: dict, window: float) -> None:
    """Merge /history summaries into the snapshot (per-chip duty trend)."""
    series = history_doc.get("series", {})
    for chip, row in snap["chips"].items():
        key = f'{_F_DUTY}{{chip="{chip}"}}'
        summ = series.get(key)
        if summ:
            row["duty_trend"] = {
                "min": summ["min"],
                "avg": summ["avg"],
                "max": summ["max"],
                "count": summ["count"],
            }
    snap["trend_window"] = window


def attach_anomalies(snap: dict, doc: dict) -> None:
    """Fold a /anomalies document into the snapshot summary form."""
    events = doc.get("events") or []
    active = [e for e in events if e.get("clear_ts") is None]
    worst = None
    from tpumon import health as _health

    for e in active:
        if worst is None or _health.severity_value(
            e.get("severity", _health.WARN)
        ) > _health.severity_value(worst.get("severity", _health.WARN)):
            worst = e
    snap["anomalies"] = {
        "active": len(active),
        "total": doc.get("total", len(events)),
        "status": doc.get("status", "ok"),
        "worst": worst,
    }


def attach_slow_cycle(snap: dict, doc: dict) -> None:
    """Fold a /debug/traces document into the snapshot: the slowest
    retained poll cycle and its dominant stages (the trace plane's smi
    surface — "which stage ate the budget" at a glance)."""
    traces = doc.get("traces") or []
    if not traces:
        return
    worst = max(traces, key=lambda t: t.get("duration_seconds", 0.0))
    stages = sorted(
        worst.get("spans") or [],
        key=lambda s: -s.get("duration_seconds", 0.0),
    )
    snap["slow_cycle"] = {
        "id": worst.get("id"),
        "start_ts": worst.get("start_ts"),
        "duration_seconds": worst.get("duration_seconds", 0.0),
        "slow": bool(worst.get("slow")),
        "stages": [
            [s.get("name", "?"), s.get("duration_seconds", 0.0)]
            for s in stages[:3]
        ],
    }


def snapshot_from_url(url: str, timeout: float, window: float) -> dict:
    text = _fetch(url.rstrip("/") + "/metrics", timeout)
    snap = snapshot_from_text(text)
    try:
        doc = json.loads(
            _fetch(url.rstrip("/") + f"/history?window={window}", timeout)
        )
        attach_trends(snap, doc, window)
    except (urllib.error.URLError, urllib.error.HTTPError, ValueError):
        pass  # older exporter or history disabled — table still renders
    try:
        attach_anomalies(
            snap, json.loads(_fetch(url.rstrip("/") + "/anomalies", timeout))
        )
    except (urllib.error.URLError, urllib.error.HTTPError, ValueError):
        pass  # older exporter or anomaly engine disabled
    try:
        attach_slow_cycle(
            snap,
            json.loads(_fetch(url.rstrip("/") + "/debug/traces", timeout)),
        )
    except (urllib.error.URLError, urllib.error.HTTPError, ValueError):
        pass  # older exporter or trace plane disabled
    return snap


def fetch_fleet_snapshots(
    urls: list[str],
    timeout: float,
    window: float,
    fetch_errors: tuple = FETCH_ERRORS,
    max_workers: int = 16,
) -> list[dict]:
    """Bounded-concurrency snapshot fetch across exporter URLs.

    One refresh costs one timeout, not one per down host (a 16-host view
    with dead nodes must not stall N×), and the worker bound keeps a
    500-URL invocation from spawning 500 sockets at once. Unreachable
    hosts come back as ``{"url", "error"}`` rows — a down node must be
    visible, not silently missing. This is the same merge feed the fleet
    aggregator (tpumon/fleet) runs as a service; the CLI path remains
    for air-gapped and ad-hoc use, ``--aggregator`` for fleets.
    """
    from concurrent.futures import ThreadPoolExecutor

    def fetch(url: str) -> dict:
        try:
            return snapshot_from_url(url, timeout, window)
        except fetch_errors as exc:
            return {"url": url, "error": str(exc)}

    with ThreadPoolExecutor(
        max_workers=max(1, min(len(urls), max_workers))
    ) as pool:
        return list(pool.map(fetch, urls))


def aggregator_snapshot(url: str, timeout: float) -> dict:
    """One /fleet document from a running fleet aggregator (tpumon/fleet).

    Transient connection errors (an aggregator pod rolling, one dropped
    keep-alive) retry on a bounded jittered backoff instead of blanking
    a ``--watch`` frame or killing a one-shot invocation: three tries
    over at most ~2 s, then the error propagates to the caller's
    ordinary handling (the watch loop renders it and keeps watching).
    """
    from tpumon.resilience import RetryPolicy, retry_call

    policy = RetryPolicy(
        attempts=3, base_s=0.2, max_s=1.0, deadline_s=max(2.0, timeout)
    )
    body = retry_call(
        lambda: _fetch(url.rstrip("/") + "/fleet", timeout),
        policy,
        retryable=FETCH_ERRORS,
    )
    doc = json.loads(body)
    return {"aggregator": doc, "aggregator_url": url, "ts": time.time()}


def ledger_snapshot(
    url: str, timeout: float, job: str | None = None
) -> dict:
    """The ``--ledger`` view's data: the aggregator's goodput split and
    the fleet tokens/J trend from its ``GET /ledger`` range API
    (tpumon/ledger). Same bounded retry discipline as ``--aggregator``:
    three tries over at most ~2 s per fetch, then the error propagates
    to ordinary handling."""
    from tpumon.resilience import RetryPolicy, retry_call

    policy = RetryPolicy(
        attempts=3, base_s=0.2, max_s=1.0, deadline_s=max(2.0, timeout)
    )
    base = url.rstrip("/")

    def fetch(path: str) -> dict:
        return json.loads(retry_call(
            lambda: _fetch(base + path, timeout),
            policy,
            retryable=FETCH_ERRORS,
        ))

    goodput = fetch("/ledger?view=goodput")
    now = time.time()
    trend = fetch(
        "/ledger?family=tpu_fleet_tokens_per_joule&scope=fleet"
        f"&start={now - 3600.0:.3f}&end={now:.3f}&step=10"
    )
    # Per-pool efficiency breakdown via SERVER-SIDE aggregation: the
    # aggregator folds its slice series into one series per pool
    # inside the read path (?agg=mean&by=pool), so this CLI never
    # ships — or client-aggregates — raw per-slice series. A pre-agg
    # aggregator IGNORES the unknown params and answers 200 with the
    # raw per-slice range — detected by the missing "agg" echo in the
    # response, and degraded to no breakdown rather than rendering raw
    # slices mislabeled as pool means. Transport errors degrade too.
    try:
        by_pool = fetch(
            "/ledger?family=tpu_fleet_tokens_per_joule&scope=slice"
            "&agg=mean&by=pool"
            f"&start={now - 3600.0:.3f}&end={now:.3f}&step=60"
        )
    except FETCH_ERRORS:
        by_pool = None
    if by_pool is not None and by_pool.get("agg") != "mean":
        by_pool = None  # old aggregator: raw series, not a fold
    return {
        "ledger": {"goodput": goodput, "tokens_per_joule": trend,
                   "tokens_per_joule_by_pool": by_pool, "job": job},
        "aggregator_url": url,
        "ts": now,
    }


def capacity_snapshot(
    url: str, timeout: float, whatif: float | None = None
) -> dict:
    """The ``--capacity`` view's data: per-pool saturation forecasts,
    the top-waste ranking, and fleet waste percentiles from the
    aggregator's ``GET /ledger`` read side (tpumon/ledger/analytics.py
    + forecast.py). Same bounded retry discipline as ``--ledger``.

    An OLD aggregator (pre-forecast read side) answers ``view=forecast``
    with a 400 (unknown view) or a doc missing the ``pools`` echo —
    both degrade to an explicit "no capacity read side" marker rather
    than rendering garbage or crashing the CLI.
    """
    from tpumon.resilience import RetryPolicy, retry_call

    policy = RetryPolicy(
        attempts=3, base_s=0.2, max_s=1.0, deadline_s=max(2.0, timeout)
    )
    base = url.rstrip("/")

    def fetch(path: str) -> dict:
        return json.loads(retry_call(
            lambda: _fetch(base + path, timeout),
            policy,
            retryable=FETCH_ERRORS,
        ))

    try:
        forecast = fetch("/ledger?view=forecast")
    except FETCH_ERRORS:
        forecast = None
    if forecast is not None and "pools" not in forecast:
        forecast = None  # old aggregator: no forecast read side
    waste = None
    pct = None
    if forecast is not None:
        suffix = ""
        if whatif is not None:
            suffix = f"&whatif=dollars_per_kwh:{whatif:g}"
        try:
            waste = fetch(
                "/ledger?view=waste&group_by=job&rank=topk:10" + suffix
            )
            pct = fetch("/ledger?view=percentiles")
        except FETCH_ERRORS:
            pass
    return {
        "capacity": {"forecast": forecast, "waste": waste,
                     "percentiles": pct, "whatif": whatif},
        "aggregator_url": url,
        "ts": time.time(),
    }


def render_capacity(snap: dict, out=None) -> None:
    """The ``--capacity`` view: per-pool days-to-saturation (with the
    confidence band and the leading signal), the top-waste job ranking
    with its conservation line, and the per-class waste percentiles.
    Pools below the history gate print "insufficient history" — the
    server never fabricates a date, and neither does this renderer."""
    out = out if out is not None else sys.stdout
    doc = snap["capacity"]

    def p(line: str = "") -> None:
        print(line, file=out)

    forecast = doc.get("forecast")
    p(f"CAPACITY @ {snap.get('aggregator_url', '?')}")
    if forecast is None:
        p("  aggregator has no capacity read side "
          "(pre-forecast server, or /ledger unreachable) — "
          "upgrade the aggregator or use --ledger")
        return
    pools = forecast.get("pools") or {}
    if not pools:
        p("  no pool series yet (young ledger)")
    for pool in sorted(pools):
        verdict = pools[pool] or {}
        status = verdict.get("status", "?")
        if status == "ok":
            days = verdict.get("days_to_saturation")
            lo = verdict.get("days_lo")
            hi = verdict.get("days_hi")
            band = ""
            if lo is not None:
                band = (f" (95% band {lo:.1f}.."
                        + (f"{hi:.1f}" if hi is not None else "inf")
                        + " d)")
            p(f"  {pool}: saturates in {days:.1f} days{band}"
              f" — leading signal {verdict.get('leading_signal', '?')}")
        elif status == "insufficient_history":
            p(f"  {pool}: insufficient history "
              f"(gate {forecast.get('min_history_s', 0):.0f}s — "
              "no date until the ledger has seen enough)")
        else:
            p(f"  {pool}: {status} (no adverse trend)")
    waste = doc.get("waste")
    if waste:
        rows = waste.get("rows") or []
        whatif = doc.get("whatif")
        p(f"top waste (contended+idle chip-hours, "
          f"group_by={waste.get('group_by', 'job')}):")
        for row in rows:
            line = (
                f"  {row.get('key', '?')}: "
                f"{row.get('wasted_chip_hours', 0.0):.2f} chip-h wasted "
                f"({row.get('waste_fraction', 0.0):.1%} of its time)"
            )
            dollars = row.get("whatif_dollars")
            if dollars is not None:
                line += f", ~${dollars:.2f} @ ${whatif:g}/kWh"
            p(line)
        cons = waste.get("conservation") or {}
        if cons:
            p(f"  conservation: {cons.get('sum_groups_chip_seconds', 0.0):.0f}"
              f" == {cons.get('total_chip_seconds', 0.0):.0f} chip-s"
              " (groups vs pinned total)")
    pct = doc.get("percentiles")
    if pct and pct.get("classes"):
        p("waste percentiles by workload class:")
        for wclass in sorted(pct["classes"]):
            row = pct["classes"][wclass]
            p(f"  {wclass}: p50 {row.get('p50', 0.0):.1%} / "
              f"p90 {row.get('p90', 0.0):.1%} / "
              f"p99 {row.get('p99', 0.0):.1%} "
              f"({row.get('jobs', 0)} jobs)")


def render_ledger(snap: dict, out=None) -> None:
    """The ``--ledger`` view: per-job goodput splits (chip-hours by
    bucket, unaccounted called out — see the OPERATIONS.md goodput
    triage runbook for reading unaccounted vs idle) and the fleet
    tokens/J trend over the last hour."""
    out = out if out is not None else sys.stdout
    doc = snap["ledger"]

    def p(line: str = "") -> None:
        print(line, file=out)

    goodput = doc.get("goodput", {})
    jobs = goodput.get("jobs", [])
    job_filter = doc.get("job")
    if job_filter:
        jobs = [j for j in jobs if j.get("slice") == job_filter]
    p(f"GOODPUT ledger @ {snap.get('aggregator_url', '?')}"
      + (f" [job {job_filter}]" if job_filter else ""))
    if not jobs:
        p("  no accounted jobs"
          + (f" matching slice {job_filter!r}" if job_filter else "")
          + " yet")
    for row in jobs:
        total = row.get("chip_seconds") or 0.0
        buckets = row.get("buckets", {})
        hours = total / 3600.0
        parts = []
        for bucket in ("productive", "checkpoint", "restore",
                       "preempted", "idle", "contended", "unaccounted"):
            value = buckets.get(bucket, 0.0)
            if total > 0 and value > 0:
                label = bucket if bucket != "unaccounted" else "UNACCOUNTED"
                parts.append(f"{label} {value / total:.1%}")
        ratio = row.get("goodput_ratio")
        energy = ""
        joules = row.get("energy_joules")
        if joules is not None:
            energy = (
                f", energy {joules / 3.6e6:.2f} kWh"
                f" ({row.get('energy_source', 'modeled')})"
            )
            dollars = row.get("energy_dollars")
            if dollars is not None:
                energy += f" ${dollars:.2f}"
        p(
            f"  {row.get('slice', '?')} [{row.get('pool', '?')}]: "
            f"{hours:.2f} chip-h"
            + (f", goodput {ratio:.1%}" if ratio is not None else "")
            + energy
            + (" — " + ", ".join(parts) if parts else "")
        )
    gap = goodput.get("gap_seconds")
    if gap:
        p(f"  aggregator-blind gap ledgered: {gap:.0f}s (unaccounted)")
    trend = doc.get("tokens_per_joule", {})
    series = trend.get("series") or []
    points = series[0].get("points", []) if series else []
    if points:
        values = [v for _ts, v in points]
        p(
            f"tokens/J (fleet, last 1h @ {trend.get('tier', '?')} tier): "
            f"{values[0]:.1f} -> {values[-1]:.1f} "
            f"(min {min(values):.1f} / max {max(values):.1f}, "
            f"n={len(values)})"
        )
    else:
        p("tokens/J: no samples in the last hour "
          "(no energy-reporting hosts, or a young ledger)")
    by_pool = doc.get("tokens_per_joule_by_pool") or {}
    for row in by_pool.get("series", []):
        points = row.get("points") or []
        if not points:
            continue
        values = [v for _ts, v in points]
        p(
            f"  pool {row.get('pool', '?')}: "
            f"{values[0]:.1f} -> {values[-1]:.1f} tokens/J "
            f"(server-side {by_pool.get('agg', 'mean')} over slices, "
            f"n={len(values)})"
        )


def render_aggregator(snap: dict, out=None) -> None:
    """The ``--aggregator`` view: the aggregator's per-node snapshots
    through the same fleet table, then the pre-aggregated rollup lines
    the tier exists to serve."""
    out = out if out is not None else sys.stdout
    doc = snap["aggregator"]

    def p(line: str = "") -> None:
        print(line, file=out)

    snaps = []
    for node in doc.get("nodes", ()):
        node_snap = node.get("snap")
        if node.get("state") == "dark" or not node_snap:
            snaps.append(
                {
                    "url": node.get("url", node.get("target", "?")),
                    "error": node.get("error") or "dark (no recent data)",
                }
            )
        else:
            snaps.append(node_snap)
    render_fleet(snaps, out)

    shard = doc.get("shard", {})
    fleet = doc.get("fleet", {})
    hosts = fleet.get("hosts", {})
    visibility = fleet.get("visibility")
    partial = (
        f", visibility {visibility:.0%} PARTIAL"
        if visibility is not None and visibility < 1.0
        else ""
    )
    p(
        f"aggregator {snap.get('aggregator_url', '?')} "
        f"[shard {shard.get('index', 0)}/{shard.get('count', 1)}, "
        f"{shard.get('targets', len(snaps))} targets]: "
        f"{hosts.get('up', 0)} up / {hosts.get('stale', 0)} stale / "
        f"{hosts.get('dark', 0)} dark, {fleet.get('chips', 0)} chips"
        + partial
    )
    glob = doc.get("global")
    if glob:
        ghosts = glob.get("hosts", {})
        gvis = glob.get("visibility")
        p(
            f"  global [{glob.get('shards_alive', '?')}/"
            f"{glob.get('shards', '?')} shards alive]: "
            f"{ghosts.get('up', 0)} up / {ghosts.get('stale', 0)} stale / "
            f"{ghosts.get('dark', 0)} dark, {glob.get('chips', 0)} chips"
            + (
                f", visibility {gvis:.0%} PARTIAL"
                if gvis is not None and gvis < 1.0
                else ""
            )
            + (
                f", {glob['contested']} CONTESTED"
                if glob.get("contested")
                else ""
            )
        )
    actuate = doc.get("actuate")
    if actuate:
        flags = []
        if actuate.get("withheld_slices"):
            flags.append(f"{actuate['withheld_slices']} WITHHELD")
        if actuate.get("frozen_slices"):
            flags.append(f"{actuate['frozen_slices']} hints frozen")
        if actuate.get("epoch_conflicts_total"):
            flags.append(
                f"{actuate['epoch_conflicts_total']} epoch conflicts"
            )
        if actuate.get("contested"):
            flags.append("contested")
        p(
            f"  actuate [trust floor "
            f"{actuate.get('min_trust', 0.0):.2f}]: "
            f"{actuate.get('scored_slices', 0)} scored / "
            f"{actuate.get('slices', 0)} slices"
            + (", " + ", ".join(flags) if flags else ", all trusted")
        )
    for row in doc.get("slices", ()):
        parts = [f"{row.get('chips', 0)} chips"]
        duty = row.get("duty")
        if duty:
            parts.append(
                f"duty {duty['mean']:.1f}% "
                f"({duty['min']:.1f}-{duty['max']:.1f})"
            )
        if "hbm_headroom_ratio" in row:
            parts.append(f"HBM headroom {row['hbm_headroom_ratio']:.0%}")
        ici = row.get("ici")
        if ici:
            parts.append(f"ICI {ici['score']:.2f}")
        if "mfu" in row:
            parts.append(f"MFU {row['mfu']:.1%}")
        if row.get("degraded_hosts"):
            parts.append(f"{row['degraded_hosts']} degraded")
        flag = "  STALE" if row.get("stale") else ""
        p(
            f"  slice {row.get('slice', '?')} [{row.get('pool', '?')}]: "
            + ", ".join(parts) + flag
        )


def snapshot_from_backend(cfg, backend=None) -> dict:
    """Standalone mode: poll a backend once and snapshot the families.

    ``backend=None`` creates one from cfg and closes it afterwards; pass a
    live backend to reuse it across --watch ticks (no per-tick device
    re-initialization).
    """
    from tpumon.backends import create_backend
    from tpumon.exporter.collector import build_families

    owned = backend is None
    if owned:
        backend = create_backend(cfg)
    try:
        families, stats = build_families(backend, cfg)
        # build_families already parsed this cycle's snapshot (with
        # coverage set) for the health families — reuse it.
        return stats.snapshot or snapshot_from_families(families)
    finally:
        if owned:
            backend.close()


def _chip_cells(chip: str, row: dict, has_trend: bool) -> str:
    """The per-chip table cells shared by single-host and fleet views."""
    duty = row.get("duty_pct")
    duty_s = f"{duty:5.1f}" if duty is not None else "    -"
    used, total = row.get("hbm_used"), row.get("hbm_total")
    hbm_s = (
        f"{_human_bytes(used)}/{_human_bytes(total)}"
        if used is not None and total is not None
        else "-"
    )
    thr = row.get("throttle")
    thr_s = f"{thr:3.0f}" if thr is not None else "  -"
    line = (
        f" {chip:>4} | {row.get('coords', ''):<9} | {duty_s}  |"
        f" {hbm_s:<18} | {thr_s} |"
    )
    if has_trend:
        t = row.get("duty_trend")
        trend_s = (
            f"{t['min']:5.1f}/{t['avg']:5.1f}/{t['max']:5.1f}" if t else "-"
        )
        line += f" {trend_s:<22} |"
    return line


def render_fleet(snaps: list[dict], out=None) -> None:
    """Merged view over several exporters (one per DaemonSet host).

    Snapshots carrying an ``error`` key render as unreachable rows —
    a down node must be visible, not silently missing.
    """
    out = out if out is not None else sys.stdout

    def p(line: str = "") -> None:
        print(line, file=out)

    ok = [s for s in snaps if "error" not in s]
    slices = sorted(
        {s["identity"].get("slice", "?") for s in ok if s.get("identity")}
    )
    chips = sum(len(s.get("chips", {})) for s in ok)
    p(
        f"tpumon smi — fleet: {len(ok)}/{len(snaps)} hosts up, "
        f"{chips} chips | slice(s): {', '.join(slices) or '?'}"
    )
    p(time.strftime("%a %b %d %H:%M:%S %Y"))

    has_trend = any(
        "duty_trend" in c for s in ok for c in s.get("chips", {}).values()
    )
    window = max((s.get("trend_window", 60) for s in ok), default=60)
    cols = "| Host            | Chip | Coords    | Duty%  | HBM used/total     | Thr |"
    if has_trend:
        cols += f" Duty min/avg/max ({window:.0f}s) |"
    sep = "+" + "-" * (len(cols) - 2) + "+"
    p(sep)
    p(cols)
    p(sep)

    from tpumon import health as _health

    worst = _health.OK
    healthy = total_links = 0
    worst_link = None
    for snap in sorted(
        snaps, key=lambda s: s.get("identity", {}).get("host", s.get("url", ""))
    ):
        host = snap.get("identity", {}).get("host") or snap.get("url", "?")
        if "error" in snap:
            p(f"| {host:<15} | UNREACHABLE: {snap['error']}")
            worst = _health.CRIT
            continue
        if snap.get("device_count") == 0:
            # A CPU-only/stub node is up but deviceless — it must be
            # distinguishable from a host the operator forgot to pass.
            p(f"| {host:<15} | (stub: no accelerator devices)")
            continue
        for chip in sorted(snap.get("chips", {}), key=lambda c: (len(c), c)):
            p(f"| {host:<15} |" + _chip_cells(chip, snap["chips"][chip], has_trend))
        ici = snap.get("ici") or {}
        healthy += ici.get("healthy", 0)
        total_links += ici.get("total", 0)
        w = ici.get("worst")
        if w and (worst_link is None or w[1] > worst_link[1]):
            worst_link = (f"{host}:{w[0]}", w[1])
        findings = _health.evaluate(snap)
        status = _health.overall(findings)
        if _health.severity_value(status) > _health.severity_value(worst):
            worst = status
    p(sep)
    if total_links:
        line = f"ici links: {healthy}/{total_links} healthy across fleet"
        if worst_link:
            line += f" (worst: {worst_link[0]} score={worst_link[1]:.0f})"
        p(line)
    p(f"fleet health: {worst.upper()}")


def render(snap: dict, out=None) -> None:
    out = out if out is not None else sys.stdout

    def p(line: str = "") -> None:
        print(line, file=out)

    ident = snap["identity"]
    head = " ".join(f"{k}={v}" for k, v in ident.items())
    cov = snap.get("coverage")
    cov_s = f" coverage={cov * 100:.0f}%" if cov is not None else ""
    p(f"tpumon smi — {head or 'no identity (runtime detached?)'}{cov_s}")
    ts = snap.get("ts", time.time())
    p(time.strftime("%a %b %d %H:%M:%S %Y", time.localtime(ts)))

    if snap.get("device_count") == 0:
        p("no accelerator devices on this node (stub)")
        return

    has_trend = any("duty_trend" in c for c in snap["chips"].values())
    cols = "| Chip | Coords    | Duty%  | HBM used/total     | Thr |"
    if has_trend:
        cols += f" Duty min/avg/max ({snap.get('trend_window', 60):.0f}s) |"
    sep = "+" + "-" * (len(cols) - 2) + "+"
    p(sep)
    p(cols)
    p(sep)
    for chip in sorted(snap["chips"], key=lambda c: (len(c), c)):
        p("|" + _chip_cells(chip, snap["chips"][chip], has_trend))
    p(sep)

    if snap["cores"]:
        parts = [
            f"{core}={snap['cores'][core]:.0f}%"
            for core in sorted(snap["cores"], key=lambda c: (len(c), c))
        ]
        p("core util: " + " ".join(parts))
    ici = snap["ici"]
    if ici["total"]:
        line = f"ici links: {ici['healthy']}/{ici['total']} healthy"
        if ici["worst"]:
            line += f" (worst: {ici['worst'][0]} score={ici['worst'][1]:.0f})"
        p(line)
    degraded = snap.get("degraded")
    if degraded and degraded.get("active"):
        stale = degraded.get("families") or {}
        parts = []
        if stale:
            parts.append(f"serving last-good data for {len(stale)} families")
            oldest = max(stale.items(), key=lambda kv: kv[1])
            parts.append(f"oldest {oldest[0]} at {oldest[1]:.0f}s")
        if degraded.get("breakers_open"):
            parts.append(
                f"{len(degraded['breakers_open'])} breakers open "
                f"({', '.join(degraded['breakers_open'][:3])}"
                + ("..." if len(degraded["breakers_open"]) > 3 else "")
                + ")"
            )
        if not parts:
            # Degraded without stale families or open breakers (e.g. a
            # recovered enumeration outage): still worth the line.
            parts.append("serving on degraded data paths")
        p("DEGRADED: " + "; ".join(parts))

    guard = snap.get("guard")
    if guard and (guard.get("state", 0) > 0 or guard.get("shed_total")
                  or guard.get("cardinality_dropped")):
        # Self-protection plane (tpumon/guard): only printed while the
        # guard has actually intervened — a quiet exporter stays quiet.
        parts = []
        state = guard.get("state", 0)
        if state >= 2:
            parts.append("HARD memory watermark (metrics-only serving)")
        elif state == 1:
            parts.append("soft memory watermark (rings shrunk)")
        if guard.get("shed_total"):
            worst = max(guard["shed"].items(), key=lambda kv: kv[1])
            parts.append(
                f"{guard['shed_total']:.0f} requests shed "
                f"(most: {worst[0]})"
            )
        if guard.get("cardinality_dropped"):
            fams_hit = sorted(guard["cardinality_dropped"])
            parts.append(
                f"cardinality budget collapsing {len(fams_hit)} "
                f"families ({', '.join(fams_hit[:2])}"
                + ("..." if len(fams_hit) > 2 else "") + ")"
            )
        p("GUARD: " + "; ".join(parts))

    energy = snap.get("energy")
    if energy and energy.get("watts") is not None:
        # Energy/cost plane (tpumon/energy): node power with its
        # provenance, plus the efficiency joins when a workload feed
        # reports throughput.
        parts = [f"{energy['watts']:.0f} W ({energy.get('source', '?')})"]
        if energy.get("tokens_per_joule") is not None:
            parts.append(f"{energy['tokens_per_joule']:.4g} tok/J")
        if energy.get("step_cost_dollars") is not None:
            parts.append(f"${energy['step_cost_dollars']:.4g}/step")
        p("ENERGY: " + "  ".join(parts))

    straggler = snap.get("straggler")
    if straggler and straggler.get("active"):
        # Host-correlation verdict (tpumon/hostcorr): the laggard chip
        # plus the cause the cross-signal join attributed.
        p(
            f"STRAGGLER: chip {straggler.get('chip', '?')} lagging "
            f"{straggler.get('skew_pct', 0):.0f} duty points below the "
            f"slice median — cause: {straggler.get('cause', 'unknown')} "
            "(GET /hostcorr for the time-aligned host signals)"
        )
    if snap.get("hostcorr_available") is False:
        p(
            "hostcorr: host signals unavailable (no PSI/schedstat) — "
            "straggler verdicts are device-only"
        )

    streams = snap.get("watch_streams")
    if streams:
        p(
            "monitoring transport: "
            + ", ".join(
                f"{n} {state}" for state, n in sorted(streams.items())
            )
            + " (non-streaming metrics ride the unary poll)"
        )

    from tpumon import health as _health

    findings = _health.evaluate(snap)
    status = _health.overall(findings)
    if findings:
        top = findings[0]
        extra = f" (+{len(findings) - 1} more)" if len(findings) > 1 else ""
        p(f"health: {status.upper()} — {top.message}{extra}")
    else:
        p("health: OK")

    anoms = snap.get("anomalies")
    if anoms:
        # Streaming-detector verdict (tpumon.anomaly), same severity
        # vocabulary as the health line above.
        if anoms["active"] and anoms.get("worst"):
            w = anoms["worst"]
            more = (
                f" (+{anoms['active'] - 1} more)" if anoms["active"] > 1 else ""
            )
            p(
                f"anomalies: {anoms['status'].upper()} — "
                f"[{w['detector']}] {w['message']}{more}"
            )
        else:
            p(f"anomalies: none active ({anoms['total']} retained)")

    slow = snap.get("slow_cycle")
    if slow:
        # Trace-plane summary (/debug/traces): the slowest retained poll
        # cycle, stage-attributed.
        stages = "  ".join(
            f"{name} {dur * 1e3:.1f}ms" for name, dur in slow["stages"]
        )
        flag = " SLOW" if slow.get("slow") else ""
        p(
            f"slowest recent cycle{flag}: "
            f"{slow['duration_seconds'] * 1e3:.1f} ms "
            f"[trace {slow['id']}] — {stages}"
        )

    if "workload" in snap:
        render_workload(snap["workload"], p)


def main(argv: list[str] | None = None, out=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpumon smi", description="per-chip accelerator status table"
    )
    parser.add_argument(
        "--url",
        action="append",
        help="running exporter base URL; repeat for a merged fleet view "
        "across hosts. Without --url or --backend, http://localhost:9400 "
        "is probed and an in-process backend is the fallback",
    )
    parser.add_argument(
        "--aggregator",
        metavar="URL",
        help="a running fleet aggregator's base URL (tpumon/fleet): "
        "render the fleet view from its pre-aggregated /fleet API "
        "instead of fanning out to every exporter from this CLI",
    )
    parser.add_argument(
        "--ledger",
        action="store_true",
        help="with --aggregator: render per-job goodput splits and the "
        "fleet tokens/J trend from the aggregator's /ledger API "
        "(tpumon/ledger) instead of the node table",
    )
    parser.add_argument(
        "--job",
        metavar="SLICE",
        help="filter the --ledger goodput view to one job's slice",
    )
    parser.add_argument(
        "--capacity",
        action="store_true",
        help="with --aggregator: render per-pool saturation forecasts, "
        "the top-waste ranking, and per-class waste percentiles from "
        "the aggregator's /ledger read side (view=forecast/waste/"
        "percentiles) instead of the node table",
    )
    parser.add_argument(
        "--whatif",
        type=float,
        metavar="DOLLARS_PER_KWH",
        help="with --capacity: re-price the waste ranking's stored "
        "joules at this electricity price (?whatif=dollars_per_kwh:V)",
    )
    parser.add_argument(
        "--watch", type=float, metavar="SEC", help="refresh every SEC seconds"
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument(
        "--workload",
        metavar="URL",
        help="a running workload's metrics URL (harness --metrics-port): "
        "appends steps/s, loss, MFU, and collective counts to the view — "
        "the inside-the-process complement of the chip table",
    )
    parser.add_argument(
        "--window", type=float, default=60.0, help="trend window seconds"
    )
    parser.add_argument("--timeout", type=float, default=5.0)
    from tpumon.config import Config

    Config.add_args(parser)
    args = parser.parse_args(argv)
    if args.ledger and not args.aggregator:
        parser.error("--ledger requires --aggregator URL (the ledger "
                     "lives in the fleet aggregator)")
    if args.capacity and not args.aggregator:
        parser.error("--capacity requires --aggregator URL (the "
                     "forecast read side lives in the fleet aggregator)")
    out = out if out is not None else sys.stdout

    # The data source is chosen once and sticks: under --watch a transient
    # exporter outage must not silently switch a URL view to an in-process
    # device backend, and a pinned backend is created ONCE and reused
    # across ticks (per-tick create/close would re-init the device runtime
    # every second — the touching this CLI promises to avoid).
    source: dict = {"mode": None, "backend": None, "cfg": None}

    fetch_errors = FETCH_ERRORS  # module-level set, documented there

    def pinned_backend():
        if source["backend"] is None:
            from tpumon.backends import create_backend

            source["cfg"] = Config.from_env().with_args(args)
            source["backend"] = create_backend(source["cfg"])
        return source["backend"]

    def fleet_snapshot(urls: list[str]) -> dict:
        # Bounded-concurrency fan-out (module-level helper, shared
        # idiom with the fleet tier's ingest).
        snaps = fetch_fleet_snapshots(
            urls, args.timeout, args.window, fetch_errors
        )
        return {"fleet": snaps, "ts": time.time()}

    def fetch_workload() -> dict:
        # Best-effort side fetch: a dead workload process must not take
        # the chip table down with it.
        try:
            return workload_snapshot_from_text(
                _fetch(args.workload.rstrip("/") + "/metrics", args.timeout)
            )
        except fetch_errors as exc:
            return {"url": args.workload, "error": str(exc)}

    def one_snapshot() -> dict:
        # The workload fetch rides a future so a dead endpoint costs the
        # refresh ONE timeout total, overlapped with the chip fetch — the
        # same invariant (and the same concurrent.futures machinery) the
        # fleet pool keeps for down hosts. A future, not a bare thread:
        # an exception outside fetch_workload's curated catches re-raises
        # here with its real traceback instead of dying in the thread.
        from concurrent.futures import ThreadPoolExecutor
        from concurrent.futures import TimeoutError as FutureTimeout

        wl_future = None
        pool = None
        if args.workload:
            pool = ThreadPoolExecutor(max_workers=1)
            wl_future = pool.submit(fetch_workload)
        try:
            snap = _chip_snapshot()
            if wl_future is not None:
                # fetch_workload bounds its own socket I/O (args.timeout);
                # the result bound guards the thread itself wedging. Its
                # TimeoutError is NOT in fetch_errors (py3.10: not an
                # OSError), so degrade here — a wedged workload fetch
                # must not take the chip table (or a --watch loop) down.
                try:
                    snap["workload"] = wl_future.result(
                        timeout=args.timeout + 30.0
                    )
                except FutureTimeout:
                    snap["workload"] = {
                        "url": args.workload,
                        "error": "workload fetch timed out",
                    }
        finally:
            if pool is not None:
                pool.shutdown(wait=False)
        snap["ts"] = time.time()
        return snap

    def _chip_snapshot() -> dict:
        if args.capacity:
            # Capacity-planning view: forecasts + waste ranking off the
            # ledger's read side; degrades explicitly on old servers.
            return capacity_snapshot(
                args.aggregator, args.timeout, whatif=args.whatif
            )
        if args.ledger:
            # Efficiency-ledger view: the aggregator's /ledger API
            # (goodput splits + tokens/J trend), not the node table.
            return ledger_snapshot(
                args.aggregator, args.timeout, job=args.job
            )
        if args.aggregator:
            # The fleet tier already fanned in and rolled up; one fetch
            # renders the whole fleet whatever its size.
            return aggregator_snapshot(args.aggregator, args.timeout)
        if args.url and len(args.url) > 1:
            return fleet_snapshot(args.url)
        if args.url:
            snap = snapshot_from_url(args.url[0], args.timeout, args.window)
        elif args.backend:
            # An explicit --backend always means in-process, even when a
            # local exporter happens to be listening.
            backend = pinned_backend()
            snap = snapshot_from_backend(source["cfg"], backend)
        elif source["mode"] == "url":
            snap = snapshot_from_url(
                "http://localhost:9400", args.timeout, args.window
            )
        elif source["mode"] == "backend":
            backend = pinned_backend()
            snap = snapshot_from_backend(source["cfg"], backend)
        else:
            # First snapshot: probe the conventional local exporter, fall
            # back to in-process, and remember the choice.
            try:
                snap = snapshot_from_url(
                    "http://localhost:9400", args.timeout, args.window
                )
                source["mode"] = "url"
            except fetch_errors:
                backend = pinned_backend()
                snap = snapshot_from_backend(source["cfg"], backend)
                source["mode"] = "backend"
        return snap

    def emit(snap: dict) -> None:
        if args.json:
            print(json.dumps(snap, sort_keys=True), file=out)
        elif "capacity" in snap:
            render_capacity(snap, out)
        elif "ledger" in snap:
            render_ledger(snap, out)
        elif "aggregator" in snap:
            render_aggregator(snap, out)
            if "workload" in snap:
                render_workload(snap["workload"], lambda l="": print(l, file=out))
        elif "fleet" in snap:
            render_fleet(snap["fleet"], out)
            if "workload" in snap:
                render_workload(snap["workload"], lambda l="": print(l, file=out))
        else:
            render(snap, out)

    try:
        if args.watch:
            while True:
                # A watch survives transient errors (exporter pod restart,
                # one timed-out scrape) — render the error, keep polling.
                try:
                    snap = one_snapshot()
                except fetch_errors as exc:
                    if not args.json and out is sys.stdout:
                        print("\x1b[2J\x1b[H", end="", file=out)
                    print(f"tpumon smi: fetch failed: {exc}", file=sys.stderr)
                    time.sleep(args.watch)
                    continue
                if not args.json and out is sys.stdout:
                    print("\x1b[2J\x1b[H", end="", file=out)
                emit(snap)
                time.sleep(args.watch)
        else:
            emit(one_snapshot())
    except KeyboardInterrupt:
        return 0
    except fetch_errors as exc:
        print(f"tpumon smi: cannot reach exporter: {exc}", file=sys.stderr)
        return 1
    finally:
        if source["backend"] is not None:
            source["backend"].close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
