from tpumon.attribution.client import PodAttribution, PodResourcesClient

__all__ = ["PodAttribution", "PodResourcesClient"]
