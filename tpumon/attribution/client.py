"""Chip→pod attribution via the kubelet pod-resources API
(SURVEY.md §7 hard part (d)).

The reference genre's DCGM path attributes GPU metrics to processes via
driver accounting; there is no TPU equivalent, so tpumon maps **device IDs
to pods** the Kubernetes-native way: the kubelet's pod-resources gRPC
service (`unix:///var/lib/kubelet/pod-resources/kubelet.sock`, stable v1
API) lists which ``google.com/tpu`` device IDs each container was
allocated. Joined with discovery's chip inventory this yields the
``accelerator_pod_info{namespace,pod,container,chip}`` family that lets
Grafana slice every per-chip gauge by workload.

grpc_tools is not installed here, so the client uses grpcio's generic
``unary_unary`` with protoc-generated message classes
(``podresources_pb2.py``, regenerated from ``podresources.proto``).
Failure of any kind degrades to "no attribution" — the exporter's device
metrics never depend on this path.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

log = logging.getLogger(__name__)

KUBELET_SOCKET = "unix:///var/lib/kubelet/pod-resources/kubelet.sock"
_METHOD = "/v1.PodResourcesLister/List"

#: Resource names treated as accelerator devices, in the unified schema
#: spirit: TPU and GPU pools attribute identically.
ACCELERATOR_RESOURCES = ("google.com/tpu", "nvidia.com/gpu")


@dataclass(frozen=True)
class PodDevice:
    namespace: str
    pod: str
    container: str
    resource: str
    device_id: str


class PodResourcesClient:
    """Thin client over the kubelet pod-resources List RPC."""

    def __init__(self, socket_addr: str = KUBELET_SOCKET, timeout: float = 2.0):
        self.addr = socket_addr
        self.timeout = timeout
        self._channel = None
        self._call = None

    def _ensure(self) -> bool:
        if self._call is not None:
            return True
        try:
            import grpc
        except ImportError as exc:
            # The feature was enabled but can't work at all — say so once,
            # above DEBUG (it would otherwise vanish silently).
            log.warning("pod attribution disabled: grpcio not installed (%s)", exc)
            return False
        try:
            from tpumon.attribution import podresources_pb2 as pb

            self._channel = grpc.insecure_channel(self.addr)
            self._call = self._channel.unary_unary(
                _METHOD,
                request_serializer=pb.ListPodResourcesRequest.SerializeToString,
                response_deserializer=pb.ListPodResourcesResponse.FromString,
            )
            self._pb = pb
            return True
        except Exception as exc:
            log.debug("pod-resources client unavailable: %s", exc)
            return False

    def list_devices(self) -> list[PodDevice] | None:
        """Accelerator device allocations; None on FAILURE (socket down,
        grpcio missing), [] when the node genuinely has no accelerator
        pods — callers must treat the two differently."""
        if not self._ensure():
            return None
        try:
            resp = self._call(
                self._pb.ListPodResourcesRequest(), timeout=self.timeout
            )
        except Exception as exc:
            log.debug("pod-resources List failed: %s", exc)
            return None
        out: list[PodDevice] = []
        for pod in resp.pod_resources:
            for container in pod.containers:
                for dev in container.devices:
                    if dev.resource_name not in ACCELERATOR_RESOURCES:
                        continue
                    for device_id in dev.device_ids:
                        out.append(
                            PodDevice(
                                namespace=pod.namespace,
                                pod=pod.name,
                                container=container.name,
                                resource=dev.resource_name,
                                device_id=str(device_id),
                            )
                        )
        return out

    def close(self) -> None:
        if self._channel is not None:
            try:
                self._channel.close()
            except Exception as exc:
                log.debug("pod-resources channel close failed: %s", exc)
            self._channel = None
            self._call = None


class PodAttribution:
    """Builds the accelerator_pod_info family for the poll loop.

    Backs off after failures: off-cluster there is no kubelet socket, and
    the 1 Hz poll budget must not pay a connection attempt every cycle.
    The backoff is the shared bounded-exponential policy
    (tpumon/resilience/policy.py): first failure retries quickly — a
    kubelet restart is usually seconds — then delays double with jitter
    up to ``BACKOFF_MAX_S``, so a permanently absent socket settles at
    one attempt per ~5 minutes instead of a fixed cadence every
    DaemonSet pod shares.
    """

    BACKOFF_BASE_S = 5.0
    BACKOFF_MAX_S = 300.0

    def __init__(self, client: PodResourcesClient | None = None) -> None:
        from tpumon.resilience import Backoff

        self.client = client or PodResourcesClient()
        self._backoff = Backoff(
            base_s=self.BACKOFF_BASE_S, max_s=self.BACKOFF_MAX_S
        )
        self._next_try = 0.0

    @staticmethod
    def _chip_label(device_id: str, topology) -> str:
        """Map a kubelet device ID onto the exporter's chip index label.

        Device metrics label chips by 0-based index (tpumon/parsing.py);
        kubelet device IDs are plugin-defined — bare indices on GKE TPU
        node pools, UUIDs for NVIDIA GPUs. Match against the discovered
        chip inventory first, then accept bare indices; otherwise the
        chip label is empty (the raw ID stays in ``device_id``) so joins
        fail visibly rather than silently matching nothing.
        """
        if topology is not None:
            for chip in topology.chips:
                if chip.device_id and chip.device_id == device_id:
                    return str(chip.index)
            if device_id.isdigit() and int(device_id) < max(
                topology.num_chips, 1
            ):
                return device_id
        elif device_id.isdigit():
            return device_id
        return ""

    def families(self, base_keys: tuple, base_vals: tuple, topology=None):
        import time

        from prometheus_client.core import GaugeMetricFamily

        now = time.monotonic()
        if now < self._next_try:
            return
        devices = self.client.list_devices()
        if devices is None:  # failure → back off (exponential, jittered)
            self._next_try = now + self._backoff.next_delay()
            return
        self._backoff.reset()
        self._next_try = 0.0
        if not devices:  # healthy but no accelerator pods: keep polling
            return
        fam = GaugeMetricFamily(
            "accelerator_pod_info",
            "Accelerator devices allocated to pods (kubelet pod-resources "
            "API); `chip` matches the device metrics' chip index for "
            "joins, `device_id` keeps the raw kubelet ID. Value is 1.",
            labels=base_keys
            + ("namespace", "pod", "container", "resource", "chip", "device_id"),
        )
        for d in devices:
            fam.add_metric(
                base_vals
                + (
                    d.namespace,
                    d.pod,
                    d.container,
                    d.resource,
                    self._chip_label(d.device_id, topology),
                    d.device_id,
                ),
                1.0,
            )
        yield fam
