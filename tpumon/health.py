"""Device-health evaluation — the `dcgmi health -c` analogue.

The DCGM hostengine of the reference genre (SURVEY.md §2.1) exposes a
health-watch API that turns raw counters into pass/warn/fail verdicts
(thermal violations, NVLink errors, retired pages). The TPU-native
equivalent evaluates the monitor's own unified families:

- ``tpu_throttle_score``: 0 none, 1-10 throttled by 10-100% (schema.py)
- ``ici_link_health``: 0 healthy, 1-5 transient, 6-9 persistent minor,
  10 unusable (schema.py)
- HBM occupancy ratio per chip
- exporter metric coverage vs the ≥95% BASELINE target

Consumers: ``tpumon.doctor`` (prints findings, gates exit code),
the exporter's ``/health/devices`` JSON endpoint (K8s-scriptable), and
``tpumon smi`` (one summary line). All of them evaluate the *same parsed
snapshot* (tpumon.smi.snapshot_from_text), so verdicts cannot drift
between surfaces.
"""

from __future__ import annotations

import logging
import os
from dataclasses import asdict, dataclass, fields

log = logging.getLogger(__name__)

OK = "ok"
WARN = "warn"
CRIT = "crit"

_SEV_ORDER = {OK: 0, WARN: 1, CRIT: 2}

#: The single definition of the BASELINE ≥95% coverage target — doctor,
#: the health evaluator, and the alert-rule drift test all import this.
COVERAGE_TARGET = 0.95


@dataclass(frozen=True)
class Thresholds:
    """Health-check thresholds, overridable per deployment.

    A DaemonSet operator cannot monkeypatch module constants; every field
    here is settable via ``TPUMON_HEALTH_<FIELD>`` (e.g.
    ``TPUMON_HEALTH_HBM_WARN_RATIO=0.85``). A malformed value logs and
    keeps the default — same never-crash stance as tpumon.config.
    """

    throttle_warn: float = 1.0  # any throttling at all
    throttle_crit: float = 5.0  # throttled by >= 50%
    ici_transient_min: float = 1.0  # 1-5: transient errors
    ici_persistent_min: float = 6.0  # 6-9: persistent minor
    ici_unusable: float = 10.0
    hbm_warn_ratio: float = 0.92
    hbm_crit_ratio: float = 0.98
    coverage_target: float = COVERAGE_TARGET
    #: Programs enqueued on a core while the whole device shows ~no
    #: compute — the wedged-runtime signature (work is queued but nothing
    #: executes). One poll can be a transient; the Prometheus alert adds
    #: a `for:` duration on top of this instantaneous check.
    queue_stall_depth: float = 8.0
    queue_stall_duty_pct: float = 1.0

    @classmethod
    def from_env(cls, environ=None) -> "Thresholds":
        env = os.environ if environ is None else environ
        kwargs = {}
        for f in fields(cls):
            raw = env.get("TPUMON_HEALTH_" + f.name.upper())
            if raw is None:
                continue
            try:
                kwargs[f.name] = float(raw)
            except ValueError:
                log.warning(
                    "ignoring malformed TPUMON_HEALTH_%s=%r",
                    f.name.upper(), raw,
                )
        return cls(**kwargs)


#: (env-values key, parsed Thresholds) — evaluate() runs at 1 Hz in the
#: poll loop, so the env is re-parsed (and a malformed value re-warned)
#: only when a TPUMON_HEALTH_* value actually changes, not per call.
_env_cache: tuple | None = None


def env_thresholds() -> Thresholds:
    """Process-env-backed thresholds, parsed once per distinct env state."""
    global _env_cache
    key = tuple(
        os.environ.get("TPUMON_HEALTH_" + f.name.upper())
        for f in fields(Thresholds)
    )
    if _env_cache is None or _env_cache[0] != key:
        _env_cache = (key, Thresholds.from_env())
    return _env_cache[1]


@dataclass(frozen=True)
class Finding:
    severity: str  # ok | warn | crit
    code: str  # stable machine id, e.g. "throttle", "ici_link"
    message: str
    chip: str | None = None


def evaluate(snap: dict, thresholds: Thresholds | None = None) -> list[Finding]:
    """Evaluate a parsed snapshot (tpumon.smi.snapshot_from_text shape).

    Returns findings sorted most-severe first; an empty list means every
    check passed with data present. Missing families (runtime detached)
    produce no findings — absence is "no data", never "healthy" or
    "broken" (SURVEY.md §2.2 absent-not-zero).

    ``thresholds`` defaults to :meth:`Thresholds.from_env`, so a
    DaemonSet's ``TPUMON_HEALTH_*`` env vars flow into every consumer
    (exporter poll loop, /health/devices, doctor, smi) without plumbing.
    """
    t = thresholds if thresholds is not None else env_thresholds()
    findings: list[Finding] = []

    for chip in sorted(snap.get("chips", {})):
        row = snap["chips"][chip]
        thr = row.get("throttle")
        if thr is not None and thr >= t.throttle_warn:
            sev = CRIT if thr >= t.throttle_crit else WARN
            findings.append(
                Finding(
                    sev,
                    "throttle",
                    f"chip {chip} throttled (score {thr:.0f}/10 ≈ "
                    f"{thr * 10:.0f}% slowdown)",
                    chip=chip,
                )
            )
        used, total = row.get("hbm_used"), row.get("hbm_total")
        if used is not None and total:
            ratio = used / total
            if ratio >= t.hbm_warn_ratio:
                sev = CRIT if ratio >= t.hbm_crit_ratio else WARN
                findings.append(
                    Finding(
                        sev,
                        "hbm_pressure",
                        f"chip {chip} HBM {ratio * 100:.1f}% full",
                        chip=chip,
                    )
                )

    ici = snap.get("ici") or {}
    links = ici.get("links") or {}
    for link, score in sorted(links.items()):
        if score >= t.ici_unusable:
            findings.append(
                Finding(CRIT, "ici_link", f"ICI link {link} unusable (10)")
            )
        elif score >= t.ici_persistent_min:
            findings.append(
                Finding(
                    CRIT,
                    "ici_link",
                    f"ICI link {link} persistent errors (score {score:.0f})",
                )
            )
        elif score >= t.ici_transient_min:
            findings.append(
                Finding(
                    WARN,
                    "ici_link",
                    f"ICI link {link} transient errors (score {score:.0f})",
                )
            )

    # Stall signature: deep HLO queues while the device does no work (the
    # eACGM-style anomaly pairing of a load signal with a progress signal).
    queues = snap.get("queues") or {}
    if queues:
        duties = [
            row.get("duty_pct")
            for row in snap.get("chips", {}).values()
            if row.get("duty_pct") is not None
        ]
        device_idle = bool(duties) and max(duties) <= t.queue_stall_duty_pct
        if device_idle:
            for core, depth in sorted(queues.items()):
                if depth >= t.queue_stall_depth:
                    findings.append(
                        Finding(
                            WARN,
                            "queue_stall",
                            f"core {core} has {depth:.0f} programs queued "
                            "while the device shows no compute "
                            "(possible wedged runtime)",
                        )
                    )

    cov = snap.get("coverage")
    if cov is not None and cov < t.coverage_target:
        findings.append(
            Finding(
                WARN,
                "coverage",
                f"metric coverage {cov * 100:.0f}% below the "
                f"{t.coverage_target * 100:.0f}% target",
            )
        )

    findings.sort(key=lambda f: -_SEV_ORDER[f.severity])
    return findings


def severity_value(severity: str) -> int:
    """Numeric form for the accelerator_health_status gauge (0/1/2)."""
    return _SEV_ORDER[severity]


def overall(findings: list[Finding]) -> str:
    """Worst severity across findings; `ok` when none."""
    worst = OK
    for f in findings:
        if _SEV_ORDER[f.severity] > _SEV_ORDER[worst]:
            worst = f.severity
    return worst


def report(snap: dict, findings: list[Finding] | None = None) -> dict:
    """JSON-ready verdict document (the /health/devices body).

    Pass ``findings`` to reuse an evaluation already done on this snap
    (the poll cycle computes one for the metric families).
    """
    if findings is None:
        findings = evaluate(snap)
    return {
        "status": overall(findings),
        "findings": [asdict(f) for f in findings],
        "chips": len(snap.get("chips", {})),
        "coverage": snap.get("coverage"),
    }
