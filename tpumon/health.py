"""Device-health evaluation — the `dcgmi health -c` analogue.

The DCGM hostengine of the reference genre (SURVEY.md §2.1) exposes a
health-watch API that turns raw counters into pass/warn/fail verdicts
(thermal violations, NVLink errors, retired pages). The TPU-native
equivalent evaluates the monitor's own unified families:

- ``tpu_throttle_score``: 0 none, 1-10 throttled by 10-100% (schema.py)
- ``ici_link_health``: 0 healthy, 1-5 transient, 6-9 persistent minor,
  10 unusable (schema.py)
- HBM occupancy ratio per chip
- exporter metric coverage vs the ≥95% BASELINE target

Consumers: ``tpumon.doctor`` (prints findings, gates exit code),
the exporter's ``/health/devices`` JSON endpoint (K8s-scriptable), and
``tpumon smi`` (one summary line). All of them evaluate the *same parsed
snapshot* (tpumon.smi.snapshot_from_text), so verdicts cannot drift
between surfaces.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

OK = "ok"
WARN = "warn"
CRIT = "crit"

_SEV_ORDER = {OK: 0, WARN: 1, CRIT: 2}

#: Thresholds (module-level so operators can monkeypatch/configure).
THROTTLE_WARN = 1.0  # any throttling at all
THROTTLE_CRIT = 5.0  # throttled by >= 50%
ICI_TRANSIENT_MIN = 1.0  # 1-5: transient errors
ICI_PERSISTENT_MIN = 6.0  # 6-9: persistent minor
ICI_UNUSABLE = 10.0
HBM_WARN_RATIO = 0.92
HBM_CRIT_RATIO = 0.98
COVERAGE_TARGET = 0.95
#: Programs enqueued on a core while the whole device shows ~no compute —
#: the wedged-runtime signature (work is queued but nothing executes).
#: One poll can be a transient; the Prometheus alert adds a `for:`
#: duration on top of this instantaneous check.
QUEUE_STALL_DEPTH = 8.0
QUEUE_STALL_DUTY_PCT = 1.0


@dataclass(frozen=True)
class Finding:
    severity: str  # ok | warn | crit
    code: str  # stable machine id, e.g. "throttle", "ici_link"
    message: str
    chip: str | None = None


def evaluate(snap: dict) -> list[Finding]:
    """Evaluate a parsed snapshot (tpumon.smi.snapshot_from_text shape).

    Returns findings sorted most-severe first; an empty list means every
    check passed with data present. Missing families (runtime detached)
    produce no findings — absence is "no data", never "healthy" or
    "broken" (SURVEY.md §2.2 absent-not-zero).
    """
    findings: list[Finding] = []

    for chip in sorted(snap.get("chips", {})):
        row = snap["chips"][chip]
        thr = row.get("throttle")
        if thr is not None and thr >= THROTTLE_WARN:
            sev = CRIT if thr >= THROTTLE_CRIT else WARN
            findings.append(
                Finding(
                    sev,
                    "throttle",
                    f"chip {chip} throttled (score {thr:.0f}/10 ≈ "
                    f"{thr * 10:.0f}% slowdown)",
                    chip=chip,
                )
            )
        used, total = row.get("hbm_used"), row.get("hbm_total")
        if used is not None and total:
            ratio = used / total
            if ratio >= HBM_WARN_RATIO:
                sev = CRIT if ratio >= HBM_CRIT_RATIO else WARN
                findings.append(
                    Finding(
                        sev,
                        "hbm_pressure",
                        f"chip {chip} HBM {ratio * 100:.1f}% full",
                        chip=chip,
                    )
                )

    ici = snap.get("ici") or {}
    links = ici.get("links") or {}
    for link, score in sorted(links.items()):
        if score >= ICI_UNUSABLE:
            findings.append(
                Finding(CRIT, "ici_link", f"ICI link {link} unusable (10)")
            )
        elif score >= ICI_PERSISTENT_MIN:
            findings.append(
                Finding(
                    CRIT,
                    "ici_link",
                    f"ICI link {link} persistent errors (score {score:.0f})",
                )
            )
        elif score >= ICI_TRANSIENT_MIN:
            findings.append(
                Finding(
                    WARN,
                    "ici_link",
                    f"ICI link {link} transient errors (score {score:.0f})",
                )
            )

    # Stall signature: deep HLO queues while the device does no work (the
    # eACGM-style anomaly pairing of a load signal with a progress signal).
    queues = snap.get("queues") or {}
    if queues:
        duties = [
            row.get("duty_pct")
            for row in snap.get("chips", {}).values()
            if row.get("duty_pct") is not None
        ]
        device_idle = bool(duties) and max(duties) <= QUEUE_STALL_DUTY_PCT
        if device_idle:
            for core, depth in sorted(queues.items()):
                if depth >= QUEUE_STALL_DEPTH:
                    findings.append(
                        Finding(
                            WARN,
                            "queue_stall",
                            f"core {core} has {depth:.0f} programs queued "
                            "while the device shows no compute "
                            "(possible wedged runtime)",
                        )
                    )

    cov = snap.get("coverage")
    if cov is not None and cov < COVERAGE_TARGET:
        findings.append(
            Finding(
                WARN,
                "coverage",
                f"metric coverage {cov * 100:.0f}% below the "
                f"{COVERAGE_TARGET * 100:.0f}% target",
            )
        )

    findings.sort(key=lambda f: -_SEV_ORDER[f.severity])
    return findings


def severity_value(severity: str) -> int:
    """Numeric form for the accelerator_health_status gauge (0/1/2)."""
    return _SEV_ORDER[severity]


def overall(findings: list[Finding]) -> str:
    """Worst severity across findings; `ok` when none."""
    worst = OK
    for f in findings:
        if _SEV_ORDER[f.severity] > _SEV_ORDER[worst]:
            worst = f.severity
    return worst


def report(snap: dict, findings: list[Finding] | None = None) -> dict:
    """JSON-ready verdict document (the /health/devices body).

    Pass ``findings`` to reuse an evaluation already done on this snap
    (the poll cycle computes one for the metric families).
    """
    if findings is None:
        findings = evaluate(snap)
    return {
        "status": overall(findings),
        "findings": [asdict(f) for f in findings],
        "chips": len(snap.get("chips", {})),
        "coverage": snap.get("coverage"),
    }
