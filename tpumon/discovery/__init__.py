from tpumon.discovery.topology import Chip, Topology, discover

__all__ = ["Chip", "Topology", "discover"]
