"""Device-discovery sidecar (SURVEY.md §3.4, §1 L2).

The TPU-native replacement for the reference genre's PCIe-BDF discovery
sidecar: discovers slice topology (host/chip/core + coords), writes it as
JSON to a shared volume for other containers in the pod, and exposes an
``accelerator_info`` identity gauge on its own ``/metrics``.

Runs alongside the exporter in the DaemonSet pod (deploy/daemonset.yaml).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading
import time

from prometheus_client.registry import CollectorRegistry

from tpumon.config import Config
from tpumon.discovery.topology import Topology, discover
from tpumon.exporter.collector import topology_families

log = logging.getLogger(__name__)


class _TopologyCollector:
    """Prometheus collector over the most recent discovery result.

    Reuses the exporter's identity-family construction so the sidecar and
    exporter can never drift on schema/labels.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._topology = Topology()

    def update(self, topology: Topology) -> None:
        with self._lock:
            self._topology = topology

    def collect(self):
        with self._lock:
            topo = self._topology
        yield from topology_families(topo)


def write_topology(topology: Topology, path: str) -> None:
    """Atomically write topology JSON for pod-mates (shared emptyDir)."""
    directory = os.path.dirname(path) or "."
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(topology.to_json())
        fh.write("\n")
    os.replace(tmp, path)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpumon-discovery")
    Config.add_args(parser)
    parser.add_argument(
        "--once", action="store_true", help="discover, write JSON, exit"
    )
    parser.add_argument(
        "--refresh",
        type=float,
        default=60.0,
        help="re-discovery interval seconds (topology rarely changes)",
    )
    args = parser.parse_args(argv)
    cfg = Config.from_env().with_args(args)
    logging.basicConfig(
        level=getattr(logging, cfg.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )

    topo = discover(cfg.topology_file)
    write_topology(topo, cfg.topology_out)
    log.info(
        "discovered %d chips (%s) → %s",
        topo.num_chips,
        topo.accelerator_type,
        cfg.topology_out,
    )
    if args.once:
        return 0

    collector = _TopologyCollector()
    collector.update(topo)
    registry = CollectorRegistry()
    registry.register(collector)

    from tpumon.exporter.server import ExporterServer, _make_app, registry_renderer
    from tpumon.exporter.telemetry import SelfTelemetry

    # Same registry that is served, so the sidecar's own scrape-duration
    # and liveness gauges are actually visible to Prometheus.
    telemetry = SelfTelemetry(registry)
    telemetry.last_poll.set(time.time())
    # The sidecar has no device poll loop; its refresh loop is its
    # liveness. Without this the shared tpumon_up gauge would read 0
    # forever and falsely trip the TPUMonPollLoopDown alert.
    telemetry.up.set(1)
    app = _make_app(registry_renderer(registry), telemetry, lambda: (True, "ok\n"))
    server = ExporterServer(app, cfg.addr, cfg.port)
    server.start()
    log.info("discovery sidecar serving %s/metrics", server.url)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        while not stop.wait(timeout=args.refresh):
            topo = discover(cfg.topology_file)
            collector.update(topo)
            write_topology(topo, cfg.topology_out)
            telemetry.last_poll.set(time.time())
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
