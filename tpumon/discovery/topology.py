"""TPU slice-topology discovery (SURVEY.md §1 L2, §3.4).

Replaces the reference genre's PCIe-BDF device identity with TPU-native
identity: slice / host / chip / core plus physical chip coordinates.

Sources, in precedence order:

1. An explicit topology JSON file (``--topology-file``) — used by tests and
   air-gapped deployments.
2. GKE TPU environment variables (``TPU_WORKER_ID``,
   ``TPU_WORKER_HOSTNAMES``, ``TPU_ACCELERATOR_TYPE``, ``TPU_CHIPS_PER_HOST_BOUNDS``
   / ``TPU_HOST_BOUNDS``) — present in pods on ``google.com/tpu`` node pools.
3. ``libtpu.sdk.slice.get_chip_coordinates()`` for physical coords — the
   live probe in SURVEY.md §2.2 shows this raises ``RuntimeError`` when the
   hostname carries no worker index, so it is strictly best-effort.
4. JAX local device enumeration (chip count + platform), when importable.
5. Zero devices → the exporter runs in stub mode (BASELINE.json config 1).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
from dataclasses import dataclass

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Chip:
    """One accelerator chip on this host."""

    index: int
    #: Physical coordinates in the slice mesh (x, y, z), if known.
    coords: tuple[int, int, int] | None = None
    #: Number of compute cores (TensorCores) on the chip.
    num_cores: int = 1
    #: Stable device identifier (TPU: "slice/host/chip"; GPU path: UUID).
    device_id: str = ""


@dataclass(frozen=True)
class Topology:
    """Identity of the accelerators visible to this exporter process."""

    #: e.g. "v5litepod-16", "v5p-64", "v4-8"; "none" when no accelerator.
    accelerator_type: str = "none"
    #: Logical slice/pool name (GKE: from TPU_WORKER_HOSTNAMES prefix).
    slice_name: str = "default"
    hostname: str = ""
    #: This host's worker index within the slice.
    worker_id: int = 0
    num_hosts: int = 1
    chips: tuple[Chip, ...] = ()

    @property
    def num_chips(self) -> int:
        return len(self.chips)

    @property
    def num_cores(self) -> int:
        return sum(c.num_cores for c in self.chips)

    def base_labels(self) -> dict[str, str]:
        """Labels shared by every sample from this host (SURVEY.md §1 L3)."""
        return {
            "slice": self.slice_name,
            "host": self.hostname,
            "worker": str(self.worker_id),
            "accelerator": self.accelerator_type,
        }

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, raw: str) -> "Topology":
        obj = json.loads(raw)
        chips = tuple(
            Chip(
                index=c["index"],
                coords=tuple(c["coords"]) if c.get("coords") else None,
                num_cores=c.get("num_cores", 1),
                device_id=c.get("device_id", ""),
            )
            for c in obj.get("chips", ())
        )
        return cls(
            accelerator_type=obj.get("accelerator_type", "none"),
            slice_name=obj.get("slice_name", "default"),
            hostname=obj.get("hostname", ""),
            worker_id=obj.get("worker_id", 0),
            num_hosts=obj.get("num_hosts", 1),
            chips=chips,
        )


def _cores_per_chip(accelerator_type: str) -> int:
    """TPU generations differ: v4/v5p chips expose 2 TensorCores, v5e/v6e 1."""
    t = accelerator_type.lower()
    if "v5lite" in t or "v5e" in t or "v6e" in t:
        return 1
    if t.startswith(("v4", "v5p", "v3", "v2")):
        return 2
    return 1


def _from_file(path: str) -> Topology | None:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return Topology.from_json(fh.read())
    except (OSError, ValueError, KeyError) as exc:
        log.warning("topology file %s unusable: %s", path, exc)
        return None


def _gke_env() -> dict[str, str]:
    keys = (
        "TPU_WORKER_ID",
        "TPU_WORKER_HOSTNAMES",
        "TPU_ACCELERATOR_TYPE",
        "TPU_CHIPS_PER_HOST_BOUNDS",
        "TPU_HOST_BOUNDS",
        "TPU_SKIP_MDS_QUERY",
    )
    return {k: os.environ[k] for k in keys if k in os.environ}


def _chips_from_bounds(bounds: str) -> int:
    # "2,2,1" -> 4 chips on this host.
    try:
        n = 1
        for part in bounds.split(","):
            n *= int(part)
        return max(n, 0)
    except ValueError:
        return 0


def _libtpu_coords(num_chips: int) -> list[tuple[int, int, int] | None]:
    """Best-effort physical coords via libtpu.sdk.slice (SURVEY.md §3.4)."""
    try:
        from libtpu.sdk import slice as tpu_slice  # type: ignore

        cc = tpu_slice.get_chip_coordinates()
        coords = getattr(cc, "coordinates", None) or list(cc)  # duck-typed
        out: list[tuple[int, int, int] | None] = []
        for c in coords[:num_chips]:
            tup = tuple(int(v) for v in c)
            out.append((tup + (0, 0, 0))[:3])  # pad to 3-D
        while len(out) < num_chips:
            out.append(None)
        return out
    except Exception as exc:  # RuntimeError observed live on 1-host (§2.2)
        log.debug("chip coordinates unavailable: %s", exc)
        return [None] * num_chips


def _dev_accel_count() -> int:
    """Count /dev/accel* device nodes (present on real TPU VMs/nodes)."""
    import glob

    return len(glob.glob("/dev/accel*"))


#: How long the JAX-based fallback may take before discovery gives up.
#: Initializing JAX attaches to the TPU runtime, which can HANG when the
#: runtime is wedged (observed live on this host) — a monitoring agent
#: must degrade to stub mode instead of hanging at startup.
JAX_DISCOVERY_TIMEOUT_S = 15.0


#: Single shared probe state: at most ONE jax-enumeration thread ever
#: exists per process. The sidecar re-runs discover() every refresh
#: interval; without this, a wedged runtime would stack a new permanently
#: hung thread (and re-pay the 15s stall) every cycle.
_jax_probe_lock = None
_jax_probe_thread = None
_jax_probe_result: list[tuple[int, str]] = []


def _jax_chip_count() -> tuple[int, str]:
    """Fallback enumeration via JAX local devices, bounded by a timeout.

    The probe runs in a single daemon thread shared across calls; on
    timeout, discovery reports zero chips (stub mode) immediately and
    later calls pick up the result if the probe ever completes.
    """
    import threading

    global _jax_probe_lock, _jax_probe_thread
    if _jax_probe_lock is None:
        _jax_probe_lock = threading.Lock()

    def probe() -> None:
        try:
            import jax

            devices = jax.local_devices()
            platform = devices[0].platform if devices else "none"
            if platform != "tpu":
                _jax_probe_result.append((0, platform))
                return
            chip_ids = {getattr(d, "id", i) for i, d in enumerate(devices)}
            _jax_probe_result.append((len(chip_ids), platform))
        except Exception as exc:
            log.debug("jax enumeration unavailable: %s", exc)
            _jax_probe_result.append((0, "none"))

    with _jax_probe_lock:
        if _jax_probe_result:
            return _jax_probe_result[0]
        if _jax_probe_thread is None:
            _jax_probe_thread = threading.Thread(
                target=probe, name="tpumon-jax-discover", daemon=True
            )
            _jax_probe_thread.start()
        thread = _jax_probe_thread

    thread.join(timeout=JAX_DISCOVERY_TIMEOUT_S)
    if not _jax_probe_result:
        log.warning(
            "jax device enumeration timed out after %.0fs (TPU runtime "
            "wedged?); continuing with zero chips",
            JAX_DISCOVERY_TIMEOUT_S,
        )
        return 0, "none"
    return _jax_probe_result[0]


def discover(topology_file: str | None = None) -> Topology:
    """Build the host's Topology from the best available source."""
    if topology_file:
        topo = _from_file(topology_file)
        if topo is not None:
            return topo

    hostname = socket.gethostname()
    env = _gke_env()

    accel = env.get("TPU_ACCELERATOR_TYPE", "")
    try:
        worker_id = int(env.get("TPU_WORKER_ID", "0") or 0)
    except ValueError:
        # e.g. TPU_WORKER_ID="worker-0": keep the digits, else 0 — discovery
        # must never crash the exporter over a malformed env var.
        digits = "".join(ch for ch in env.get("TPU_WORKER_ID", "") if ch.isdigit())
        worker_id = int(digits) if digits else 0
    worker_hosts = [
        h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h.strip()
    ]
    num_hosts = max(len(worker_hosts), 1)
    slice_name = os.environ.get(
        "TPUMON_SLICE_NAME",
        (worker_hosts[0].split(".")[0].rsplit("-", 1)[0] if worker_hosts else "default"),
    )

    num_chips = _chips_from_bounds(env.get("TPU_CHIPS_PER_HOST_BOUNDS", ""))
    if num_chips == 0:
        # Cheap and hang-proof before the JAX fallback: real TPU nodes
        # expose /dev/accel* device nodes.
        num_chips = _dev_accel_count()
        if num_chips and not accel:
            accel = "tpu"
    if num_chips == 0:
        num_chips, platform = _jax_chip_count()
        if num_chips and not accel:
            accel = f"tpu-{platform}"

    if num_chips == 0:
        return Topology(
            accelerator_type="none",
            slice_name=slice_name,
            hostname=hostname,
            worker_id=worker_id,
            num_hosts=num_hosts,
            chips=(),
        )

    cores = _cores_per_chip(accel)
    coords = _libtpu_coords(num_chips)
    chips = tuple(
        Chip(
            index=i,
            coords=coords[i],
            num_cores=cores,
            device_id=f"{slice_name}/{worker_id}/{i}",
        )
        for i in range(num_chips)
    )
    return Topology(
        accelerator_type=accel or "tpu",
        slice_name=slice_name,
        hostname=hostname,
        worker_id=worker_id,
        num_hosts=num_hosts,
        chips=chips,
    )
