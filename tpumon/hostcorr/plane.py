"""The host-correlation plane wired into the poll loop.

One :meth:`HostCorrPlane.cycle` call per poll, fed the PollStats the
collector already computed. The pass:

1. samples host signals (procfs/cgroupfs only — **zero device queries**,
   preserving the collector's scrape-latency design rule);
2. joins them with the SAME cycle's device snapshot into a per-slice
   straggler verdict (tpumon/hostcorr/detectors.py);
3. appends one time-aligned record to the bounded correlation ring
   (served as ``GET /hostcorr``, ``?since=`` replay like /anomalies);
4. injects a ``hostcorr`` block into ``PollStats.snapshot`` so the
   anomaly engine's cross-signal detectors (host_straggler, host_stall)
   see host and device series side by side;
5. returns the ``tpu_hostcorr_*`` / ``tpu_straggler_*`` families for
   this cycle's page (names/help/labels from the HOSTCORR_FAMILIES
   registry, so docs and dashboards cannot drift).

Graceful degradation: on hosts without PSI/schedstat the page carries
``tpu_hostcorr_available 0`` and per-group availability; the verdict
falls back to device-only attribution (never errors), and every signal
family is simply absent (absent-not-zero).
"""

from __future__ import annotations

import logging
import threading
from collections import Counter, deque

from prometheus_client.core import CounterMetricFamily, GaugeMetricFamily

from tpumon.hostcorr.detectors import StragglerJudge, env_thresholds
from tpumon.hostcorr.sampler import SIGNAL_GROUPS, HostSampler

log = logging.getLogger(__name__)


def _same_job_step_seconds(feeds: dict) -> dict[str, float]:
    """Per-feed step seconds from the lifecycle block, restricted to
    the LARGEST group of feeds sharing one workload mesh signature
    (``workload_mesh_info`` axes — the job identity a feed carries).

    Two different jobs sharing a pool run at legitimately different
    step times; comparing them would arm the step-skew stream against
    a phantom straggler. Feeds without a mesh signature group together
    (device-only harnesses all look alike — better one honest bucket
    than silently dropping them). Ties break deterministically on the
    first-seen group, i.e. lifecycle feed configuration order."""
    groups: dict[tuple, dict[str, float]] = {}
    for url, feed in feeds.items():
        if not isinstance(feed, dict):
            continue
        seconds = feed.get("step_seconds")
        if seconds is None:
            continue
        axes = feed.get("axes")
        sig = (
            tuple(sorted(axes.items())) if isinstance(axes, dict) else ()
        )
        groups.setdefault(sig, {})[url] = seconds
    best: dict[str, float] = {}
    for group in groups.values():
        if len(group) >= 2 and len(group) > len(best):
            best = group
    return best


class HostCorrPlane:
    """Thread model: ``cycle`` runs on the poller thread only;
    ``replay``/``snapshot``/``resize`` may be called from HTTP threads —
    shared state (ring, last record, onset totals) is guarded by one
    lock held for deque/dict work only."""

    def __init__(
        self,
        proc_root: str = "",
        ring: int = 600,
        sampler: HostSampler | None = None,
    ) -> None:
        self._sampler = sampler if sampler is not None else HostSampler(proc_root)
        self._judge = StragglerJudge()
        self._full_ring = max(1, int(ring))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._full_ring)  # guarded-by: self._lock
        self._last: dict | None = None  # guarded-by: self._lock
        self._totals: Counter = Counter()  # guarded-by: self._lock
        self._cycles = 0  # guarded-by: self._lock
        self._was_active = False  # poller thread only
        #: Episode onset seen but cause still "unknown" — the count is
        #: held until the judge upgrades it (or the episode clears), so
        #: tpu_straggler_events_total, the verdict gauge, and the event
        #: stream always name the SAME cause for one episode (counters
        #: can't decrement a mislabeled onset).
        self._pending_unknown = False  # poller thread only

    @property
    def ring_capacity(self) -> int:
        return self._full_ring

    def resize(self, n: int) -> None:
        """Re-cap the correlation ring in place — the memory-watermark
        response (tpumon/guard/memwatch); newest records retained,
        reversible."""
        n = max(1, int(n))
        with self._lock:
            if n == self._ring.maxlen:
                return
            self._ring = deque(self._ring, maxlen=n)

    # -- poll-loop integration --------------------------------------------

    def cycle(self, now: float, stats) -> list:
        """One Poller cycle: sample, judge, record, inject, emit."""
        host = self._sampler.sample(now)
        snap = stats.snapshot if stats.snapshot is not None else {}
        duties: dict[str, float] = {}
        worst_throttled = False
        chips = snap.get("chips") or {}
        for chip, row in chips.items():
            duty = row.get("duty_pct")
            if duty is not None:
                duties[chip] = duty
        t = env_thresholds()
        worst = min(duties, key=lambda c: duties[c]) if duties else None
        if worst is not None:
            worst_throttled = (chips.get(worst) or {}).get("throttle", 0) > 0
        evidence = {"throttled": worst_throttled}
        # Step-skew evidence (ROADMAP remnant): when the lifecycle plane
        # — which runs earlier in the same poll cycle — probes multiple
        # hosts of ONE JOB, the per-feed step durations feed the judge's
        # second evidence stream (a lagging HOST with locally balanced
        # chips is invisible to duty skew). Cause attribution unchanged.
        # Feeds group by their workload's mesh signature first: two
        # DIFFERENT jobs sharing a pool (the interference scenario)
        # legitimately run at different step times, and a cross-job
        # median would read that as a straggler forever. Only the
        # largest same-signature group (≥2 feeds) arms the stream.
        step_seconds = _same_job_step_seconds(
            (snap.get("lifecycle") or {}).get("feeds") or {}
        )
        verdict = self._judge.judge(
            duties, host, evidence, t, step_seconds=step_seconds or None
        )

        active = bool(verdict.get("active"))
        onset = active and not self._was_active
        cleared = self._was_active and not active
        self._was_active = active
        cause = verdict.get("cause", "unknown")

        host_doc = host.to_dict()
        record = {
            "ts": now,
            "host": host_doc,
            "device": {
                "duty": duties,
                "median_duty_pct": verdict.get("median_duty_pct"),
                "worst_chip": verdict.get("chip"),
                "worst_throttled": worst_throttled,
                "degraded": bool(stats.degraded),
            },
            "straggler": verdict,
        }
        with self._lock:
            self._cycles += 1
            if onset:
                if cause == "unknown":
                    self._pending_unknown = True
                else:
                    self._totals[cause] += 1
            elif active and self._pending_unknown and cause != "unknown":
                # The sticky judge upgraded the episode's cause: count it
                # now, once, under the cause every other surface reports.
                self._totals[cause] += 1
                self._pending_unknown = False
            elif cleared and self._pending_unknown:
                # The episode ended without ever confessing: it WAS
                # unknown, and stays counted that way.
                self._totals["unknown"] += 1
                self._pending_unknown = False
            self._ring.append(record)
            self._last = record
            totals = dict(self._totals)

        if stats.snapshot is not None:
            # The anomaly engine's cross-signal detectors read this block
            # from the snapshot the engine is fed anyway — no side channel.
            stats.snapshot["hostcorr"] = {
                "available": host.available,
                "signals": host_doc,
                "straggler": verdict,
            }
        return self._families(
            stats.base_keys, stats.base_vals, host, verdict, totals
        )

    # -- exposition --------------------------------------------------------

    def _families(self, base_keys, base_vals, host, verdict, totals) -> list:
        from tpumon.families import HOSTCORR_FAMILIES

        labels = tuple(base_keys)
        vals = tuple(base_vals)

        def fam(name, cls):
            _, help_text, extra = HOSTCORR_FAMILIES[name]
            return cls(name, help_text, labels=labels + extra)

        available = fam("tpu_hostcorr_available", GaugeMetricFamily)
        available.add_metric(vals, 1.0 if host.available else 0.0)
        out = [available]

        groups = fam("tpu_hostcorr_signal_available", GaugeMetricFamily)
        for group in SIGNAL_GROUPS:
            groups.add_metric(
                vals + (group,), 1.0 if host.groups.get(group) else 0.0
            )
        out.append(groups)

        if host.psi:
            share = fam("tpu_hostcorr_psi_share", GaugeMetricFamily)
            stall = fam(
                "tpu_hostcorr_psi_stall_seconds_total", CounterMetricFamily
            )
            for resource in sorted(host.psi):
                for kind in sorted(host.psi[resource]):
                    row = host.psi[resource][kind]
                    share.add_metric(
                        vals + (resource, kind), row["share"]
                    )
                    stall.add_metric(
                        vals + (resource, kind), row["stall_s"]
                    )
            out.extend([share, stall])

        if host.pod_psi:
            pod_share = fam("tpu_hostcorr_pod_psi_share", GaugeMetricFamily)
            for pod in sorted(host.pod_psi):
                for resource in sorted(host.pod_psi[pod]):
                    pod_share.add_metric(
                        vals + (pod, resource),
                        host.pod_psi[pod][resource]["share"],
                    )
            out.append(pod_share)

        pods = {
            pod: row for pod, row in host.sched.items() if row
        }
        if pods:
            delay = fam(
                "tpu_hostcorr_sched_delay_seconds_total", CounterMetricFamily
            )
            shares = fam("tpu_hostcorr_sched_delay_share", GaugeMetricFamily)
            any_share = False
            for pod in sorted(pods):
                row = pods[pod]
                delay.add_metric(vals + (pod,), row["delay_s"])
                if row.get("share") is not None:
                    shares.add_metric(vals + (pod,), row["share"])
                    any_share = True
            out.append(delay)
            if any_share:
                out.append(shares)

        rates = {
            "tpu_hostcorr_net_bytes_per_second": host.net_bps,
            "tpu_hostcorr_disk_bytes_per_second": host.disk_bps,
        }
        for name, by_dir in rates.items():
            present = {
                d: v for d, v in by_dir.items() if v is not None
            }
            if present:
                rate_fam = fam(name, GaugeMetricFamily)
                for direction in sorted(present):
                    rate_fam.add_metric(
                        vals + (direction,), present[direction]
                    )
                out.append(rate_fam)

        if host.page_cache_bytes is not None:
            cache = fam("tpu_hostcorr_page_cache_bytes", GaugeMetricFamily)
            cache.add_metric(vals, host.page_cache_bytes)
            out.append(cache)
        if host.reclaim_pps is not None:
            reclaim = fam(
                "tpu_hostcorr_reclaim_pages_per_second", GaugeMetricFamily
            )
            reclaim.add_metric(vals, host.reclaim_pps)
            out.append(reclaim)

        if verdict.get("skew_pct") is not None:
            skew = fam("tpu_straggler_skew_pct", GaugeMetricFamily)
            skew.add_metric(vals, verdict["skew_pct"])
            out.append(skew)
        if verdict.get("step_skew_ratio") is not None:
            # The step-stream magnitude: without it a step-skew-only
            # episode would read ~0 on the skew_pct family and rank
            # last in every fleet worst-straggler view.
            step_skew = fam(
                "tpu_straggler_step_skew_ratio", GaugeMetricFamily
            )
            step_skew.add_metric(vals, verdict["step_skew_ratio"])
            out.append(step_skew)
        if verdict.get("active"):
            vfam = fam("tpu_straggler_verdict", GaugeMetricFamily)
            vfam.add_metric(
                vals
                + (verdict.get("cause", "unknown"), verdict.get("chip", "")),
                1.0,
            )
            out.append(vfam)
        if totals:
            events = fam("tpu_straggler_events_total", CounterMetricFamily)
            for cause in sorted(totals):
                events.add_metric(vals + (cause,), float(totals[cause]))
            out.append(events)
        return out

    # -- query surfaces ----------------------------------------------------

    def replay(self, since: float = 0.0) -> tuple[dict, list]:
        """(/hostcorr envelope, records at/after ``since``) — the server
        bounds the record list and stamps continuation tokens."""
        with self._lock:
            records = [r for r in self._ring if r["ts"] >= since]
            last = self._last
            totals = dict(self._totals)
            cycles = self._cycles
            capacity = self._ring.maxlen
        doc = {
            "cycles": cycles,
            "ring_capacity": capacity,
            "available": bool(last and last["host"]["available"]),
            "groups": dict(last["host"]["groups"]) if last else {},
            "straggler": dict(last["straggler"]) if last else {},
            "events_total": totals,
        }
        return doc, records

    def snapshot(self) -> dict:
        """The /debug/vars "hostcorr" block: O(1) occupancy + verdict."""
        with self._lock:
            return {
                "cycles": self._cycles,
                "records": len(self._ring),
                "ring_capacity": self._ring.maxlen,
                "available": bool(
                    self._last and self._last["host"]["available"]
                ),
                "groups": (
                    dict(self._last["host"]["groups"]) if self._last else {}
                ),
                "straggler": (
                    dict(self._last["straggler"]) if self._last else {}
                ),
                "events_total": dict(self._totals),
            }
