"""Host-correlation plane: non-instrumented straggler/stall attribution.

The sixth node-level plane (after anomaly, trace, resilience, guard,
analysis) and the first that explains *why* a device metric moved: a 1 Hz
procfs/cgroupfs sampler (cgroup PSI, per-pod sched delay, net/disk byte
rates, page-cache pressure — zero device queries, zero instrumentation)
time-aligned with each cycle's PollStats into a bounded correlation ring,
plus cross-signal detectors that join device and host series into a
per-slice straggler verdict with a cause label
(``device`` / ``host-cpu`` / ``host-mem`` / ``host-io`` / ``unknown``).

Surfaces: ``tpu_hostcorr_*`` / ``tpu_straggler_*`` families on the poll
page, ``GET /hostcorr`` (``?since=`` replay), host_straggler/host_stall
events on ``/anomalies``, smi/doctor lines, and fleet-tier rollups
(``tpu_fleet_stragglers``). Grounded in PAPERS.md arXiv 2510.16946
(host-side telemetry) and arXiv 2506.02007 (eACGM's non-instrumented
stance).
"""

from tpumon.hostcorr.detectors import (
    CAUSES,
    HOSTCORR_DETECTOR_NAMES,
    HostCorrThresholds,
    StragglerJudge,
    attribute_cause,
    hostcorr_detectors,
)
from tpumon.hostcorr.plane import HostCorrPlane
from tpumon.hostcorr.sampler import (
    PSI_RESOURCES,
    SIGNAL_GROUPS,
    HostSampler,
    HostSignals,
    parse_psi,
)

__all__ = [
    "CAUSES",
    "HOSTCORR_DETECTOR_NAMES",
    "PSI_RESOURCES",
    "SIGNAL_GROUPS",
    "HostCorrPlane",
    "HostCorrThresholds",
    "HostSampler",
    "HostSignals",
    "StragglerJudge",
    "attribute_cause",
    "hostcorr_detectors",
    "parse_psi",
]
