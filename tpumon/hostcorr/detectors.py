"""Cross-signal straggler/stall attribution over device + host series.

Two halves:

- :class:`StragglerJudge` — runs inside the HostCorrPlane's poll-cycle
  pass, joining this cycle's per-chip duty snapshot with the same
  cycle's :class:`~tpumon.hostcorr.sampler.HostSignals` into a per-slice
  straggler verdict: worst-chip vs median duty skew, attributed to a
  cause ∈ ``device`` / ``host-cpu`` / ``host-mem`` / ``host-io`` /
  ``unknown``. A straggler is a *consistent* laggard: the SAME chip must
  sit ``skew_warn_pct`` below the slice median for ``skew_cycles``
  consecutive polls while the median itself is busy — per-cycle jitter
  (the fake backend's noise, real MoE imbalance) never qualifies.

- :class:`HostStragglerDetector` / :class:`HostStallDetector` — streaming
  detectors with the tpumon.anomaly observe() contract, consuming the
  ``hostcorr`` block the plane injects into PollStats.snapshot. They ride
  the existing AnomalyEngine (onset/clear events, /anomalies replay,
  history windows) — the first detectors that explain *why* a device
  metric moved rather than just that it moved.

Cause attribution order: the strongest host signal above its threshold
wins (host evidence explains the symptom without blaming the device);
with no host signal, device-side evidence on the lagging chip (throttle)
reads ``device``; otherwise ``unknown``. When host signals are entirely
unavailable (no PSI kernel, no proc root) the verdict degrades to
device-only attribution instead of erroring — the graceful-degradation
contract of the plane.

Thresholds follow the AnomalyThresholds pattern: every field is a
``TPUMON_HOSTCORR_<FIELD>`` env var, malformed values keep the default,
re-parsed only when the env changes.
"""

from __future__ import annotations

import logging
import os
import statistics
from collections import deque
from dataclasses import dataclass, fields

from tpumon.health import CRIT, WARN

log = logging.getLogger(__name__)

#: Verdict cause labels, in exposition order.
CAUSES = ("device", "host-cpu", "host-mem", "host-io", "unknown")


@dataclass(frozen=True)
class HostCorrThresholds:
    """Cross-signal tuning, overridable per deployment via TPUMON_HOSTCORR_*."""

    #: Straggler onset: worst chip this many duty points below the slice
    #: median, for skew_cycles consecutive polls with the same worst chip,
    #: while the median is at least busy_duty_pct (idle slices have no
    #: stragglers). Clears at half the onset skew.
    skew_warn_pct: float = 20.0
    skew_cycles: float = 5.0
    busy_duty_pct: float = 25.0
    #: Step-skew onset (multi-host jobs): the slowest workload feed's
    #: step time this fraction above the feed median — the signal that
    #: catches a straggler HOST whose own chips are locally balanced
    #: (duty skew can't see it; the lagging host's steps can). Same
    #: streak/hysteresis discipline as duty skew; cause attribution
    #: unchanged.
    step_skew_ratio: float = 0.5
    #: Host-cause attribution thresholds: PSI avg10 shares (0-1) per
    #: resource, per-pod sched-delay share (delay s per wall s), and the
    #: page-reclaim scan rate backing host-mem.
    cpu_share: float = 0.10
    mem_share: float = 0.05
    io_share: float = 0.05
    sched_share: float = 0.10
    reclaim_pps: float = 1000.0
    #: Host-stall detector: duty collapsed below stall_duty_pct on every
    #: chip for stall_cycles polls while HBM stays flat (occupancy range
    #: under hbm_flat_delta) and a host signal is above threshold.
    stall_duty_pct: float = 1.0
    stall_cycles: float = 3.0
    hbm_flat_delta: float = 0.002

    @classmethod
    def from_env(cls, environ=None) -> "HostCorrThresholds":
        env = os.environ if environ is None else environ
        kwargs = {}
        for f in fields(cls):
            raw = env.get("TPUMON_HOSTCORR_" + f.name.upper())
            if raw is None:
                continue
            try:
                kwargs[f.name] = float(raw)
            except ValueError:
                log.warning(
                    "ignoring malformed TPUMON_HOSTCORR_%s=%r",
                    f.name.upper(), raw,
                )
        return cls(**kwargs)


#: (env-values key, parsed thresholds) — re-parse only when the env
#: changed, same cache shape as anomaly/health env_thresholds.
_env_cache: tuple | None = None


def env_thresholds() -> HostCorrThresholds:
    global _env_cache
    key = tuple(
        os.environ.get("TPUMON_HOSTCORR_" + f.name.upper())
        for f in fields(HostCorrThresholds)
    )
    if _env_cache is None or _env_cache[0] != key:
        _env_cache = (key, HostCorrThresholds.from_env())
    return _env_cache[1]


def score_host_signals(
    cpu: float, sched: float, mem: float, reclaim: float, io: float,
    t: HostCorrThresholds,
) -> list[tuple[float, float, str, str]]:
    """The single cause-scoring rule: ``(ratio, value, cause, signal)``
    candidates for every host signal at-or-above ITS OWN threshold,
    ratio = signal/threshold so a screaming PSI beats a marginal sched
    delay, reclaim counted toward host-mem. ``signal`` names the concrete
    series that won within the cause (``psi-cpu``/``sched``,
    ``psi-mem``/``reclaim``, ``psi-io``) and ``value`` is THAT signal's
    level — so event anchoring can point at the series that actually
    moved, not a flat sibling. Both :func:`attribute_cause` (/hostcorr
    verdicts) and ``HostStallDetector`` (/anomalies events) rank by
    ``max()`` of this list, so the two surfaces can never attribute the
    same host state to different causes.
    """
    def ratio(value: float, threshold: float) -> float:
        # A zero (or negative) threshold means "always attribute this
        # signal" — the >= gate above it is then unconditionally true —
        # so rank it as infinitely strong instead of dividing by zero
        # and killing the hostcorr stage every cycle.
        return value / threshold if threshold > 0 else float("inf")

    scores: list[tuple[float, float, str, str]] = []
    if cpu >= t.cpu_share or sched >= t.sched_share:
        scores.append(max(
            (ratio(cpu, t.cpu_share), cpu, "host-cpu", "psi-cpu"),
            (ratio(sched, t.sched_share), sched, "host-cpu", "sched"),
        ))
    if mem >= t.mem_share or reclaim >= t.reclaim_pps:
        scores.append(max(
            (ratio(mem, t.mem_share), mem, "host-mem", "psi-mem"),
            (ratio(reclaim, t.reclaim_pps), reclaim, "host-mem", "reclaim"),
        ))
    if io >= t.io_share:
        scores.append((ratio(io, t.io_share), io, "host-io", "psi-io"))
    return scores


def attribute_cause(host, evidence: dict, t: HostCorrThresholds) -> str:
    """Pick the cause label for an active straggler/stall.

    ``host`` is a HostSignals (or None); ``evidence`` carries the
    device-side booleans the plane extracted from the snapshot
    (``throttled`` on the worst chip). The strongest host signal above
    threshold wins (:func:`score_host_signals`); the absence of every
    host signal falls back to device evidence, then ``unknown``.
    """
    scores: list[tuple[float, float, str]] = []
    if host is not None and host.available:
        def share(resource: str) -> float:
            # Worst of node-scope and per-pod PSI: a single starving
            # pod on a big node barely moves the root share but its
            # own pod dir screams — per-pod is the sharper evidence,
            # node scope stays the cgroup-v1 fallback.
            return max(
                host.psi_share(resource) or 0.0,
                host.max_pod_psi_share(resource) or 0.0,
            )

        scores = score_host_signals(
            share("cpu"),
            host.max_sched_share() or 0.0,
            share("memory"),
            host.reclaim_pps or 0.0,
            share("io"),
            t,
        )
    if scores:
        return max(scores)[2]
    if evidence.get("throttled"):
        return "device"
    return "unknown"


class StragglerJudge:
    """Worst-chip-vs-median skew tracking; poll thread only.

    Two independent evidence streams feed one verdict: per-chip duty
    skew (this node's worst chip vs its slice median) and — when the
    lifecycle plane probes multiple hosts of one job — per-feed STEP
    skew (the slowest host's step time vs the feed median). Step skew
    catches the straggler shape duty skew is blind to: a lagging host
    whose own chips are perfectly balanced with each other. Either
    stream crossing its streak requirement activates the verdict; cause
    attribution (:func:`attribute_cause`) is identical for both.
    """

    def __init__(self) -> None:
        self._streak = 0
        self._last_worst: str | None = None
        self._step_streak = 0
        self._last_step_worst: str | None = None
        #: Per-stream hysteresis: each stream's clear-band applies only
        #: while THAT stream is active — a step episode must not halve
        #: the duty stream's onset bar (or a benign 12-pt duty skew
        #: could latch the verdict forever once anything else fired).
        self._duty_active = False
        self._step_active = False
        self._cause: str | None = None

    @property
    def _active(self) -> bool:
        return self._duty_active or self._step_active

    def judge(
        self,
        duties: dict[str, float],
        host,
        evidence: dict,
        t: HostCorrThresholds | None = None,
        step_seconds: dict[str, float] | None = None,
    ) -> dict:
        """One cycle's verdict. Returns a JSON-able dict; ``active`` only
        after a streak requirement is met, ``cause`` present while
        active. ``step_seconds`` (feed url -> step wall seconds, from
        the lifecycle block) arms the step-skew stream when ≥2 feeds
        report."""
        t = t if t is not None else env_thresholds()

        # -- duty-skew stream (per-chip, this node) -----------------------
        skew = med = None
        worst: str | None = None
        if len(duties) >= 2:
            med = statistics.median(duties.values())
            worst = min(duties, key=lambda c: duties[c])
            skew = med - duties[worst]
            clear_at = t.skew_warn_pct / 2.0
            threshold = clear_at if self._duty_active else t.skew_warn_pct
            candidate = med >= t.busy_duty_pct and skew >= threshold
            if candidate and worst == self._last_worst:
                self._streak += 1
            elif candidate:
                self._streak = 1
            else:
                self._streak = 0
            self._last_worst = worst if candidate else None
        else:
            self._streak = 0
            self._last_worst = None

        # -- step-skew stream (per-feed, multi-host jobs) -----------------
        step_ratio = None
        step_worst: str | None = None
        if step_seconds and len(step_seconds) >= 2:
            smed = statistics.median(step_seconds.values())
            step_worst = max(step_seconds, key=lambda u: step_seconds[u])
            if smed > 0:
                step_ratio = step_seconds[step_worst] / smed - 1.0
                s_threshold = (
                    t.step_skew_ratio / 2.0
                    if self._step_active
                    else t.step_skew_ratio
                )
                s_candidate = step_ratio >= s_threshold
                if s_candidate and step_worst == self._last_step_worst:
                    self._step_streak += 1
                elif s_candidate:
                    self._step_streak = 1
                else:
                    self._step_streak = 0
                self._last_step_worst = (
                    step_worst if s_candidate else None
                )
            else:
                self._step_streak = 0
                self._last_step_worst = None
        else:
            self._step_streak = 0
            self._last_step_worst = None

        need = max(1, int(t.skew_cycles))
        self._duty_active = self._streak >= need
        self._step_active = self._step_streak >= need
        if skew is None and self._step_streak < 1 and not self._active:
            # Neither stream has evidence (single chip, ≤1 feed): the
            # pre-step-skew idle shape, preserved for callers.
            self._cause = None
            return {"active": False, "skew_pct": None}
        verdict: dict = {
            "active": self._active,
            "skew_pct": skew,
            # The chip label names the accused: only duty evidence
            # accuses a chip. A step-skew-only episode is a lagging
            # HOST (named by step_feed) — blaming this node's
            # duty-worst chip would point the operator at an innocent
            # device with meaningless duty evidence. Inactive verdicts
            # keep naming the current worst chip (context, not blame).
            "chip": (
                ""
                if worst is None
                or (self._step_active and not self._duty_active)
                else worst
            ),
            "median_duty_pct": med,
            "streak": self._streak,
            "evidence": [
                name
                for name, on in (
                    ("duty", self._duty_active), ("step", self._step_active)
                )
                if on
            ],
        }
        if step_ratio is not None:
            verdict["step_skew_ratio"] = step_ratio
            verdict["step_feed"] = step_worst
            verdict["step_streak"] = self._step_streak
        if self._active:
            # Sticky per-episode attribution: during the hysteresis
            # decay tail the host is already calm, and recomputing
            # would erase the cause the onset established — the event
            # message, the events_total counter, and the fleet rollup
            # must all tell the same story. Only an "unknown" episode
            # may upgrade if evidence arrives later.
            cause = attribute_cause(host, evidence, t)
            if self._cause in (None, "unknown"):
                self._cause = cause
            verdict["cause"] = self._cause
        else:
            self._cause = None
        return verdict


class HostStragglerDetector:
    """AnomalyEngine adapter over the plane's straggler verdict.

    The judgment already happened in the plane (same cycle); this
    detector translates it into the engine's onset/clear event stream so
    stragglers get /anomalies replay, bounded rings, and the 1 Hz
    history window of ``tpu_straggler_skew_pct`` attached at onset.
    """

    name = "host_straggler"
    _family = "tpu_straggler_skew_pct"
    #: Step-skew-only episodes anchor their history window at the step
    #: series — their duty skew is meaningless context, not evidence.
    _step_family = "tpu_lifecycle_step_duration_seconds"

    def __init__(self) -> None:
        self._active = False
        self._chip = "?"
        #: ("duty", chip) or ("step", feed) latched at onset: the
        #: retained event and its clear must keep the onset's signal id
        #: and story even if the other evidence stream takes over
        #: mid-episode (a changing signal id would make the engine age
        #: the event out by absence instead of clearing it).
        self._latched: tuple[str, str] | None = None

    def reset(self) -> None:
        """Lifecycle-suppression re-baseline (the plane's judge resets
        itself when duty collapses — this clears the adapter's latch)."""
        self._active = False
        self._chip = "?"
        self._latched = None

    def observe(self, ts: float, snap: dict, t) -> list:
        from tpumon.anomaly.detectors import Reading

        verdict = (snap.get("hostcorr") or {}).get("straggler") or {}
        active = bool(verdict.get("active"))
        was = self._active
        self._active = active
        if not active and not was:
            return []
        hc = env_thresholds()
        skew = verdict.get("skew_pct") or 0.0
        cause = verdict.get("cause", "unknown")
        evidence = verdict.get("evidence") or []
        if active and self._latched is None:
            # Onset: latch which stream accused whom. Step-only
            # episodes blame the lagging HOST's feed — naming this
            # node's duty-worst chip would accuse an innocent device.
            if evidence == ["step"]:
                self._latched = (
                    "step", verdict.get("step_feed") or "?"
                )
            else:
                self._latched = ("duty", verdict.get("chip", "?"))
        kind, who = self._latched if self._latched is not None else (
            "duty", self._chip
        )
        self._chip = who
        if not active:
            self._latched = None
        if kind == "step":
            ratio = verdict.get("step_skew_ratio") or 0.0
            sev = CRIT if ratio >= 2.0 * hc.step_skew_ratio else WARN
            return [
                Reading(
                    f"feed:{who}",
                    active,
                    sev,
                    ratio,
                    f"workload feed {who} step time {ratio:.0%} above "
                    f"the job median for "
                    f"{verdict.get('step_streak', 0)} polls — lagging "
                    f"host, chips locally balanced — cause: {cause}",
                    self._step_family,
                    (),
                )
            ]
        sev = CRIT if skew >= 2.0 * hc.skew_warn_pct else WARN
        return [
            Reading(
                f"chip:{who}",
                active,
                sev,
                skew,
                f"chip {who} duty {skew:.0f} pts below the slice median "
                f"for {verdict.get('streak', 0)} polls — cause: {cause}",
                self._family,
                (),
            )
        ]


class HostStallDetector:
    """Whole-device stall with host-side pressure: "HBM flat + duty
    collapsed + host signal spiked" = the runtime is starved by the
    host, not wedged by the device (that pairing is queue_stall's).
    """

    name = "host_stall"

    #: signal -> (family, label_match builder) for event anchoring: the
    #: onset history window and the operator's first click must land on
    #: the series that actually spiked — a sched-triggered stall points
    #: at the pod's delay share, a reclaim-triggered one at the scan
    #: rate, never at a flat PSI sibling.
    _ANCHORS = {
        "psi-cpu": ("tpu_hostcorr_psi_share", "cpu"),
        "psi-mem": ("tpu_hostcorr_psi_share", "memory"),
        "psi-io": ("tpu_hostcorr_psi_share", "io"),
        "sched": ("tpu_hostcorr_sched_delay_share", None),
        "reclaim": ("tpu_hostcorr_reclaim_pages_per_second", None),
    }

    def __init__(self) -> None:
        self._streak = 0
        self._hbm: deque = deque(maxlen=16)
        self._active = False
        #: [value, cause, signal, pod] latched at onset: the retained
        #: event (message rewritten every active cycle) and its clear
        #: must keep telling the onset's story even if another signal
        #: overtakes mid-episode or the host is already calm on the
        #: clearing cycle. Only the latched signal's own level updates.
        self._latched: list | None = None

    def reset(self) -> None:
        """Lifecycle-suppression re-baseline: HBM flatness across a
        restore is the checkpoint's doing, not a stall's."""
        self._streak = 0
        self._hbm.clear()
        self._active = False
        self._latched = None

    def observe(self, ts: float, snap: dict, t) -> list:
        from tpumon.anomaly.detectors import Reading

        hc_block = snap.get("hostcorr") or {}
        host = hc_block.get("signals") or {}
        if not host.get("available"):
            # Graceful degradation: without host signals there is no
            # host-stall verdict to render (device-only detectors still
            # cover the wedged-runtime case).
            self._streak = 0
            if not self._active:
                return []
        hc = env_thresholds()
        duties = [
            row.get("duty_pct")
            for row in (snap.get("chips") or {}).values()
            if row.get("duty_pct") is not None
        ]
        ratios = [
            row["hbm_used"] / row["hbm_total"]
            for row in (snap.get("chips") or {}).values()
            if row.get("hbm_used") is not None and row.get("hbm_total")
        ]
        window = max(1, int(hc.stall_cycles))
        if self._hbm.maxlen < window:
            # The flatness window must hold stall_cycles samples — a
            # fixed cap would silently disable the detector for any
            # TPUMON_HOSTCORR_STALL_CYCLES above it.
            self._hbm = deque(self._hbm, maxlen=window)
        if ratios:
            self._hbm.append(sum(ratios) / len(ratios))
        collapsed = bool(duties) and max(duties) <= hc.stall_duty_pct
        recent = list(self._hbm)[-window:]
        hbm_flat = (
            len(recent) >= window
            and max(recent) - min(recent) <= hc.hbm_flat_delta
        )
        pressure = self._host_pressure(host, hc)
        stalled = collapsed and hbm_flat and pressure is not None
        self._streak = self._streak + 1 if stalled else 0
        was = self._active
        self._active = self._streak >= window
        if not self._active and not was:
            return []
        if self._active and not was:
            # pressure is non-None here: `stalled` (and so the streak
            # that just crossed the window) requires it.
            self._latched = list(pressure)
        elif (
            self._latched is not None
            and pressure is not None
            and pressure[2] == self._latched[2]
        ):
            self._latched[0] = pressure[0]
        value, cause, signal, pod = (
            self._latched if self._latched is not None
            else (0.0, "unknown", "psi-cpu", None)
        )
        if not self._active:
            self._latched = None
        family, resource = self._ANCHORS[signal]
        if signal == "sched":
            label_match = (("pod", pod),) if pod else ()
            evidence = (
                f"pod {pod} runnable-but-waiting {value:.0%} of wall time"
            )
        elif signal == "reclaim":
            label_match = ()
            evidence = f"page-reclaim scanning at {value:.0f} pages/s"
        else:
            label_match = (("resource", resource), ("kind", "some"))
            evidence = (
                f"{cause.removeprefix('host-')} pressure "
                f"({value:.0%} stall share)"
            )
        return [
            Reading(
                "node",
                self._active,
                WARN,
                value,
                f"device idle with flat HBM while the host shows "
                f"{evidence} for {self._streak} polls — "
                "host-side stall, not a device fault",
                family,
                label_match,
            )
        ]

    @staticmethod
    def _host_pressure(host: dict, hc: HostCorrThresholds):
        """(value, cause, signal, pod) for the strongest host signal
        above threshold, from the compact signals block the plane
        injects; None if calm. Scoring delegates to
        :func:`score_host_signals` — the one rule shared with
        attribute_cause. ``value`` is the winning signal's own level
        (PSI/sched shares as 0-1 fractions, reclaim as pages/s);
        ``pod`` names the worst-delayed pod when sched won, else None.
        """
        psi = host.get("psi") or {}
        pod_psi = host.get("pod_psi") or {}

        def share(resource: str) -> float:
            node = ((psi.get(resource) or {}).get("some") or {}).get(
                "share"
            ) or 0.0
            pods = [
                (rows.get(resource) or {}).get("share") or 0.0
                for rows in pod_psi.values()
            ]
            # Same worst-of-both rule as attribute_cause: the two
            # surfaces must score identical host state identically.
            return max([node, *pods]) if pods else node

        sched = {
            pod: row.get("share") or 0.0
            for pod, row in (host.get("sched") or {}).items()
        }
        scores = score_host_signals(
            share("cpu"),
            max(sched.values()) if sched else 0.0,
            share("memory"),
            host.get("reclaim_pps") or 0.0,
            share("io"),
            hc,
        )
        if not scores:
            return None
        _, value, cause, signal = max(scores)
        pod = None
        if signal == "sched" and sched:
            pod = max(sched, key=lambda p: sched[p])
        return value, cause, signal, pod


def hostcorr_detectors() -> list:
    """The cross-signal detector roster appended to the anomaly engine
    when the host-correlation plane is enabled."""
    return [HostStragglerDetector(), HostStallDetector()]


HOSTCORR_DETECTOR_NAMES: tuple[str, ...] = ("host_straggler", "host_stall")
