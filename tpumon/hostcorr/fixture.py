"""Hermetic fake procfs/cgroupfs tree + deterministic straggler backend.

Test/CI doubles for the host-correlation plane, mirroring the role
FakeTpuBackend plays for the device side:

- :class:`FakeProcTree` writes a directory tree shaped like the slice of
  ``/proc`` + ``/sys/fs/cgroup`` the sampler reads (PSI files, kubepods
  pids with schedstat, net/dev, diskstats, meminfo, vmstat), pointed at
  via ``TPUMON_HOSTCORR_PROC_ROOT`` / ``Config.hostcorr_proc_root`` — so
  hostcorr tests and CI run without a PSI-capable kernel, and chaos
  drills can script host pressure by rewriting files mid-run.
- :class:`StragglerBackend` wraps any device backend and pins one chip's
  duty cycle low (and optionally its throttle score high) — the
  deterministic device-side straggler the fixture tree's host pressure
  is correlated against. It also counts every ``sample()`` call, which
  is the "zero additional device queries per cycle" evidence in
  ``soak.py --straggler``.

Used by tests/conftest.py (the ``proc_tree`` fixture), tests/test_hostcorr.py,
and tools/soak.py; never imported by the exporter itself.
"""

from __future__ import annotations

import os
from collections import Counter


class FakeProcTree:
    """Writable fake proc root. All setters are idempotent full-file
    rewrites, so a mutator thread can script a scenario mid-run."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(os.path.join(root, "proc", "pressure"), exist_ok=True)
        os.makedirs(os.path.join(root, "proc", "net"), exist_ok=True)
        os.makedirs(os.path.join(root, "proc", "self"), exist_ok=True)
        os.makedirs(os.path.join(root, "sys", "fs", "cgroup"), exist_ok=True)
        # Healthy defaults: zero pressure, quiet counters, schedstat
        # support present (proc/self marks the kernel capability).
        for resource in ("cpu", "memory", "io"):
            self.set_pressure(resource)
        self._write("proc", "self", "schedstat", "0 0 0\n")
        self.set_net(0, 0)
        self.set_disk(0, 0)
        self.set_meminfo(cached_kb=1_000_000)
        self.set_vmstat(0)

    def _write(self, *parts_and_text: str) -> None:
        # Atomic temp+rename: a mutator thread scripts scenarios mid-run
        # while the sampler reads the same files, and a truncate-then-write
        # open() would hand the sampler empty/partial reads.
        *parts, text = parts_and_text
        path = os.path.join(self.root, *parts)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
        os.replace(tmp, path)

    # -- PSI ---------------------------------------------------------------

    def set_pressure(
        self,
        resource: str,
        some_avg10: float = 0.0,
        some_total_us: int = 0,
        full_avg10: float = 0.0,
        full_total_us: int = 0,
    ) -> None:
        text = (
            f"some avg10={some_avg10:.2f} avg60=0.00 avg300=0.00 "
            f"total={some_total_us}\n"
            f"full avg10={full_avg10:.2f} avg60=0.00 avg300=0.00 "
            f"total={full_total_us}\n"
        )
        self._write("proc", "pressure", resource, text)
        self._write("sys", "fs", "cgroup", f"{resource}.pressure", text)

    def remove_pressure(self) -> None:
        """Simulate a pre-PSI kernel (the graceful-degradation path)."""
        for resource in ("cpu", "memory", "io"):
            for path in (
                os.path.join(self.root, "proc", "pressure", resource),
                os.path.join(
                    self.root, "sys", "fs", "cgroup", f"{resource}.pressure"
                ),
            ):
                if os.path.exists(path):
                    os.remove(path)

    # -- pods / schedstat --------------------------------------------------

    def add_pod(
        self, uid: str, pid: int, run_delay_ns: int = 0,
        driver: str = "systemd",
    ) -> None:
        """One kubepods process: cgroup membership + schedstat. ``driver``
        picks the cgroup-path shape: ``systemd`` (…pod<uid>.slice, the
        kubeadm default) or ``cgroupfs`` (/kubepods/burstable/pod<uid>/,
        where the QoS class is its own path segment)."""
        if driver == "cgroupfs":
            line = f"0::/kubepods/burstable/pod{uid}/abc123\n"
        else:
            line = (
                "0::/kubepods.slice/kubepods-burstable.slice/"
                f"kubepods-burstable-pod{uid.replace('-', '_')}.slice/"
                "cri-containerd-abc123.scope\n"
            )
        self._write("proc", str(pid), "cgroup", line)
        self.set_pod_delay(pid, run_delay_ns)

    def set_pod_pressure(
        self,
        uid: str,
        resource: str,
        some_avg10: float = 0.0,
        some_total_us: int = 0,
        driver: str = "systemd",
    ) -> None:
        """The pod cgroup dir's own ``<resource>.pressure`` file (per-pod
        PSI); ``driver`` must match the shape ``add_pod`` wrote."""
        text = (
            f"some avg10={some_avg10:.2f} avg60=0.00 avg300=0.00 "
            f"total={some_total_us}\n"
            f"full avg10=0.00 avg60=0.00 avg300=0.00 total=0\n"
        )
        self._write(
            "sys", "fs", "cgroup", *self._pod_dir_parts(uid, driver),
            f"{resource}.pressure", text,
        )

    @staticmethod
    def _pod_dir_parts(uid: str, driver: str) -> tuple[str, ...]:
        if driver == "cgroupfs":
            return ("kubepods", "burstable", f"pod{uid}")
        return (
            "kubepods.slice",
            "kubepods-burstable.slice",
            f"kubepods-burstable-pod{uid.replace('-', '_')}.slice",
        )

    def remove_pod(self, pid: int) -> None:
        """The pod's process is gone (pod deleted / job finished)."""
        import shutil

        shutil.rmtree(
            os.path.join(self.root, "proc", str(pid)), ignore_errors=True
        )

    def set_pod_delay(self, pid: int, run_delay_ns: int) -> None:
        self._write(
            "proc", str(pid), "schedstat", f"123456 {run_delay_ns} 42\n"
        )

    def remove_schedstat(self) -> None:
        """Simulate a kernel without CONFIG_SCHED_INFO."""
        for entry in os.listdir(os.path.join(self.root, "proc")):
            path = os.path.join(self.root, "proc", entry, "schedstat")
            if os.path.exists(path):
                os.remove(path)

    # -- counters ----------------------------------------------------------

    def set_net(
        self, rx_bytes: int, tx_bytes: int,
        extra_ifaces: tuple = (),
    ) -> None:
        """``extra_ifaces``: (name, rx, tx) rows appended after eth0 —
        for exercising the virtual-interface exclusion."""
        lines = [
            "Inter-|   Receive                |  Transmit\n",
            " face |bytes packets errs drop fifo frame compressed "
            "multicast|bytes packets errs drop fifo colls carrier "
            "compressed\n",
            "    lo: 9999 9 0 0 0 0 0 0 9999 9 0 0 0 0 0 0\n",
            f"  eth0: {rx_bytes} 1 0 0 0 0 0 0 {tx_bytes} 1 0 0 0 0 0 0\n",
        ]
        for name, rx, tx in extra_ifaces:
            lines.append(
                f"  {name}: {rx} 1 0 0 0 0 0 0 {tx} 1 0 0 0 0 0 0\n"
            )
        self._write("proc", "net", "dev", "".join(lines))

    def set_disk(
        self, read_sectors: int, write_sectors: int,
        extra_devices: tuple = (),
    ) -> None:
        """``extra_devices``: (name, read_sectors, write_sectors) rows —
        for exercising the stacked-device (dm-*/md*) exclusion."""
        lines = [
            f"   8       0 sda 10 0 {read_sectors} 5 10 0 "
            f"{write_sectors} 5 0 10 10\n",
            "   8       1 sda1 10 0 999999 5 10 0 999999 5 0 10 10\n",
            "   7       0 loop0 10 0 999999 5 10 0 999999 5 0 10 10\n",
        ]
        for name, rd, wr in extra_devices:
            lines.append(
                f" 253       0 {name} 10 0 {rd} 5 10 0 {wr} 5 0 10 10\n"
            )
        self._write("proc", "diskstats", "".join(lines))

    def set_meminfo(self, cached_kb: int, dirty_kb: int = 0) -> None:
        self._write(
            "proc", "meminfo",
            "MemTotal:       16000000 kB\n"
            "MemAvailable:    8000000 kB\n"
            f"Cached:         {cached_kb} kB\n"
            f"Dirty:          {dirty_kb} kB\n",
        )

    def set_vmstat(self, pgscan_kswapd: int, pgscan_direct: int = 0) -> None:
        self._write(
            "proc", "vmstat",
            f"pgscan_kswapd {pgscan_kswapd}\n"
            f"pgscan_direct {pgscan_direct}\n"
            "pgsteal_kswapd 0\n",
        )


class StragglerBackend:
    """Wraps a device backend; pins one chip slow (and optionally
    throttled) while counting every device query."""

    def __init__(self, inner) -> None:
        self._inner = inner
        #: Chip index pinned to lag_duty (None = pass-through).
        self.lag_chip: int | None = None
        self.lag_duty = 3.0
        self.busy_duty = 75.0
        #: Chip index reporting a hard throttle score (device evidence).
        self.throttle_chip: int | None = None
        #: metric name -> sample() call count (query-budget evidence).
        self.calls: Counter = Counter()

    @property
    def name(self) -> str:
        return self._inner.name

    def sample(self, metric: str):
        from tpumon.backends.base import RawMetric

        self.calls[metric] += 1
        raw = self._inner.sample(metric)
        chips = len(raw.data)
        if metric == "duty_cycle_pct" and self.lag_chip is not None and chips:
            data = tuple(
                f"{self.lag_duty if i == self.lag_chip else self.busy_duty:.2f}"
                for i in range(chips)
            )
            return RawMetric(metric, data)
        if (
            metric == "tpu_throttle_score"
            and self.throttle_chip is not None
            and chips
        ):
            data = tuple(
                "8" if i == self.throttle_chip else "0" for i in range(chips)
            )
            return RawMetric(metric, data)
        return raw

    def __getattr__(self, attr):
        return getattr(self._inner, attr)
