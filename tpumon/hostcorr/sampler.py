"""Non-instrumented host-signal sampling from procfs/cgroupfs.

The host-correlation plane (ROADMAP item 3; PAPERS.md "Host-Side
Telemetry for Performance Diagnosis" arXiv 2510.16946, eACGM arXiv
2506.02007) reads ONLY kernel-exported files — no ptrace, no eBPF
programs, no agent inside the workload, and critically **zero device
queries**: every signal here comes from ``/proc`` and
``/sys/fs/cgroup``, sampled once per poll cycle on the poller thread.

Signal groups, each independently degradable (older kernels without PSI,
disarmed cgroup controllers, non-Linux test hosts):

- ``psi``   — cgroup-v2 pressure-stall information for cpu/memory/io
  (``/sys/fs/cgroup/*.pressure`` at the root cgroup, falling back to
  ``/proc/pressure/*``): the kernel's own "how much wall time did tasks
  lose waiting for this resource" accounting.
- ``sched`` — per-pod scheduler run delay from ``/proc/<pid>/schedstat``
  (field 2: ns spent runnable-but-not-running), with pids grouped into
  pods by the kubepods cgroup path in ``/proc/<pid>/cgroup`` — the
  pod→pid mapping the attribution plane's kubelet view cannot provide
  (the pod-resources API names pods, not processes).
- ``net``   — interface byte counters from ``/proc/net/dev`` (lo and
  virtual veth/bridge/tunnel interfaces excluded), as rx/tx rates.
- ``disk``  — physical whole-device sector counters from
  ``/proc/diskstats`` (partitions and dm/md stacked devices excluded),
  as read/write byte rates.
- ``vm``    — page-cache occupancy from ``/proc/meminfo`` and reclaim
  scan activity (``pgscan_kswapd + pgscan_direct``) from
  ``/proc/vmstat`` — the page-cache-pressure signal.

Every path is rooted at ``TPUMON_HOSTCORR_PROC_ROOT`` so tests and CI
run against a hermetic fixture tree (tpumon/hostcorr/fixture.py) instead
of requiring a PSI-capable kernel.

Rates are deltas between consecutive samples; the first cycle has no
delta and reports ``None`` (absent-not-zero, the repo-wide stance).
"""

from __future__ import annotations

import logging
import os
import re
import time
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

#: PSI resources sampled, in exposition order.
PSI_RESOURCES = ("cpu", "memory", "io")

#: Signal-group names (the `signal` label of tpu_hostcorr_signal_available).
SIGNAL_GROUPS = ("psi", "sched", "net", "disk", "vm")

#: kubepods pod-UID extraction from a /proc/<pid>/cgroup line. Matches
#: both the systemd-driver shape (kubepods-burstable-pod3b4f_12ab.slice,
#: underscores for dashes) and the cgroupfs-driver shape, where the QoS
#: class is its OWN path segment between kubepods and the pod dir
#: (/kubepods/burstable/pod3b4f-12ab/...; guaranteed pods sit directly
#: under /kubepods/).
_POD_RE = re.compile(
    r"kubepods[^/]*(?:/(?:burstable|besteffort))?"
    r"[/-]pod([0-9a-fA-F][0-9a-fA-F_-]{7,})"
)

#: Physical whole-device names in /proc/diskstats. Partitions are
#: excluded so bytes are not double-counted — and so are stacked devices
#: (dm-*, md*): an LVM/dm-crypt write increments BOTH the dm row and the
#: backing sda/nvme row, so counting only the physical layer keeps one
#: payload byte one accounted byte. loop/ram/zram excluded as
#: non-storage.
_DISK_RE = re.compile(
    r"^(?:sd[a-z]+|hd[a-z]+|vd[a-z]+|xvd[a-z]+|nvme\d+n\d+|mmcblk\d+)$"
)

#: Virtual interfaces excluded from /proc/net/dev rates: pod traffic
#: traverses the NIC *and* the CNI bridge *and* a veth pair, so counting
#: them all would report 2-3x the real wire rate (node-exporter's
#: default device exclusion, same motivation).
#: bond/team masters are excluded too: the master row re-reports every
#: byte already counted on its physical slave rows.
_VIRTUAL_IF_RE = re.compile(
    r"^(?:lo|veth.*|docker.*|br-.*|cni.*|flannel.*|cali.*|tunl.*"
    r"|virbr.*|kube-.*|dummy.*|tap.*|vxlan.*|gre.*|nodelocaldns"
    r"|bond.*|team.*)$"
)

_SECTOR_BYTES = 512.0


def _pod_cgroup_dir(cgroup_text: str) -> str | None:
    """The cgroup-v2 pod DIRECTORY (relative path under /sys/fs/cgroup)
    from a /proc/<pid>/cgroup file: the ``0::<path>`` line's path cut
    just past the pod segment — ``.../kubepods-besteffort-pod<uid>.slice``
    (systemd driver) or ``.../kubepods/burstable/pod<uid>`` (cgroupfs).
    None when no v2 line carries a kubepods pod segment."""
    for line in cgroup_text.splitlines():
        if not line.startswith("0::"):
            continue
        path = line[3:].strip()
        m = _POD_RE.search(path)
        if m is None:
            continue
        cut = path.find("/", m.end(1))
        pod_path = path if cut < 0 else path[:cut]
        return pod_path.lstrip("/")
    return None


@dataclass
class HostSignals:
    """One cycle's host-side sample, time-aligned with PollStats.

    ``psi[resource][kind]`` carries ``share`` (avg10 as a 0-1 fraction)
    and ``stall_s`` (cumulative stall seconds). ``sched[pod]`` carries
    ``delay_s`` (cumulative run-delay seconds accumulated since plane
    start) and ``share`` (delay seconds per wall second over the last
    cycle; ``None`` on the first observation). Rate fields are ``None``
    until a previous sample exists.
    """

    ts: float = 0.0
    available: bool = False
    groups: dict = field(default_factory=dict)  # group -> bool
    psi: dict = field(default_factory=dict)
    #: pod uid -> {resource: {share, stall_s}} from the kubepods pod
    #: dir's OWN *.pressure files ('some' kind) — names WHICH pod is
    #: starving where node-scope PSI only says that one is. Empty on
    #: cgroup-v1 nodes (node scope is the fallback).
    pod_psi: dict = field(default_factory=dict)
    sched: dict = field(default_factory=dict)
    net_bps: dict = field(default_factory=dict)  # dir -> rate | None
    disk_bps: dict = field(default_factory=dict)
    page_cache_bytes: float | None = None
    dirty_bytes: float | None = None
    reclaim_pps: float | None = None

    def psi_share(self, resource: str, kind: str = "some") -> float | None:
        row = (self.psi.get(resource) or {}).get(kind)
        return None if row is None else row.get("share")

    def max_pod_psi_share(self, resource: str) -> float | None:
        """Worst per-pod PSI 'some' share for one resource (None when
        no pod dir carries pressure files)."""
        shares = [
            row[resource]["share"]
            for row in self.pod_psi.values()
            if resource in row
        ]
        return max(shares) if shares else None

    def max_sched_share(self) -> float | None:
        shares = [
            row["share"] for row in self.sched.values()
            if row.get("share") is not None
        ]
        return max(shares) if shares else None

    def to_dict(self) -> dict:
        return {
            "ts": self.ts,
            "available": self.available,
            "groups": dict(self.groups),
            "psi": {
                res: {kind: dict(row) for kind, row in kinds.items()}
                for res, kinds in self.psi.items()
            },
            "pod_psi": {
                pod: {res: dict(row) for res, row in rows.items()}
                for pod, rows in self.pod_psi.items()
            },
            "sched": {pod: dict(row) for pod, row in self.sched.items()},
            "net_bps": dict(self.net_bps),
            "disk_bps": dict(self.disk_bps),
            "page_cache_bytes": self.page_cache_bytes,
            "dirty_bytes": self.dirty_bytes,
            "reclaim_pps": self.reclaim_pps,
        }


def parse_psi(text: str) -> dict:
    """``some avg10=1.23 ... total=456`` lines → {kind: {avg10, total_us}}.

    Malformed lines are skipped (a truncated read must degrade to fewer
    kinds, not a dead sampler).
    """
    out: dict[str, dict[str, float]] = {}
    for line in text.splitlines():
        parts = line.split()
        if not parts or parts[0] not in ("some", "full"):
            continue
        row: dict[str, float] = {}
        for tok in parts[1:]:
            key, _, val = tok.partition("=")
            try:
                row[key] = float(val)
            except ValueError:
                continue
        if "avg10" in row and "total" in row:
            out[parts[0]] = {"avg10": row["avg10"], "total_us": row["total"]}
    return out


class HostSampler:
    """Reads the host-signal files and folds deltas into rates.

    Runs ONLY on the poller thread (the plane publishes results under its
    own lock), so no locking here. Every group degrades independently:
    an unreadable file marks its group unavailable for the cycle and the
    sampler keeps going.
    """

    #: Cycles between full /proc scans rebuilding the pod→pid map; the
    #: per-cycle cost between refreshes is one schedstat read per known
    #: pod process, not a full process-table walk.
    MAP_REFRESH_CYCLES = 15

    #: Pod cardinality bound (a node hosts tens of pods, not thousands;
    #: a runaway kubepods tree must not explode series — the guard
    #: plane's governor is the backstop, this is the sane default).
    MAX_PODS = 64

    def __init__(self, proc_root: str = "") -> None:
        self.proc_root = proc_root or ""
        self._cycles = 0
        #: resource -> resolved PSI path parts (or None = absent); probed
        #: on the refresh cadence, read directly between refreshes so a
        #: cycle costs one open per resource, not two.
        self._psi_paths: dict[str, tuple[str, ...] | None] = {}
        #: Cached "kernel exposes schedstat" probe (refresh cadence).
        self._schedstat_ok = False
        #: pod uid -> {pid: last run-delay ns} (delta accumulation).
        self._pod_pids: dict[str, dict[int, float]] = {}
        #: pod uid -> cgroup-v2 pod dir (relative path under
        #: /sys/fs/cgroup), discovered on the refresh scan; the pod
        #: dir's own *.pressure files back per-pod PSI.
        self._pod_dirs: dict[str, str] = {}
        #: pod uid -> cumulative delay seconds since sampler start.
        self._pod_delay_s: dict[str, float] = {}
        #: Previous cumulative counters for rate computation.
        self._prev_ts: float | None = None
        self._prev_net: dict[str, float] | None = None
        self._prev_disk: dict[str, float] | None = None
        self._prev_reclaim: float | None = None
        #: pod uid -> previous cumulative delay (share computation).
        self._prev_pod_delay: dict[str, float] = {}

    # -- path helpers ------------------------------------------------------

    def _path(self, *parts: str) -> str:
        return os.path.join(self.proc_root or "/", *parts)

    def _read(self, *parts: str) -> str | None:
        try:
            with open(self._path(*parts), encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    # -- the per-cycle entry point ----------------------------------------

    def sample(self, now: float | None = None) -> HostSignals:
        ts = time.time() if now is None else now
        sig = HostSignals(ts=ts)
        dt = None
        if self._prev_ts is not None:
            dt = ts - self._prev_ts
            if dt <= 0:
                dt = None  # clock went sideways: skip rates this cycle

        # Path discovery (which PSI source exists, schedstat support,
        # the pod→pid map) is re-probed on the refresh cadence only; the
        # steady-state cycle pays one read per live signal, keeping the
        # stage's poll-budget cost flat (measured: the every-cycle /proc
        # walk alone cost ~1 ms on a 2-core sandbox kernel).
        refresh = self._cycles % self.MAP_REFRESH_CYCLES == 0
        if refresh:
            self._probe_paths()
            # Pods gone from the kubepods tree leave the exposition too
            # (absent-not-zero): without this, every pod ever seen keeps
            # a frozen counter + zero-share gauge for the exporter's
            # lifetime — unbounded label cardinality under pod churn.
            for uid in list(self._pod_delay_s):
                if uid not in self._pod_pids:
                    del self._pod_delay_s[uid]
                    self._prev_pod_delay.pop(uid, None)

        node_psi = self._sample_psi(sig)
        pod_psi = self._sample_pod_psi(sig)
        # The psi GROUP is available when either scope reads: a node
        # whose root files are missing but whose pod dirs carry
        # pressure still has the signal (and vice versa on cgroup v1).
        sig.groups["psi"] = node_psi or pod_psi
        sig.groups["sched"] = self._sample_sched(sig, dt)
        sig.groups["net"] = self._sample_net(sig, dt)
        sig.groups["disk"] = self._sample_disk(sig, dt)
        sig.groups["vm"] = self._sample_vm(sig, dt)
        sig.available = any(sig.groups.values())
        self._prev_ts = ts
        self._cycles += 1
        return sig

    def _probe_paths(self) -> None:
        """Refresh-cadence discovery: PSI source per resource, schedstat
        support, and the pod→pid map."""
        for resource in PSI_RESOURCES:
            for parts in (
                ("sys", "fs", "cgroup", f"{resource}.pressure"),
                ("proc", "pressure", resource),
            ):
                if os.path.exists(self._path(*parts)):
                    self._psi_paths[resource] = parts
                    break
            else:
                self._psi_paths[resource] = None
        self._schedstat_ok = os.path.exists(
            self._path("proc", "self", "schedstat")
        )
        self._pod_pids = self._scan_pod_pids()

    # -- PSI ---------------------------------------------------------------

    def _sample_psi(self, sig: HostSignals) -> bool:
        found = False
        for resource in PSI_RESOURCES:
            parts = self._psi_paths.get(resource)
            if parts is None:
                continue
            text = self._read(*parts)
            if text is None:
                continue
            rows = parse_psi(text)
            if not rows:
                continue
            found = True
            sig.psi[resource] = {
                kind: {
                    "share": row["avg10"] / 100.0,
                    "stall_s": row["total_us"] / 1e6,
                }
                for kind, row in rows.items()
            }
        return found

    # -- per-pod scheduler delay ------------------------------------------

    def _scan_pod_pids(self) -> dict[str, dict[int, float]]:
        """Walk /proc once, grouping pids by kubepods pod UID. Preserves
        each surviving pid's last-seen delay so deltas stay continuous
        across refreshes. Also harvests each pod's cgroup-v2 dir (the
        ``0::`` line's path up to the pod segment) into ``_pod_dirs``
        for the per-pod PSI reads."""
        proc_dir = self._path("proc")
        try:
            entries = os.listdir(proc_dir)
        except OSError:
            return {}
        pods: dict[str, dict[int, float]] = {}
        dirs: dict[str, str] = {}
        for entry in entries:
            if not entry.isdigit():
                continue
            pid = int(entry)
            cgroup = self._read("proc", entry, "cgroup")
            if cgroup is None:
                continue  # pid exited between listdir and read: routine
            m = _POD_RE.search(cgroup)
            if m is None:
                continue
            uid = m.group(1).replace("_", "-")
            if uid not in pods and len(pods) >= self.MAX_PODS:
                continue
            prev = self._pod_pids.get(uid, {}).get(pid)
            pods.setdefault(uid, {})[pid] = prev if prev is not None else -1.0
            if uid not in dirs:
                pod_dir = _pod_cgroup_dir(cgroup)
                if pod_dir is not None:
                    dirs[uid] = pod_dir
        self._pod_dirs = dirs
        return pods

    # -- per-pod PSI -------------------------------------------------------

    def _sample_pod_psi(self, sig: HostSignals) -> bool:
        """Per-pod PSI from the kubepods pod dirs' own *.pressure files
        ('some' kind only — the per-pod question is "is THIS pod
        stalled", not the full/partial split). cgroup-v1 nodes have no
        per-pod pressure files and simply contribute nothing; the
        node-scope PSI stays the fallback signal."""
        found = False
        for uid, pod_dir in self._pod_dirs.items():
            rows: dict[str, dict[str, float]] = {}
            for resource in PSI_RESOURCES:
                text = self._read(
                    "sys", "fs", "cgroup", *pod_dir.split("/"),
                    f"{resource}.pressure",
                )
                if text is None:
                    continue
                parsed = parse_psi(text).get("some")
                if parsed is None:
                    continue
                rows[resource] = {
                    "share": parsed["avg10"] / 100.0,
                    "stall_s": parsed["total_us"] / 1e6,
                }
            if rows:
                sig.pod_psi[uid] = rows
                found = True
        return found

    def _read_run_delay_ns(self, pid: int) -> float | None:
        text = self._read("proc", str(pid), "schedstat")
        if text is None:
            return None
        parts = text.split()
        if len(parts) < 2:
            return None
        try:
            return float(parts[1])
        except ValueError:
            return None

    def _sample_sched(self, sig: HostSignals, dt: float | None) -> bool:
        any_read = False
        for uid, pids in self._pod_pids.items():
            for pid in list(pids):
                delay_ns = self._read_run_delay_ns(pid)
                if delay_ns is None:
                    del pids[pid]  # pid died; its past deltas are kept
                    continue
                any_read = True
                last = pids[pid]
                if last >= 0 and delay_ns >= last:
                    self._pod_delay_s[uid] = (
                        self._pod_delay_s.get(uid, 0.0)
                        + (delay_ns - last) / 1e9
                    )
                else:
                    # First observation of this pid (or a counter reset):
                    # establish the baseline, contribute no delta.
                    self._pod_delay_s.setdefault(uid, 0.0)
                pids[pid] = delay_ns
        if self._pod_pids:
            available = any_read  # pods exist; did any schedstat read?
        else:
            # No kubepods on this host (bare exporters, CI): the sched
            # signal is available iff the kernel exposes schedstat at all
            # (cached probe, refresh cadence).
            available = self._schedstat_ok
        if not available:
            # Absent-not-zero: with schedstat unreadable this cycle the
            # remembered per-pod totals are zombies — exporting them
            # would show frozen counters and zero shares under a group
            # flagged unavailable.
            return False
        for uid, total_s in self._pod_delay_s.items():
            prev = self._prev_pod_delay.get(uid)
            share = None
            if dt is not None and prev is not None:
                share = max(0.0, (total_s - prev) / dt)
            sig.sched[uid] = {"delay_s": total_s, "share": share}
        self._prev_pod_delay = dict(self._pod_delay_s)
        return True

    # -- /proc/net/dev byte rates -----------------------------------------

    def _sample_net(self, sig: HostSignals, dt: float | None) -> bool:
        text = self._read("proc", "net", "dev")
        if text is None:
            return False
        rx = tx = 0.0
        seen = False
        for line in text.splitlines():
            name, sep, rest = line.partition(":")
            if not sep:
                continue
            iface = name.strip()
            if _VIRTUAL_IF_RE.match(iface):
                continue
            parts = rest.split()
            if len(parts) < 9:
                continue
            try:
                rx += float(parts[0])
                tx += float(parts[8])
            except ValueError:
                continue
            seen = True
        if not seen:
            return False
        cur = {"rx": rx, "tx": tx}
        if dt is not None and self._prev_net is not None:
            for direction in ("rx", "tx"):
                delta = cur[direction] - self._prev_net[direction]
                sig.net_bps[direction] = max(0.0, delta / dt)
        else:
            sig.net_bps = {"rx": None, "tx": None}
        self._prev_net = cur
        return True

    # -- /proc/diskstats byte rates ---------------------------------------

    def _sample_disk(self, sig: HostSignals, dt: float | None) -> bool:
        text = self._read("proc", "diskstats")
        if text is None:
            return False
        read_b = write_b = 0.0
        seen = False
        for line in text.splitlines():
            parts = line.split()
            if len(parts) < 10 or not _DISK_RE.match(parts[2]):
                continue
            try:
                read_b += float(parts[5]) * _SECTOR_BYTES
                write_b += float(parts[9]) * _SECTOR_BYTES
            except ValueError:
                continue
            seen = True
        if not seen:
            # All-stacked storage (dm-only LVM/dm-crypt roots): a flat-0
            # rate here would read "disk quiet" during a real IO storm —
            # absent-not-zero, same as _sample_net with no physical NIC.
            return False
        cur = {"read": read_b, "write": write_b}
        if dt is not None and self._prev_disk is not None:
            for direction in ("read", "write"):
                delta = cur[direction] - self._prev_disk[direction]
                sig.disk_bps[direction] = max(0.0, delta / dt)
        else:
            sig.disk_bps = {"read": None, "write": None}
        self._prev_disk = cur
        return True

    # -- page cache + reclaim ---------------------------------------------

    def _sample_vm(self, sig: HostSignals, dt: float | None) -> bool:
        meminfo = self._read("proc", "meminfo")
        vmstat = self._read("proc", "vmstat")
        found = False
        if meminfo is not None:
            for line in meminfo.splitlines():
                parts = line.split()
                if len(parts) < 2:
                    continue
                if parts[0] == "Cached:":
                    try:
                        sig.page_cache_bytes = float(parts[1]) * 1024.0
                        found = True
                    except ValueError:
                        pass
                elif parts[0] == "Dirty:":
                    try:
                        sig.dirty_bytes = float(parts[1]) * 1024.0
                    except ValueError:
                        pass
        if vmstat is not None:
            scans = 0.0
            seen = False
            for line in vmstat.splitlines():
                parts = line.split()
                if len(parts) == 2 and parts[0] in (
                    "pgscan_kswapd", "pgscan_direct"
                ):
                    try:
                        scans += float(parts[1])
                        seen = True
                    except ValueError:
                        continue
            if seen:
                found = True
                if dt is not None and self._prev_reclaim is not None:
                    sig.reclaim_pps = max(
                        0.0, (scans - self._prev_reclaim) / dt
                    )
                self._prev_reclaim = scans
        return found
