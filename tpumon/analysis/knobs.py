"""Rule ``knob-drift``: every TPUMON_* env knob exists everywhere it must.

Knob discovery is AST-resolution, not grep, because the repo composes
env names three ways a text search cannot see as knobs:

- ``config.py`` reads ``_env("PORT")`` — the ``TPUMON_`` prefix lives in
  ``ENV_PREFIX`` and is applied inside ``_env``;
- ``health.py``/``detectors.py`` read
  ``os.environ.get("TPUMON_HEALTH_" + f.name.upper())`` inside a loop
  over ``dataclasses.fields(cls)`` — one PREFIX yields one knob per
  dataclass field;
- everything else reads literal ``os.environ.get("TPUMON_X")``.

Checks (violation keys in parentheses):

- ``undocumented:<knob>`` — knob not mentioned anywhere in docs/ or
  README.md. Operators discover knobs from OPERATIONS.md's reference
  table, not from the source.
- ``chart-missing:<knob>`` — a Config-field knob (the curated operator
  surface) not settable via the Helm chart's daemonset template or
  values.yaml. Prefix-family knobs (TPUMON_HEALTH_*/TPUMON_ANOMALY_*)
  are exempt: charts pass them through ``exporter.extraEnv``.
- ``chart-unknown:<knob>`` / ``deploy-unknown:<knob>`` — an env name a
  daemonset manifest sets that no code reads (the dcgm-exporter
  field-metadata drift class: a renamed knob silently stops applying).
- ``deploy-chart-drift:<knob>`` — a knob the kustomize daemonset pins
  that the chart daemonset cannot set: the two install paths disagree
  about the tunable surface.
- ``config-unwired:<field>`` — a Config dataclass field never resolved
  from the environment in ``from_env`` (a new field that silently
  ignores its documented env var).
"""

from __future__ import annotations

import ast
import re

from tpumon.analysis.core import (
    Project,
    Violation,
    call_name,
    dotted,
    str_const,
)

RULE = "knob-drift"

_CONFIG_PATH = "tpumon/config.py"
_ENV_FNS = ("_env", "_env_int", "_env_float", "_env_bool")
_ENV_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
_MANIFEST_ENV_RE = re.compile(r"-\s+name:\s+(TPUMON_[A-Z0-9_]+)")

#: Docs a knob may be documented in.
_DOC_PATHS = (
    "docs/OPERATIONS.md",
    "docs/ARCHITECTURE.md",
    "docs/METRICS.md",
    "docs/MIGRATING.md",
    "README.md",
)


def _env_prefix(src) -> str:
    """Resolve ``ENV_PREFIX`` from the module's assignments."""
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "ENV_PREFIX":
                    value = str_const(node.value)
                    if value:
                        return value
    return "TPUMON_"


def _dataclass_fields(tree: ast.Module) -> dict[str, list[str]]:
    """class name -> ordered field names (AnnAssign targets)."""
    out: dict[str, list[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            fields = [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
            out[node.name] = fields
    return out


def _fields_loop_class(node: ast.AST, src) -> str | None:
    """When ``node`` sits inside ``for f in fields(X)`` (statement or
    comprehension), return ``X``'s class name — ``cls``/``self`` resolve
    to the enclosing class."""
    def _fields_arg(it: ast.AST) -> str | None:
        if isinstance(it, ast.Call) and call_name(it) == "fields" and it.args:
            arg = it.args[0]
            if isinstance(arg, ast.Name):
                return arg.id
        return None

    chain = [node, *src.ancestors(node)]
    for anc in chain:
        target: str | None = None
        if isinstance(anc, ast.For):
            target = _fields_arg(anc.iter)
        elif isinstance(anc, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            for comp in anc.generators:
                target = target or _fields_arg(comp.iter)
        if target is None:
            continue
        if target in ("cls", "self"):
            for outer in src.ancestors(anc):
                if isinstance(outer, ast.ClassDef):
                    return outer.name
            return None
        return target
    return None


def discover_knobs(project: Project) -> dict[str, list[tuple[str, int]]]:
    """knob -> [(path, line), ...] across every resolution style."""
    knobs: dict[str, list[tuple[str, int]]] = {}

    def add(name: str, path: str, line: int) -> None:
        knobs.setdefault(name, []).append((path, line))

    for path, src in sorted(project.python.items()):
        prefix = _env_prefix(src) if path == _CONFIG_PATH else "TPUMON_"
        classes = _dataclass_fields(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            # Style 1: config.py _env*("NAME") — prefix applied inside.
            if path == _CONFIG_PATH and name in _ENV_FNS and node.args:
                lit = str_const(node.args[0])
                if lit and _ENV_NAME_RE.match(lit):
                    add(prefix + lit, path, node.lineno)
                continue
            # Styles 2+3 ride os.environ.get / env.get / os.getenv.
            if name not in ("get", "getenv"):
                continue
            base = dotted(node.func)
            if base not in ("os.environ.get", "env.get", "os.getenv", "environ.get"):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            lit = str_const(arg)
            if lit and lit.startswith("TPUMON_"):
                add(lit, path, node.lineno)
                continue
            # Style 2: "TPUMON_X_" + f.name.upper() inside fields(C) loop.
            if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
                left = str_const(arg.left)
                if left and left.startswith("TPUMON_"):
                    cls = _fields_loop_class(node, src)
                    for fld in classes.get(cls or "", []):
                        add(left + fld.upper(), path, node.lineno)
    return knobs


def _config_surface(project: Project) -> tuple[list[str], set[str]]:
    """(Config field names in order, env names resolved in from_env)."""
    src = project.py(_CONFIG_PATH)
    if src is None:
        return [], set()
    fields: list[str] = []
    wired: set[str] = set()
    prefix = _env_prefix(src)
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            fields = [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
            for fn in node.body:
                if isinstance(fn, ast.FunctionDef) and fn.name == "from_env":
                    for call in ast.walk(fn):
                        if (
                            isinstance(call, ast.Call)
                            and call_name(call) in _ENV_FNS
                            and call.args
                        ):
                            lit = str_const(call.args[0])
                            if lit:
                                wired.add(prefix + lit)
    return fields, wired


def _manifest_env(text: str) -> set[str]:
    return set(_MANIFEST_ENV_RE.findall(text))


def check(project: Project) -> list[Violation]:
    out: list[Violation] = []
    knobs = discover_knobs(project)
    fields, wired = _config_surface(project)
    # AST-resolved, same as _config_surface: if ENV_PREFIX is ever
    # renamed, both halves of the rule must move together.
    cfg_src = project.py(_CONFIG_PATH)
    prefix = _env_prefix(cfg_src) if cfg_src is not None else "TPUMON_"
    field_knobs = {prefix + f.upper(): f for f in fields}

    docs_blob = "\n".join(
        project.texts.get(p, "") for p in _DOC_PATHS
    )
    chart_blob = "\n".join(
        text for path, text in project.text_items(prefix="charts/")
        if path.endswith((".yaml", ".yml"))
    )
    # Same suffix coverage as chart_blob above: an env entry in a .yml
    # file must be visible to BOTH the presence and dead-name checks.
    chart_env: set[str] = set()
    deploy_env: set[str] = set()
    for path, text in project.texts.items():
        if not path.endswith((".yaml", ".yml")):
            continue
        if path.startswith("charts/"):
            chart_env |= _manifest_env(text)
        elif path.startswith("deploy/"):
            deploy_env |= _manifest_env(text)

    # A Config field implies an intended TPUMON_* knob even when (by
    # bug) it is not wired in from_env — include those in the universe
    # so the doc/chart checks still see them.
    universe: dict[str, tuple[str, int]] = {
        knob: sites[0] for knob, sites in knobs.items()
    }
    for knob in field_knobs:
        universe.setdefault(knob, (_CONFIG_PATH, 0))

    def present(knob: str, blob: str) -> bool:
        # Word-boundary match: TPUMON_TRACE must not be satisfied by
        # TPUMON_TRACE_RING (the prefix-knob blind spot of substring
        # search is exactly the drift class this rule exists to catch).
        return re.search(rf"\b{re.escape(knob)}\b", blob) is not None

    for knob in sorted(universe):
        path, line = universe[knob]
        if docs_blob and not present(knob, docs_blob):
            out.append(
                Violation(
                    RULE, f"undocumented:{knob}", path, line,
                    f"{knob} is read by {path} but documented nowhere in "
                    "docs/ or README.md (add it to the OPERATIONS.md "
                    "configuration reference)",
                )
            )
        if knob in field_knobs and chart_blob and not present(knob, chart_blob):
            out.append(
                Violation(
                    RULE, f"chart-missing:{knob}", path, line,
                    f"{knob} is a Config knob but the Helm chart cannot "
                    "set it (add an env entry to "
                    "charts/tpumon/templates/daemonset.yaml + values.yaml)",
                )
            )

    # Dead env names: a manifest sets a knob no code reads.
    for scope, env, manifest in (
        ("chart", chart_env, "charts/tpumon/templates/daemonset.yaml"),
        ("deploy", deploy_env, "deploy/daemonset.yaml"),
    ):
        for name in sorted(env - set(universe)):
            out.append(
                Violation(
                    RULE, f"{scope}-unknown:{name}", manifest, 0,
                    f"{manifest} sets {name} but no code reads it "
                    "(renamed or removed knob — the setting silently "
                    "stops applying)",
                )
            )

    # Kustomize pins a knob the chart cannot set at all.
    for name in sorted((deploy_env & set(universe)) - chart_env):
        out.append(
            Violation(
                RULE, f"deploy-chart-drift:{name}", "deploy/daemonset.yaml", 0,
                f"deploy/daemonset.yaml pins {name} but the chart "
                "daemonset has no matching env entry — the two install "
                "paths disagree on the tunable surface",
            )
        )

    # Config fields that silently ignore their env var.
    for knob, fld in sorted(field_knobs.items()):
        if wired and knob not in wired:
            out.append(
                Violation(
                    RULE, f"config-unwired:{fld}", _CONFIG_PATH, 0,
                    f"Config.{fld} is never resolved from {knob} in "
                    "Config.from_env — the documented env var is ignored",
                )
            )
    return out
