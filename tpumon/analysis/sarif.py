"""SARIF 2.1.0 serialization of an analyzer run.

One ``run`` per invocation; every violation becomes a ``result`` with a
``partialFingerprints.tpumonFingerprint`` equal to the baseline
fingerprint (``<rule> <key>``), so code-scanning UIs track findings
across commits exactly the way the baseline file does — by identity,
not position. Baselined violations are emitted as *suppressed* results
(kind ``external``) carrying their written justification: the burn-down
list stays visible in the scanning UI instead of vanishing.
"""

from __future__ import annotations

from tpumon.analysis.core import Violation

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

#: Rule id -> short description, mirrored from docs/INVARIANTS.md.
RULE_DESCRIPTIONS = {
    "knob-drift": "Every env knob is documented, charted, and defaulted",
    "family-drift": "Emitted ⊆ registered ⊆ documented metric families",
    "lock-discipline": "Annotated guarded-by attrs accessed under lock",
    "lock-order": "Lock acquisition order is acyclic",
    "deadline": "Blocking calls in the pipeline carry timeouts",
    "except-hygiene": "No blind excepts in the serving pipeline",
    "race": "Cross-thread stores share a lock (thread-role propagation)",
    "publish-discipline": (
        "Page-feeding state mutates on its publishing role, post-publish"
    ),
}


def _result(v: Violation, reason: str | None) -> dict:
    out = {
        "ruleId": v.rule,
        "level": "note" if reason is not None else "error",
        "message": {"text": v.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": v.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(v.line, 1)},
                }
            }
        ],
        "partialFingerprints": {"tpumonFingerprint": v.fingerprint},
    }
    if reason is not None:
        out["suppressions"] = [
            {
                "kind": "external",
                "justification": reason
                or "baselined without a written reason",
            }
        ]
    return out


def to_sarif(
    violations: list[Violation],
    baseline: dict[str, str],
    version: str,
) -> dict:
    """The SARIF log document (a plain dict; caller serializes)."""
    rules = sorted({v.rule for v in violations} | set(RULE_DESCRIPTIONS))
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpumon-invariants",
                        "version": version,
                        "informationUri": (
                            "https://github.com/tpumon/tpumon"
                            "/blob/main/docs/INVARIANTS.md"
                        ),
                        "rules": [
                            {
                                "id": rule,
                                "shortDescription": {
                                    "text": RULE_DESCRIPTIONS.get(
                                        rule, rule
                                    )
                                },
                            }
                            for rule in rules
                        ],
                    }
                },
                "results": [
                    _result(v, baseline.get(v.fingerprint))
                    for v in violations
                ],
                "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            }
        ],
    }
