"""Whole-package call graph for the interprocedural concurrency rules.

The lock rules are lexical: they see one class at a time and cannot know
which *threads* reach a method. This module builds the missing layer — a
best-effort static call graph over every analyzed module:

- **module-level resolution**: ``foo()`` binds to the module's own
  ``def foo``, a ``from tpumon.x import foo`` target, or an imported
  ``mod.foo``;
- **method dispatch via self-type inference**: ``self.stripes.put()``
  resolves through ``self.stripes = StripeSet(...)`` in ``__init__`` to
  ``StripeSet.put``; plain ``self._collect_cycle()`` binds inside the
  enclosing class (base classes included); local variables typed by
  construction (``feed = NodeFeed(...)``) resolve the same way;
- **callable references**: ``functools.partial(fn, ...)`` peels to
  ``fn``; a ``lambda`` resolves to the targets its body calls — the two
  forms thread spawn sites actually use.

The graph is deliberately an under-approximation where it cannot prove a
binding (an unresolvable call contributes no edge) and an
over-approximation across same-named classes only when the name is
globally unique — both are the right polarity for the race rules, which
must not convict on guessed edges.

Qualnames are ``<path>::<dotted scope>`` (``tpumon/fleet/server.py::
FleetServer._collect_cycle``); nested defs chain through their owners
(``...::FleetServer._with_fleet_endpoint.app``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tpumon.analysis.core import Project, call_name

_MAX_RESOLVE_DEPTH = 6


def _module_path(project: Project, dotted_mod: str) -> str | None:
    """``tpumon.fleet.server`` -> ``tpumon/fleet/server.py`` when the
    module is part of the analyzed tree."""
    base = dotted_mod.replace(".", "/")
    for cand in (base + ".py", base + "/__init__.py"):
        if cand in project.python:
            return cand
    return None


@dataclass
class ClassInfo:
    """One class definition: its methods, base names, and the inferred
    types of ``self.<attr>`` instance attributes."""

    name: str
    path: str
    qual: str  # qualname prefix for methods: "<path>::<Outer.Cls>"
    node: ast.ClassDef
    methods: dict[str, str] = field(default_factory=dict)
    bases: list[str] = field(default_factory=list)
    #: self.attr -> ClassInfo candidates (from `self.attr = Cls(...)`).
    attr_types: dict[str, list["ClassInfo"]] = field(default_factory=dict)


@dataclass
class FuncInfo:
    """One (possibly nested) function definition."""

    qualname: str
    path: str
    name: str
    node: ast.AST
    cls: ClassInfo | None = None  # nearest enclosing class (for `self`)


class CallGraph:
    """functions + direct-call edges over the whole project."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.functions: dict[str, FuncInfo] = {}
        #: caller qualname -> callee qualnames (direct calls only).
        self.edges: dict[str, set[str]] = {}
        #: id(ast def node) -> FuncInfo (rules look functions up by node).
        self.by_node: dict[int, FuncInfo] = {}
        #: path -> top-level function name -> qualname.
        self._module_funcs: dict[str, dict[str, str]] = {}
        #: path -> class name (dotted for nested) -> ClassInfo.
        self._module_classes: dict[str, dict[str, ClassInfo]] = {}
        #: class name -> every ClassInfo with that name (global fallback).
        self._classes_by_name: dict[str, list[ClassInfo]] = {}
        #: path -> local name -> (module_path, attr-or-None).
        self._imports: dict[str, dict[str, tuple[str, str | None]]] = {}
        self._local_types_cache: dict[int, dict[str, list[ClassInfo]]] = {}
        self._build()

    # -- indexing ----------------------------------------------------------

    def _build(self) -> None:
        for path, src in sorted(self.project.python.items()):
            self._index_imports(path, src)
            self._index_scopes(path, src.tree, chain=[], cls=None)
        # Second pass: attr types need every class registered first.
        for classes in self._module_classes.values():
            for ci in classes.values():
                self._infer_attr_types(ci)
        # Third pass: edges need attr types.
        for path, src in sorted(self.project.python.items()):
            self._index_edges(path, src)

    def _index_imports(self, path: str, src) -> None:
        imp: dict[str, tuple[str, str | None]] = {}
        pkg_parts = path.split("/")[:-1]
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mp = _module_path(self.project, alias.name)
                    if mp is None:
                        continue
                    if alias.asname:
                        imp[alias.asname] = (mp, None)
                    # `import a.b.c` binds `a`; attribute-chain walks
                    # through packages are resolved lazily in _resolve.
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + ([mod] if mod else []))
                if not mod:
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    sub = _module_path(self.project, mod + "." + alias.name)
                    if sub is not None:
                        imp[local] = (sub, None)
                        continue
                    mp = _module_path(self.project, mod)
                    if mp is not None:
                        imp[local] = (mp, alias.name)
        self._imports[path] = imp

    def _index_scopes(
        self, path: str, node: ast.AST, chain: list[str], cls: ClassInfo | None
    ) -> None:
        in_class_body = isinstance(node, ast.ClassDef)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                name = ".".join(chain + [child.name])
                ci = ClassInfo(
                    name=child.name,
                    path=path,
                    qual=f"{path}::{name}",
                    node=child,
                    bases=[
                        b.id if isinstance(b, ast.Name) else getattr(b, "attr", "")
                        for b in child.bases
                    ],
                )
                self._module_classes.setdefault(path, {})[name] = ci
                self._classes_by_name.setdefault(child.name, []).append(ci)
                self._index_scopes(path, child, chain + [child.name], ci)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{path}::{'.'.join(chain + [child.name])}"
                fi = FuncInfo(qn, path, child.name, child, cls)
                self.functions[qn] = fi
                self.by_node[id(child)] = fi
                if in_class_body and cls is not None:
                    cls.methods.setdefault(child.name, qn)
                if not chain:
                    self._module_funcs.setdefault(path, {})[child.name] = qn
                self._index_scopes(path, child, chain + [child.name], cls)
            else:
                self._index_scopes(path, child, chain, cls)

    def _infer_attr_types(self, ci: ClassInfo) -> None:
        for node in ast.walk(ci.node):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            ctor = self._class_for_expr(ci.path, node.value.func)
            if ctor is None:
                continue
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    ci.attr_types.setdefault(tgt.attr, []).append(ctor)

    def _index_edges(self, path: str, src) -> None:
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            fi = self.by_node.get(id(node))
            if fi is None:
                continue
            out = self.edges.setdefault(fi.qualname, set())
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                owner = self._owning_function(src, call)
                if owner is not node:
                    continue
                out |= self.resolve(path, fi, call.func)

    @staticmethod
    def _owning_function(src, node: ast.AST) -> ast.AST | None:
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    # -- lookups -----------------------------------------------------------

    def _lookup_class(self, path: str, name: str) -> ClassInfo | None:
        local = self._module_classes.get(path, {}).get(name)
        if local is not None:
            return local
        imp = self._imports.get(path, {}).get(name)
        if imp is not None and imp[1] is not None:
            target = self._module_classes.get(imp[0], {}).get(imp[1])
            if target is not None:
                return target
        # Globally-unique name: safe enough for ctor typing.
        cands = self._classes_by_name.get(name, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def _class_for_expr(self, path: str, expr: ast.AST) -> ClassInfo | None:
        if isinstance(expr, ast.Name):
            return self._lookup_class(path, expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            imp = self._imports.get(path, {}).get(expr.value.id)
            if imp is not None and imp[1] is None:
                return self._module_classes.get(imp[0], {}).get(expr.attr)
        return None

    def _methods_on(self, ci: ClassInfo, name: str, depth: int = 0) -> set[str]:
        if depth > _MAX_RESOLVE_DEPTH:
            return set()
        qn = ci.methods.get(name)
        if qn is not None:
            return {qn}
        out: set[str] = set()
        for base in ci.bases:
            bi = self._lookup_class(ci.path, base)
            if bi is not None and bi is not ci:
                out |= self._methods_on(bi, name, depth + 1)
        return out

    def _class_init(self, ci: ClassInfo) -> set[str]:
        return self._methods_on(ci, "__init__")

    def local_types(self, fi: FuncInfo) -> dict[str, list[ClassInfo]]:
        """Local-variable construction types: ``feed = NodeFeed(...)``."""
        cached = self._local_types_cache.get(id(fi.node))
        if cached is not None:
            return cached
        out: dict[str, list[ClassInfo]] = {}
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            ctor = self._class_for_expr(fi.path, node.value.func)
            if ctor is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.setdefault(tgt.id, []).append(ctor)
        self._local_types_cache[id(fi.node)] = out
        return out

    # -- resolution --------------------------------------------------------

    def resolve(
        self, path: str, fi: FuncInfo | None, expr: ast.AST, depth: int = 0
    ) -> set[str]:
        """Qualnames a callable expression can bind to (possibly empty)."""
        if depth > _MAX_RESOLVE_DEPTH:
            return set()
        if isinstance(expr, ast.Lambda):
            # A lambda runs its body: resolve the calls it makes.
            out: set[str] = set()
            for node in ast.walk(expr.body):
                if isinstance(node, ast.Call):
                    out |= self.resolve(path, fi, node.func, depth + 1)
            return out
        if isinstance(expr, ast.Call):
            # functools.partial(fn, ...) as a callable reference.
            if call_name(expr) == "partial" and expr.args:
                return self.resolve(path, fi, expr.args[0], depth + 1)
            return set()
        if isinstance(expr, ast.Name):
            if fi is not None:
                # A nested `def` in the same function shadows the module
                # scope (`self._executor.submit(save)` after `def save`).
                for node in ast.walk(fi.node):
                    if (
                        isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and node.name == expr.id
                        and id(node) in self.by_node
                    ):
                        return {self.by_node[id(node)].qualname}
            return self._resolve_name(path, expr.id)
        if isinstance(expr, ast.Attribute):
            return self._resolve_attribute(path, fi, expr, depth)
        return set()

    def _resolve_name(self, path: str, name: str) -> set[str]:
        qn = self._module_funcs.get(path, {}).get(name)
        if qn is not None:
            return {qn}
        ci = self._module_classes.get(path, {}).get(name)
        if ci is not None:
            return self._class_init(ci)
        imp = self._imports.get(path, {}).get(name)
        if imp is not None:
            mp, attr = imp
            if attr is None:
                return set()  # bare module reference is not callable
            qn = self._module_funcs.get(mp, {}).get(attr)
            if qn is not None:
                return {qn}
            ci = self._module_classes.get(mp, {}).get(attr)
            if ci is not None:
                return self._class_init(ci)
        return set()

    def _resolve_attribute(
        self, path: str, fi: FuncInfo | None, expr: ast.Attribute, depth: int
    ) -> set[str]:
        base, meth = expr.value, expr.attr
        cls = fi.cls if fi is not None else None
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                got = self._methods_on(cls, meth)
                if got:
                    return got
                # self.attr as a callable: a constructed attribute whose
                # class defines __call__ would land here; out of scope.
                return set()
            if fi is not None:
                for ci in self.local_types(fi).get(base.id, []):
                    got = self._methods_on(ci, meth)
                    if got:
                        return got
            imp = self._imports.get(path, {}).get(base.id)
            if imp is not None and imp[1] is None:
                mp = imp[0]
                qn = self._module_funcs.get(mp, {}).get(meth)
                if qn is not None:
                    return {qn}
                ci = self._module_classes.get(mp, {}).get(meth)
                if ci is not None:
                    return self._class_init(ci)
            ci = self._lookup_class(path, base.id)
            if ci is not None:
                return self._methods_on(ci, meth)
            return set()
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
            and cls is not None
        ):
            out: set[str] = set()
            for ci in cls.attr_types.get(base.attr, []):
                out |= self._methods_on(ci, meth)
            return out
        if (
            isinstance(base, ast.Call)
            and isinstance(base.func, ast.Name)
            and base.func.id == "super"
            and cls is not None
        ):
            out = set()
            for bname in cls.bases:
                bi = self._lookup_class(cls.path, bname)
                if bi is not None:
                    out |= self._methods_on(bi, meth, depth + 1)
            return out
        return set()


def build(project: Project) -> CallGraph:
    return CallGraph(project)
