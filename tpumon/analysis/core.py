"""Analyzer infrastructure: project model, annotations, rule registry.

The analyzer parses every tracked source file ONCE into a
:class:`SourceFile` (AST + per-line comment map) and hands the whole
:class:`Project` to each rule. Rules are pure functions
``(Project) -> list[Violation]`` registered in :data:`RULES`; fixture
tests build synthetic projects with :meth:`Project.from_files`, so every
rule is provable on a known-bad snippet without touching the repo.

Violations carry a line number for humans but fingerprint WITHOUT it
(``rule key``): the baseline file must survive aggressive refactoring,
so keys are stable identities (knob name, family name,
``Class.attr:method``) — never positions.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass, field


ANALYZER_VERSION = "1.1.0"

#: Source trees the analyzer never parses (generated / vendored).
_EXCLUDED_PARTS = ("_native/build",)
_EXCLUDED_FILES = ("tpumon/attribution/podresources_pb2.py",)

#: Non-python files the rules cross-check (text-scanned, never parsed as
#: YAML — helm templates are not valid YAML).
_TEXT_GLOBS = (
    ("charts", (".yaml", ".yml", ".json")),
    ("deploy", (".yaml", ".yml", ".json")),
    ("dashboards", (".json",)),
    ("docs", (".md",)),
)
_TEXT_FILES = ("README.md",)

#: In-source suppression: ``# tpumon-invariants: disable=<rule>`` on the
#: offending line (reason after an em dash or extra text encouraged).
_DISABLE_MARK = "tpumon-invariants: disable="


@dataclass(frozen=True)
class Violation:
    """One invariant breach. ``key`` is the line-number-free identity the
    baseline file matches on; ``fingerprint`` is what gets written."""

    rule: str
    key: str
    path: str
    line: int
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.rule} {self.key}"


class SourceFile:
    """One parsed python file: AST, comment map, and parent links."""

    def __init__(self, path: str, text: str) -> None:
        self.path = path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        #: line number -> comment text (without the leading ``#``).
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string.lstrip("#").strip()
        except tokenize.TokenError:
            pass
        #: child AST node -> parent (ancestor walks for with/except scopes).
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def comment_near(self, line: int) -> str:
        """The comment on ``line``, or on the line above (annotations may
        not fit beside long statements)."""
        return self.comments.get(line) or self.comments.get(line - 1) or ""

    def disabled_rules(self, line: int) -> set[str]:
        """Rules suppressed in-source at ``line``."""
        out: set[str] = set()
        for text in (self.comments.get(line, ""), self.comments.get(line - 1, "")):
            if _DISABLE_MARK in text:
                spec = text.split(_DISABLE_MARK, 1)[1]
                out.add(spec.split()[0].rstrip(","))
        return out


@dataclass
class Project:
    """Everything the rules look at, loaded once."""

    root: str
    python: dict[str, SourceFile] = field(default_factory=dict)
    texts: dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_files(cls, files: dict[str, str], root: str = "<memory>") -> "Project":
        """Synthetic project for fixture tests: ``.py`` entries are
        parsed, everything else lands in ``texts``."""
        proj = cls(root=root)
        for path, text in files.items():
            if path.endswith(".py"):
                proj.python[path] = SourceFile(path, text)
            else:
                proj.texts[path] = text
        return proj

    def py(self, path: str) -> SourceFile | None:
        return self.python.get(path)

    def text_items(self, prefix: str = "", suffix: str = ""):
        for path, text in sorted(self.texts.items()):
            if path.startswith(prefix) and path.endswith(suffix):
                yield path, text


def load_project(root: str) -> Project:
    """Parse the repo at ``root`` (a checkout or an installed tree)."""
    proj = Project(root=root)
    pkg = os.path.join(root, "tpumon")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            if rel in _EXCLUDED_FILES or any(p in rel for p in _EXCLUDED_PARTS):
                continue
            with open(full, encoding="utf-8") as fh:
                proj.python[rel] = SourceFile(rel, fh.read())
    for sub, suffixes in _TEXT_GLOBS:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames.sort()
            for name in sorted(filenames):
                if not name.endswith(suffixes):
                    continue
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full, encoding="utf-8") as fh:
                    proj.texts[rel] = fh.read()
    for name in _TEXT_FILES:
        full = os.path.join(root, name)
        if os.path.isfile(full):
            with open(full, encoding="utf-8") as fh:
                proj.texts[name] = fh.read()
    return proj


# -- rule registry ---------------------------------------------------------

def all_rules() -> dict:
    """name -> rule callable. Imported lazily so ``tpumon.analysis`` stays
    importable (for /debug/vars' baseline count) without pulling every
    rule module."""
    from tpumon.analysis import (
        deadlines,
        exceptions,
        families_rule,
        knobs,
        locks,
        races,
    )

    return {
        "knob-drift": knobs.check,
        "family-drift": families_rule.check,
        "lock-discipline": locks.check_discipline,
        "lock-order": locks.check_order,
        "deadline": deadlines.check,
        "except-hygiene": exceptions.check,
        "race": races.check_races,
        "publish-discipline": races.check_publish,
    }


def run_rules(
    project: Project, rules: list[str] | None = None
) -> list[Violation]:
    """Run the named rules (default: all) and apply in-source
    ``# tpumon-invariants: disable=`` suppressions."""
    registry = all_rules()
    names = rules if rules else sorted(registry)
    out: list[Violation] = []
    for name in names:
        if name not in registry:
            raise KeyError(
                f"unknown rule {name!r}; known: {', '.join(sorted(registry))}"
            )
        for v in registry[name](project):
            src = project.py(v.path)
            if src is not None and v.rule in src.disabled_rules(v.line):
                continue
            out.append(v)
    out.sort(key=lambda v: (v.rule, v.path, v.line, v.key))
    return out


# -- shared AST helpers ----------------------------------------------------

#: The poll/serving pipeline modules every path-scoped rule starts from.
#: Rules extend this explicitly (deadline adds CLI/tools surfaces,
#: except-hygiene adds the parser) so a new plane added here is picked
#: up by ALL of them at once — the same drift class the analyzer hunts.
PIPELINE_PREFIXES = (
    "tpumon/exporter/",
    "tpumon/backends/",
    "tpumon/attribution/",
    "tpumon/resilience/",
    "tpumon/guard/",
    "tpumon/trace/",
    "tpumon/anomaly/",
    "tpumon/fleet/",
    "tpumon/hostcorr/",
    "tpumon/lifecycle/",
    "tpumon/energy/",
    "tpumon/ledger/",
    "tpumon/actuate/",
    "tpumon/chaos/",
    "tpumon/history.py",
)


def iter_functions(tree: ast.Module):
    """Every (possibly nested) function definition in the module."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node

def str_const(node: ast.AST) -> str | None:
    """The literal value when ``node`` is a string constant."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def call_name(node: ast.Call) -> str:
    """Trailing name of the called object: ``a.b.c()`` -> ``c``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted(node: ast.AST) -> str:
    """Best-effort dotted source form: ``self._lock``, ``os.environ``."""
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def has_kwarg(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords)
