"""Rule ``deadline``: blocking calls on serving/poll paths carry bounds.

A DaemonSet exporter has two latency contracts — the 1 Hz poll budget
and the scrape p99 — and one unbounded blocking call anywhere on either
path converts a misbehaving peer into a wedged exporter. The rule flags,
in the scoped modules:

- ``<thread>.join()`` with no arguments — ``Thread.join`` blocks
  forever (``str.join`` always takes an argument, so no-arg ``join`` is
  reliably a thread);
- ``<event>.wait()`` / ``<future>.result()`` / ``<queue>.get()`` with
  no arguments — unbounded waits;
- ``subprocess.run/call/check_call/check_output`` and
  ``Popen.communicate/wait`` without ``timeout=``;
- ``urllib.request.urlopen`` without ``timeout=``;
- raw socket ops (``recv``/``recv_into``/``accept``/``connect``/
  ``sendall``) in a function that never arms a deadline — no
  ``settimeout``/``setdefaulttimeout`` call and no
  ``create_connection(..., timeout=...)`` in the same function.

A call that is *deliberately* unbounded (a lifecycle wait another
thread is guaranteed to wake) declares why on its line:

    stop.wait()  # deadline: woken by SIGTERM handler — lifecycle, not a request path

Violation keys: ``<path>:<function>:<callee>``.
"""

from __future__ import annotations

import ast

from tpumon.analysis.core import (
    PIPELINE_PREFIXES,
    Project,
    Violation,
    call_name,
    dotted,
    has_kwarg,
    iter_functions,
)

RULE = "deadline"

_DEADLINE_MARK = "deadline:"

#: The shared pipeline scope plus the operator-facing surfaces whose
#: hangs strand a human (CLI tools, discovery, smi). Workload/bench
#: tooling is driver-side and excluded.
SCOPE_PREFIXES = PIPELINE_PREFIXES + (
    "tpumon/discovery/",
    "tpumon/tools/",
    "tpumon/smi.py",
)

_NOARG_BLOCKERS = {
    "join": "Thread.join() without a timeout blocks forever",
    "wait": "Event.wait() without a timeout blocks forever",
    "result": "Future.result() without a timeout blocks forever",
}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "communicate"}
_SOCKET_OPS = {"recv", "recv_into", "accept", "connect", "sendall", "makefile"}
_ARMING_CALLS = {"settimeout", "setdefaulttimeout", "create_connection"}


def _annotated(src, line: int) -> bool:
    return _DEADLINE_MARK in src.comment_near(line)


def _fn_arms_deadline(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("settimeout", "setdefaulttimeout"):
                # settimeout(None) DISABLES the timeout (the stdlib
                # fully-blocking idiom) — that arms nothing. A variable
                # argument is trusted (the _DeadlineReader pattern
                # re-arms with a computed remaining budget).
                if (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is None
                ):
                    continue
                return True
            if name == "create_connection" and (
                has_kwarg(node, "timeout") or len(node.args) >= 2
            ):
                return True
    return False


def _check_file(path: str, src, out: list[Violation]) -> None:
    reported: set[str] = set()

    def flag(fn_name: str, node: ast.Call, callee: str, why: str) -> None:
        key = f"{path}:{fn_name}:{callee}"
        if key in reported or _annotated(src, node.lineno):
            return
        reported.add(key)
        out.append(
            Violation(
                RULE, key, path, node.lineno,
                f"{why} (in {fn_name}); pass a timeout/deadline, or "
                "annotate the line `# deadline: <why unbounded is "
                "safe>`",
            )
        )

    for fn in iter_functions(src.tree):
        arms = None  # lazy: only computed when a socket op appears
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # Calls belong to the innermost def (it gets its own visit).
            owner = next(
                (
                    a for a in src.ancestors(node)
                    if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                ),
                None,
            )
            if owner is not fn:
                continue
            name = call_name(node)
            full = dotted(node.func)
            if (
                name in _NOARG_BLOCKERS
                and isinstance(node.func, ast.Attribute)
                and not node.args
                and not node.keywords
            ):
                flag(fn.name, node, name, _NOARG_BLOCKERS[name])
            elif name == "get" and isinstance(node.func, ast.Attribute):
                # queue.get() with neither timeout nor block=False.
                if (
                    not node.args
                    and not node.keywords
                    and full.endswith("queue.get")
                ):
                    flag(
                        fn.name, node, "queue.get",
                        "Queue.get() without a timeout blocks forever",
                    )
            elif name in _SUBPROCESS_FNS and (
                full.startswith("subprocess.")
                or name in ("communicate",)
            ):
                if not has_kwarg(node, "timeout"):
                    flag(
                        fn.name, node, f"subprocess.{name}",
                        f"{full or name}() without timeout= can hang "
                        "on a stuck child",
                    )
            elif name == "urlopen":
                if not has_kwarg(node, "timeout") and len(node.args) < 3:
                    flag(
                        fn.name, node, "urlopen",
                        "urlopen() without timeout= hangs on a "
                        "half-dead server",
                    )
            elif name in _SOCKET_OPS and isinstance(node.func, ast.Attribute):
                if arms is None:
                    arms = _fn_arms_deadline(fn)
                if not arms:
                    flag(
                        fn.name, node, name,
                        f"socket .{name}() in a function that never "
                        "arms a deadline (no settimeout/"
                        "create_connection(timeout=))",
                    )


def check(project: Project) -> list[Violation]:
    out: list[Violation] = []
    for path, src in sorted(project.python.items()):
        if path.startswith(SCOPE_PREFIXES):
            _check_file(path, src, out)
    return out
