"""Rule ``family-drift``: emitted ⊆ registered ⊆ documented, and every
PromQL expression references only registered families.

The registry (tpumon/families.py + schema.py + host.py + histograms.py)
is extracted by AST — not imported — so the analyzer runs on a bare
checkout and fixture tests can swap in synthetic registries.

Checks (violation keys):

- ``unregistered:<family>`` — a metric family constructed in code
  (``*MetricFamily("name", ...)``, ``Counter/Gauge/Histogram("name",
  ...)``) that the registry does not know. Counters are normalized to
  their ``_total`` exposition name first (prometheus_client appends it).
- ``undocumented:<family>`` — a registered family absent from
  docs/METRICS.md (the generated reference drifted).
- ``promql:<file>:<family>`` — a dashboard panel/annotation expr or a
  Prometheus alert rule references a family-shaped metric name the
  registry does not serve (family drift breaks dashboards silently —
  the exact dcgm-exporter failure class).
"""

from __future__ import annotations

import ast
import json
import re

from tpumon.analysis.core import Project, Violation, call_name, str_const

RULE = "family-drift"

_FAMILY_CTORS = {
    "GaugeMetricFamily",
    "CounterMetricFamily",
    "HistogramMetricFamily",
    "SummaryMetricFamily",
    "InfoMetricFamily",
}
_CLIENT_CTORS = {"Counter", "Gauge", "Histogram", "Summary", "Info"}
_COUNTER_CTORS = {"CounterMetricFamily", "Counter"}

#: Registry dict literals in tpumon/families.py and friends.
_REGISTRY_DICTS = {
    "IDENTITY_FAMILIES",
    "HEALTH_FAMILIES",
    "ANOMALY_FAMILIES",
    "HOSTCORR_FAMILIES",
    "LIFECYCLE_FAMILIES",
    "ENERGY_FAMILIES",
    "SELF_FAMILIES",
    "STEP_FAMILIES",
    "FLEET_FAMILIES",
    "LEDGER_FAMILIES",
    "ANALYTICS_FAMILIES",
    "ACTUATE_FAMILIES",
    "WORKLOAD_FAMILIES",
    "SERVE_FAMILIES",
    "HOST_FAMILIES",
}

#: Family-shaped metric tokens in PromQL — the same prefix net as
#: tests/test_dashboards.py (bare ``tpu_`` stays out: libtpu SOURCE
#: metric names appear in prose).
_METRIC_RE = re.compile(
    r"\b(?:(?:accelerator|exporter|collector|workload|host|tpu_anomaly"
    r"|tpu_hostcorr|tpu_straggler|tpu_lifecycle|tpu_step|tpu_serve"
    r"|tpu_energy|tpu_pod_energy|tpu_ledger|tpu_actuate|tpu_chaos"
    r"|tpu_fleet|tpumon_trace|tpumon_poll|tpumon_family|tpumon_breaker"
    r"|tpumon_retries|tpumon_watchdog|tpumon_guard|tpumon_shed"
    r"|tpumon_cardinality|tpumon_render|tpumon_exposition)_[a-z0-9_]+"
    r"|tpumon_up|tpumon_degraded)\b"
)

_EXPR_LINE_RE = re.compile(r"^\s*(?:expr|query)\s*:\s*(.*)$")

#: Modules whose metric constructions are checked against the registry.
_EMIT_PREFIXES = (
    "tpumon/exporter/",
    "tpumon/anomaly/",
    "tpumon/guard/",
    "tpumon/resilience/",
    "tpumon/attribution/",
    "tpumon/discovery/",
    "tpumon/fleet/",
    "tpumon/hostcorr/",
    "tpumon/lifecycle/",
    "tpumon/energy/",
    "tpumon/ledger/",
    "tpumon/workload/",
    "tpumon/actuate/",
    "tpumon/chaos/",
)


def _counter_name(name: str) -> str:
    return name if name.endswith("_total") else name + "_total"


def registered_families(project: Project) -> set[str]:
    """Registry extraction: dict-literal keys, FamilySpec family args,
    DISTRIBUTION_SOURCES family tuples."""
    names: set[str] = set()
    for path, src in project.python.items():
        for node in ast.walk(src.tree):
            targets: list[str] = []
            if isinstance(node, ast.Assign):
                targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                targets = [node.target.id]
            if targets and isinstance(node.value, ast.Dict):
                if not any(
                    t in _REGISTRY_DICTS or t == "DISTRIBUTION_SOURCES"
                    for t in targets
                ):
                    continue
                if any(t == "DISTRIBUTION_SOURCES" for t in targets):
                    # source -> (family, help, label): take tuple[0].
                    for value in node.value.values:
                        if isinstance(value, ast.Tuple) and value.elts:
                            fam = str_const(value.elts[0])
                            if fam:
                                names.add(fam)
                    continue
                for key in node.value.keys:
                    lit = str_const(key)
                    if lit:
                        names.add(lit)
            # FamilySpec("source", "family", ...) rows in schema.py.
            if isinstance(node, ast.Call) and call_name(node) == "FamilySpec":
                if len(node.args) >= 2:
                    fam = str_const(node.args[1])
                    if fam:
                        names.add(fam)
    return names


def emitted_families(project: Project) -> dict[str, list[tuple[str, int]]]:
    """family (exposition name) -> construction sites."""
    out: dict[str, list[tuple[str, int]]] = {}
    for path, src in sorted(project.python.items()):
        if not path.startswith(_EMIT_PREFIXES):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name not in (_FAMILY_CTORS | _CLIENT_CTORS) or not node.args:
                continue
            fam = str_const(node.args[0])
            if not fam:
                continue
            if name in _COUNTER_CTORS:
                fam = _counter_name(fam)
            out.setdefault(fam, []).append((path, node.lineno))
    return out


def _with_histogram_suffixes(names: set[str]) -> set[str]:
    """PromQL sees histogram families as _bucket/_sum/_count series."""
    hist = {n for n in names if n.endswith(("_seconds", "_percent"))}
    return names | {
        n + suffix for n in hist for suffix in ("_bucket", "_sum", "_count")
    }


def _dashboard_exprs(text: str):
    try:
        doc = json.loads(text)
    except ValueError:
        return
    stack = [doc]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            for key, value in node.items():
                if key == "expr" and isinstance(value, str):
                    yield value
                else:
                    stack.append(value)
        elif isinstance(node, list):
            stack.extend(node)


def _rule_exprs(text: str):
    """``expr:`` lines from prometheus-rules YAML (helm-templated copies
    are not valid YAML, so this is a line scan; multi-line ``|`` exprs
    yield their continuation lines too)."""
    lines = text.splitlines()
    for i, line in enumerate(lines):
        m = _EXPR_LINE_RE.match(line)
        if not m:
            continue
        value = m.group(1).strip()
        if value and not value.startswith(("|", ">")):
            yield value
            continue
        indent = len(line) - len(line.lstrip())
        for cont in lines[i + 1:]:
            if cont.strip() and (len(cont) - len(cont.lstrip())) <= indent:
                break
            yield cont


def check(project: Project) -> list[Violation]:
    out: list[Violation] = []
    registered = registered_families(project)
    if not registered:
        return out
    known = _with_histogram_suffixes(registered)

    for fam, sites in sorted(emitted_families(project).items()):
        if fam in registered:
            continue
        path, line = sites[0]
        out.append(
            Violation(
                RULE, f"unregistered:{fam}", path, line,
                f"{path} constructs metric family {fam!r} but it is not "
                "registered in tpumon/families.py (or schema/host/"
                "histogram registries) — docs, dashboards, and the "
                "drift tests cannot see it",
            )
        )

    metrics_doc = project.texts.get("docs/METRICS.md")
    if metrics_doc is not None:
        for fam in sorted(registered):
            if fam not in metrics_doc:
                out.append(
                    Violation(
                        RULE, f"undocumented:{fam}", "docs/METRICS.md", 0,
                        f"registered family {fam} is missing from "
                        "docs/METRICS.md (regenerate: python -m "
                        "tpumon.tools.gen_metrics_doc)",
                    )
                )

    promql: list[tuple[str, str]] = []
    for path, text in project.text_items(suffix=".json"):
        if "/dashboards/" in path or path.startswith("dashboards/"):
            promql.extend((path, e) for e in _dashboard_exprs(text))
    for path, text in project.texts.items():
        if "rules" in path and path.endswith((".yaml", ".yml")):
            promql.extend((path, e) for e in _rule_exprs(text))
    flagged: set[tuple[str, str]] = set()
    for path, expr in promql:
        for ref in _METRIC_RE.findall(expr):
            if ref in known or (path, ref) in flagged:
                continue
            flagged.add((path, ref))
            out.append(
                Violation(
                    RULE, f"promql:{path}:{ref}", path, 0,
                    f"{path} queries {ref!r} but no registered family "
                    "serves it — the panel/alert would silently show "
                    "nothing",
                )
            )
    return out
