"""Rules ``lock-discipline`` and ``lock-order``.

``lock-discipline`` enforces an annotation convention (the prometheus
client-library lock-bug class from PAPERS.md): a shared attribute is
declared guarded by writing

    self._ring = deque()  # guarded-by: self._lock

on its ``__init__`` assignment. Every later load/store of that attribute
inside the class must then sit lexically under ``with self._lock:`` (a
comma list allows aliases — ``# guarded-by: self._lock, self._cond``
for a Condition wrapping the same lock). Helper methods that are only
ever called with the lock already held declare it:

    def _trip(self) -> None:  # holds: self._lock

``__init__`` itself is exempt (construction happens-before sharing).

``lock-order`` builds the acquisition graph from syntactic nesting —
``with self.a:`` containing ``with self.b:`` adds edge ``Class.a ->
Class.b`` — across every analyzed module, and reports any cycle: two
threads taking the same pair of locks in opposite orders is a deadlock
that no test reliably reproduces.

Violation keys: ``Class.attr:method`` (discipline),
``cycle:<a>-><b>->...`` (order).
"""

from __future__ import annotations

import ast

from tpumon.analysis.core import Project, Violation

DISCIPLINE_RULE = "lock-discipline"
ORDER_RULE = "lock-order"

_GUARD_MARK = "guarded-by:"
_HOLDS_MARK = "holds:"


def _self_attr(node: ast.AST) -> str | None:
    """``self.x`` -> ``x`` (only for direct self attributes)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _parse_marked_names(comment: str, mark: str) -> set[str]:
    """``# guarded-by: self._lock, self._cond`` -> {"_lock", "_cond"}."""
    if mark not in comment:
        return set()
    spec = comment.split(mark, 1)[1]
    # Allow trailing prose after an em dash or semicolon.
    for stop in ("—", ";", " - "):
        spec = spec.split(stop, 1)[0]
    names = set()
    for part in spec.split(","):
        part = part.strip()
        if part.startswith("self."):
            names.add(part[len("self."):])
        elif part:
            names.add(part)
    return names


def _stmt_comment(src, node: ast.AST) -> str:
    """Comments on the statement's own lines ONLY (no line-above
    fallback: an annotation must not leak onto the next assignment)."""
    end = getattr(node, "end_lineno", node.lineno) or node.lineno
    return " ".join(
        src.comments[ln]
        for ln in range(node.lineno, end + 1)
        if ln in src.comments
    )


def _guarded_attrs(cls: ast.ClassDef, src) -> dict[str, set[str]]:
    """attr -> lock-name aliases, from annotated __init__ assignments."""
    out: dict[str, set[str]] = {}
    for fn in cls.body:
        if not isinstance(fn, ast.FunctionDef) or fn.name != "__init__":
            continue
        for node in ast.walk(fn):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            locks = _parse_marked_names(_stmt_comment(src, node), _GUARD_MARK)
            if not locks:
                continue
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr:
                    out[attr] = locks
    return out


def _with_lock_names(node: ast.With) -> set[str]:
    """Lock attrs acquired by a ``with`` statement (``with self.a, self.b:``)."""
    out = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr:
            out.add(attr)
    return out


def _held_locks(node: ast.AST, src, fn: ast.FunctionDef) -> set[str]:
    """Locks lexically held at ``node`` within ``fn`` (with-statement
    ancestors), plus locks ``fn`` declares via ``# holds:``."""
    held = _parse_marked_names(src.comments.get(fn.lineno, ""), _HOLDS_MARK)
    for anc in src.ancestors(node):
        if isinstance(anc, ast.With):
            held |= _with_lock_names(anc)
        if anc is fn:
            break
    return held


def _methods(cls: ast.ClassDef):
    for fn in cls.body:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield fn


def check_discipline(project: Project) -> list[Violation]:
    out: list[Violation] = []
    for path, src in sorted(project.python.items()):
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = _guarded_attrs(cls, src)
            if not guarded:
                continue
            for fn in _methods(cls):
                if fn.name == "__init__":
                    continue  # happens-before: no concurrent readers yet
                seen_attrs: set[str] = set()
                for node in ast.walk(fn):
                    attr = _self_attr(node)
                    if attr is None or attr not in guarded or attr in seen_attrs:
                        continue
                    # The acquisition itself (`with self._lock:`) and
                    # passing the lock object around are not data access.
                    parent = src.parents.get(node)
                    if isinstance(parent, ast.withitem):
                        continue
                    held = _held_locks(node, src, fn)
                    if held & guarded[attr]:
                        continue
                    seen_attrs.add(attr)  # one report per (attr, method)
                    locks = ", ".join(
                        "self." + lk for lk in sorted(guarded[attr])
                    )
                    out.append(
                        Violation(
                            DISCIPLINE_RULE,
                            f"{cls.name}.{attr}:{fn.name}",
                            path,
                            node.lineno,
                            f"{cls.name}.{fn.name} touches self.{attr} "
                            f"(guarded-by {locks}) outside the lock; "
                            "wrap in `with`, or mark the method "
                            f"`# holds: {locks}` if callers always hold it",
                        )
                    )
    return out


def _acquisition_edges(project: Project) -> dict[tuple[str, str], tuple[str, int]]:
    """(outer, inner) -> first site, from nested ``with self.x`` blocks.
    Lock identities are ``Class.attr`` so distinct classes' ``_lock``
    attributes stay distinct nodes."""
    edges: dict[tuple[str, str], tuple[str, int]] = {}
    for path, src in sorted(project.python.items()):
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.With):
                    continue
                inner = _with_lock_names(node)
                if not inner:
                    continue
                for anc in src.ancestors(node):
                    if isinstance(anc, ast.ClassDef):
                        break
                    if not isinstance(anc, ast.With):
                        continue
                    for outer_name in _with_lock_names(anc):
                        for inner_name in inner:
                            if outer_name == inner_name:
                                continue
                            edge = (
                                f"{cls.name}.{outer_name}",
                                f"{cls.name}.{inner_name}",
                            )
                            edges.setdefault(edge, (path, node.lineno))
    return edges


def check_order(project: Project) -> list[Violation]:
    edges = _acquisition_edges(project)
    graph: dict[str, set[str]] = {}
    for outer, inner in edges:
        graph.setdefault(outer, set()).add(inner)

    out: list[Violation] = []
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(node: str, stack: list[str], on_stack: set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cycle = stack[stack.index(nxt):] + [nxt]
                # Canonical rotation so each cycle reports once.
                ring = tuple(cycle[:-1])
                lo = ring.index(min(ring))
                canon = ring[lo:] + ring[:lo]
                if canon in seen_cycles:
                    continue
                seen_cycles.add(canon)
                path, line = edges[(node, nxt)]
                # Space-free key: baseline fingerprints read cleanest as
                # a single token (the human chain goes in the message).
                chain = "->".join([*canon, canon[0]])
                human = " -> ".join([*canon, canon[0]])
                out.append(
                    Violation(
                        ORDER_RULE, f"cycle:{chain}", path, line,
                        f"lock acquisition cycle {human}: two threads "
                        "taking these locks in opposite orders deadlock",
                    )
                )
                continue
            dfs(nxt, stack + [nxt], on_stack | {nxt})

    for start in sorted(graph):
        dfs(start, [start], {start})
    return out
