"""Thread-root discovery and role propagation over the call graph.

A *thread root* is a function some thread enters from the top:

- ``threading.Thread(target=X)`` / ``threading.Timer(t, X)`` spawn X;
- ``<executor>.submit(X, ...)`` runs X on a pool thread;
- a WSGI entry point (``def app(environ, start_response)``) runs on a
  serving thread per request;
- a ``*Servicer`` method runs on a gRPC server pool thread;
- ``def f(...):  # thread: <role>`` declares a root the AST cannot see
  (a callback invoked by a framework, a handler wired dynamically).

Each root carries a **role** — the stable name of the thread population
that enters it. At a spawn site the role comes from, in order: a
``# thread: <role>`` comment on the spawning statement, the ``name=``
literal (its ``tpumon-`` prefix stripped), or the target function's own
name. Roles then propagate over the call graph: a function's role set is
the union of roles of every root that (transitively) calls it. The race
rules convict on role sets, so an unresolvable call (no edge) can only
under-report — never fabricate a cross-thread access.

``__init__`` bodies get no roles from construction: object construction
happens-before sharing, matching the lock rules' exemption.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass

from tpumon.analysis.callgraph import CallGraph, FuncInfo, build
from tpumon.analysis.core import Project, call_name, dotted, str_const

ROLE_MARK = "thread:"

#: Spawn callables: callee name -> (positional index of the target,
#: keyword name of the target, default role when nothing names one).
_SPAWN_SHAPES = {
    "Thread": (None, "target", None),
    "Timer": (1, "function", "timer"),
    "submit": (0, None, "executor"),
}

_WSGI_PARAMS = ("environ", "start_response")


@dataclass(frozen=True)
class ThreadRoot:
    qualname: str
    role: str
    path: str
    line: int
    via: str  # "spawn" | "annotation" | "wsgi" | "servicer"


@dataclass
class ThreadAnalysis:
    graph: CallGraph
    roots: list[ThreadRoot]
    #: qualname -> roles of every thread population reaching it.
    roles: dict[str, set[str]]

    def roles_of(self, node: ast.AST) -> set[str]:
        """Roles reaching a function *definition* node (empty when the
        function is unreachable from any discovered root)."""
        fi = self.graph.by_node.get(id(node))
        if fi is None:
            return set()
        return self.roles.get(fi.qualname, set())


def _parse_role(comment: str) -> str | None:
    """``# thread: collect — why`` -> ``collect``."""
    if ROLE_MARK not in comment:
        return None
    spec = comment.split(ROLE_MARK, 1)[1]
    for stop in ("—", ";", " - "):
        spec = spec.split(stop, 1)[0]
    spec = spec.strip()
    return spec.split()[0].rstrip(",") if spec else None


def _stmt_comment(src, node: ast.AST) -> str:
    """Comments across the statement's own lines ONLY (no spill onto the
    next line: an annotation must not leak onto a neighboring spawn)."""
    end = getattr(node, "end_lineno", node.lineno) or node.lineno
    return " ".join(
        src.comments[ln]
        for ln in range(node.lineno, end + 1)
        if ln in src.comments
    )


def _spawn_role(src, call: ast.Call, targets: set[str], default: str | None) -> str:
    role = _parse_role(_stmt_comment(src, call))
    if role:
        return role
    for kw in call.keywords:
        if kw.arg == "name":
            lit = str_const(kw.value)
            if lit:
                return lit.removeprefix("tpumon-")
    if default is not None:
        return default
    if targets:
        # Short name of the (sorted-first) target function.
        return sorted(targets)[0].rsplit(".", 1)[-1].lstrip("_") or "thread"
    return "thread"


def _spawn_target_expr(call: ast.Call, pos: int | None, kwname: str | None):
    if kwname is not None:
        for kw in call.keywords:
            if kw.arg == kwname:
                return kw.value
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    # Thread(target=...) is keyword-only in practice, but accept
    # positional Timer/submit shapes too.
    return None


def _is_spawn(call: ast.Call) -> tuple[int | None, str | None, str | None] | None:
    name = call_name(call)
    shape = _SPAWN_SHAPES.get(name)
    if shape is None:
        return None
    if name in ("Thread", "Timer"):
        full = dotted(call.func)
        # `threading.Thread(...)`, bare `Thread(...)` (from-import), or a
        # vendor alias ending in .Thread — but not `x.submit` lookalikes.
        if full not in (name, f"threading.{name}") and not full.endswith(
            f"threading.{name}"
        ):
            return None
    return shape


def discover_roots(project: Project, graph: CallGraph) -> list[ThreadRoot]:
    roots: list[ThreadRoot] = []
    for path, src in sorted(project.python.items()):
        # Declared + structural roots on the definitions themselves.
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            fi = graph.by_node.get(id(node))
            if fi is None:
                continue
            role = _parse_role(src.comments.get(node.lineno, ""))
            if role:
                roots.append(
                    ThreadRoot(fi.qualname, role, path, node.lineno, "annotation")
                )
            params = [a.arg for a in node.args.args]
            if fi.cls is not None and params[:1] == ["self"]:
                params = params[1:]
            if tuple(params[:2]) == _WSGI_PARAMS:
                roots.append(
                    ThreadRoot(fi.qualname, "serve", path, node.lineno, "wsgi")
                )
            if (
                fi.cls is not None
                and fi.cls.name.endswith("Servicer")
                and not node.name.startswith("_")
            ):
                roots.append(
                    ThreadRoot(fi.qualname, "serve", path, node.lineno, "servicer")
                )
        # Spawn sites.
        for call in ast.walk(src.tree):
            if not isinstance(call, ast.Call):
                continue
            shape = _is_spawn(call)
            if shape is None:
                continue
            pos, kwname, default = shape
            expr = _spawn_target_expr(call, pos, kwname)
            if expr is None:
                continue
            owner_node = CallGraph._owning_function(src, call)
            fi = graph.by_node.get(id(owner_node)) if owner_node else None
            targets = graph.resolve(path, fi, expr)
            if not targets:
                continue
            role = _spawn_role(src, call, targets, default)
            for qn in sorted(targets):
                roots.append(ThreadRoot(qn, role, path, call.lineno, "spawn"))
    return roots


def propagate(graph: CallGraph, roots: list[ThreadRoot]) -> dict[str, set[str]]:
    roles: dict[str, set[str]] = {}
    work: deque[str] = deque()
    for root in roots:
        got = roles.setdefault(root.qualname, set())
        if root.role not in got:
            got.add(root.role)
            work.append(root.qualname)
    while work:
        qn = work.popleft()
        mine = roles.get(qn, set())
        for callee in graph.edges.get(qn, ()):
            fi = graph.functions.get(callee)
            if fi is not None and fi.name == "__init__":
                # Construction happens-before sharing: __init__ bodies
                # run before the object is visible to other threads.
                continue
            got = roles.setdefault(callee, set())
            missing = mine - got
            if missing:
                got |= missing
                work.append(callee)
    return roles


def analyze(project: Project) -> ThreadAnalysis:
    """Build (and cache on the project) the thread-role analysis."""
    cached = getattr(project, "_thread_analysis", None)
    if cached is not None:
        return cached
    graph = build(project)
    roots = discover_roots(project, graph)
    roles = propagate(graph, roots)
    analysis = ThreadAnalysis(graph, roots, roles)
    project._thread_analysis = analysis  # type: ignore[attr-defined]
    return analysis
