"""Rule ``except-hygiene``: broad handlers in the poll/serving pipeline
must observe the failure — log it, count it, or re-raise.

``except Exception: pass`` in a 1 Hz loop is how a permanently broken
stage becomes invisible: the exporter keeps publishing, the family just
quietly vanishes. The collector's contract (SURVEY §5.3) is "degrade to
a dropped sample PLUS a counter increment"; this rule makes the *plus*
mechanical.

A handler is compliant when its body (transitively) contains any of:

- a ``raise``;
- a logging call (``log.*``/``logger.*``/``logging.*`` with a level
  method name);
- a counter/telemetry call (``.inc()``, ``.observe()``, ``.record()``,
  ``.count_shed()``) — the stage-error funnel (bare ``.labels()`` /
  ``.set()`` do NOT count: they move no counter a human can alert on);
- an explicit ``# tpumon-invariants: disable=except-hygiene`` (core
  suppression) on the ``except`` line.

Only broad handlers are checked: ``except Exception``, ``except
BaseException``, bare ``except``, and tuples containing them. Narrow
handlers (``except (AttributeError, OSError)``) encode intent already.

Violation keys: ``<path>:<function>:<line-of-handler-relative-id>`` —
actually ``<path>:<function>:<exception-type>#<n>`` (n-th broad handler
in that function) so line churn does not invalidate the baseline.
"""

from __future__ import annotations

import ast

from tpumon.analysis.core import (
    PIPELINE_PREFIXES,
    Project,
    Violation,
    call_name,
    dotted,
    iter_functions,
)

RULE = "except-hygiene"

#: The shared pipeline scope plus the parser (sample decoding is
#: poll-pipeline work even though it lives at top level).
SCOPE_PREFIXES = PIPELINE_PREFIXES + ("tpumon/parsing.py",)

_LOG_METHODS = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
}
#: Calls that actually record the failure somewhere a human or alert can
#: see it. Deliberately narrow: bare `.labels(...)` creates a series
#: without moving it, and `.set()` on an Event is control flow — neither
#: observes anything.
_COUNT_METHODS = {"inc", "observe", "record", "count_shed"}
_LOG_OBJECTS = {"log", "logger", "logging", "self"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(el, "id", "") for el in t.elts]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in ("Exception", "BaseException") for n in names)


def _observes(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            base = dotted(node.func).split(".", 1)[0]
            if name in _LOG_METHODS and base in _LOG_OBJECTS:
                return True
            if name in _COUNT_METHODS and isinstance(node.func, ast.Attribute):
                return True
    return False


def check(project: Project) -> list[Violation]:
    out: list[Violation] = []
    for path, src in sorted(project.python.items()):
        if not path.startswith(SCOPE_PREFIXES):
            continue
        for fn in iter_functions(src.tree):
            broad_seen = 0
            for node in ast.walk(fn):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                # Handlers belong to the innermost function: skip ones
                # owned by a nested def (they get their own visit).
                owner = None
                for anc in src.ancestors(node):
                    if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        owner = anc
                        break
                if owner is not fn or not _is_broad(node):
                    continue
                broad_seen += 1
                if _observes(node):
                    continue
                kind = "bare" if node.type is None else "Exception"
                out.append(
                    Violation(
                        RULE,
                        f"{path}:{fn.name}:{kind}#{broad_seen}",
                        path,
                        node.lineno,
                        f"broad `except {kind}` in {fn.name} swallows the "
                        "failure silently: log it, count it "
                        "(stage-error counter), or re-raise",
                    )
                )
    return out
