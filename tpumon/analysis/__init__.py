"""Repo-specific invariant analyzer (AST-driven lint plane).

Four planes of growth (trace, resilience, guard, anomaly) left tpumon's
correctness resting on cross-file invariants nothing enforced: every
``TPUMON_*`` knob must exist in config/chart/kustomize/docs, every metric
family must be registered and documented, shared state must stay under
its lock, blocking calls on the serving/poll paths must carry deadlines,
and ``except Exception`` in the poll pipeline must never swallow
silently. Since 1.1.0 the discipline is interprocedural: a whole-package
call graph (callgraph.py) propagates thread roles from every spawn site,
executor submit, WSGI/gRPC entry point, and ``# thread:`` annotation
(threads.py), and two concurrency rules (races.py) convict unlocked
cross-role stores and off-role mutations of page-feeding
``# publish-on:`` state — the PR 19 ``tpu_fleet_shard_targets`` skew
class, caught in the AST instead of 200 chaos schedules. This package
proves those invariants mechanically:

- ``python -m tpumon.tools.check`` — the CLI (``--strict`` gates CI);
- ``tests/test_analysis.py`` — per-rule fixture proofs + a repo
  self-check that runs in the tier-1 suite;
- ``tpumon/analysis/baseline.txt`` — the suppression file enumerating
  accepted violations (each with a reason); new violations fail CI.

Everything here is stdlib-only (ast + tokenize + json + re): the
analyzer must run on a bare checkout with no dependencies installed.
See docs/INVARIANTS.md for the rule catalog and annotation conventions
(``# guarded-by:``, ``# holds:``, ``# deadline:``, ``# thread:``,
``# publish-on:``, ``# tpumon-invariants: disable=<rule>``).
"""

from __future__ import annotations

from tpumon.analysis.core import (
    ANALYZER_VERSION,
    Project,
    Violation,
    load_project,
    run_rules,
)
from tpumon.analysis.baseline import (
    baseline_count,
    baseline_path,
    load_baseline,
    stamp_info,
)

__all__ = [
    "ANALYZER_VERSION",
    "Project",
    "Violation",
    "baseline_count",
    "baseline_path",
    "load_baseline",
    "load_project",
    "run_rules",
    "stamp_info",
]
