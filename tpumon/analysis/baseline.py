"""Baseline (suppression) file + check-stamp helpers.

``tpumon/analysis/baseline.txt`` ships inside the package and enumerates
the violations the repo has consciously accepted, one per line:

    <rule> <key>  # <reason>

Fingerprints are line-number-free (see core.Violation), so the baseline
survives refactoring; a fingerprint that stops matching is reported as
STALE and fails ``--strict`` — burn-down is enforced, not aspirational.

The checker also writes a stamp (``.tpumon-invariants.json`` at the repo
root, or ``$TPUMON_INVARIANTS_STAMP``) recording the last run's verdict;
``tpumon doctor`` prints it and the exporter's ``/debug/vars`` carries
the analyzer version + baseline size, so discipline status is visible
from the running DaemonSet, not only from CI.
"""

from __future__ import annotations

import json
import os
import time

STAMP_ENV = "TPUMON_INVARIANTS_STAMP"
STAMP_NAME = ".tpumon-invariants.json"


def baseline_path(root: str | None = None) -> str:
    """The packaged baseline file (or the one in a checkout at root)."""
    if root is not None:
        return os.path.join(root, "tpumon", "analysis", "baseline.txt")
    return os.path.join(os.path.dirname(__file__), "baseline.txt")


def load_baseline(path: str | None = None) -> dict[str, str]:
    """fingerprint -> reason (empty string when none given)."""
    path = path or baseline_path()
    out: dict[str, str] = {}
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return out
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        entry, _, reason = line.partition("  #")
        # The WHOLE pre-comment text is the fingerprint: keys may carry
        # internal spaces (nothing guarantees them space-free forever),
        # and truncating would break the --update-baseline round-trip.
        entry = entry.strip()
        if len(entry.split()) >= 2:
            out[entry] = reason.strip()
    return out


def baseline_count(path: str | None = None) -> int:
    return len(load_baseline(path))


def default_stamp_path(root: str) -> str:
    return os.environ.get(STAMP_ENV) or os.path.join(root, STAMP_NAME)


def write_stamp(
    root: str,
    *,
    new: int,
    baselined: int,
    stale: int,
    version: str,
    new_by_rule: dict[str, int] | None = None,
) -> str:
    path = default_stamp_path(root)
    doc = {
        "ts": time.time(),
        "analyzer_version": version,
        "new_violations": new,
        "baselined": baselined,
        "stale_baseline_entries": stale,
        "ok": new == 0 and stale == 0,
    }
    if new_by_rule:
        doc["new_by_rule"] = dict(sorted(new_by_rule.items()))
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def stamp_info(root: str | None = None) -> dict | None:
    """The last check's stamp, or None when never run. ``root`` defaults
    to the checkout containing this package (doctor's case)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    path = default_stamp_path(root)
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None
