"""Rules ``race`` and ``publish-discipline``: interprocedural
concurrency analysis over the thread-role propagation (threads.py).

``race`` — an unguarded cross-thread store. For every ``self.attr``
store outside ``__init__`` in the pipeline modules, the rule computes
the set of thread roles that reach the storing method over the call
graph. When the stores of one attribute are reachable from **two or
more roles** and no common lock is held lexically at every store site,
two threads can interleave the writes — the bug class PR 19's chaos
search needed 200 seeded schedules to hit, visible here in the AST.
The rule *composes* with the lock annotations instead of replacing
them: an attribute declared ``# guarded-by: self._lock`` is
lock-discipline's jurisdiction (that rule already flags unheld
accesses), and a store set that shares a lexical ``with self.<lock>:``
is accepted as guarded even without an annotation.

``publish-discipline`` — state that feeds a published page mutates only
on its publishing thread, after the page publish. Declaration rides the
attribute's construction, like ``guarded-by``:

    self.shard_targets = Gauge("tpu_fleet_shard_targets", ...)
    # publish-on: collect

Any mutation of that attribute (``.set()``/``.inc()``/``.dec()``/
``.observe()`` on it, or rebinding it) reachable from a role outside
the declared set is a violation naming the gauge and both roles — the
exact PR 19 ``tpu_fleet_shard_targets`` bug class, where the membership
thread stamped a gauge against a rollup that had not adopted its
targets yet. Inside the publishing role, a mutation that precedes the
``.publish(...)`` call in the same function breaks page-atomicity the
other way (the fresh value rides the *previous* page) and is flagged as
``<name>:before-publish:<method>``.

Violation keys: ``Class.attr`` (race), ``<gauge-or-attr>:<method>`` and
``<gauge-or-attr>:before-publish:<method>`` (publish-discipline).
"""

from __future__ import annotations

import ast

from tpumon.analysis.core import (
    PIPELINE_PREFIXES,
    Project,
    Violation,
    call_name,
    str_const,
)
from tpumon.analysis.locks import (
    _guarded_attrs,
    _held_locks,
    _methods,
    _parse_marked_names,
    _self_attr,
    _stmt_comment,
)
from tpumon.analysis.threads import analyze

RACE_RULE = "race"
PUBLISH_RULE = "publish-discipline"

_PUBLISH_MARK = "publish-on:"

#: Metric-object methods that move a published value.
_MUTATORS = {"set", "inc", "dec", "observe"}

#: Metric constructors whose first literal argument names the family —
#: used to report the gauge by its exposition name, not its attribute.
_METRIC_CTORS = {
    "Gauge", "Counter", "Histogram", "Summary", "Info",
    "GaugeMetricFamily", "CounterMetricFamily", "HistogramMetricFamily",
}

#: The race rules run on the serving/poll pipeline (like deadline and
#: except-hygiene): driver-side tooling (workload harness, bench, smi)
#: spawns throwaway threads whose state never outlives a run.
SCOPE_PREFIXES = PIPELINE_PREFIXES


def _store_targets(node: ast.AST) -> list[str]:
    """self-attribute names stored by an assignment statement."""
    targets: list[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    out = []
    for tgt in targets:
        if isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                attr = _self_attr(el)
                if attr:
                    out.append(attr)
        else:
            attr = _self_attr(tgt)
            if attr:
                out.append(attr)
    return out


def check_races(project: Project) -> list[Violation]:
    analysis = analyze(project)
    out: list[Violation] = []
    for path, src in sorted(project.python.items()):
        if not path.startswith(SCOPE_PREFIXES):
            continue
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = _guarded_attrs(cls, src)
            # attr -> list of (method, node, roles, held-locks).
            stores: dict[str, list] = {}
            for fn in _methods(cls):
                if fn.name == "__init__":
                    continue
                roles = analysis.roles_of(fn)
                for node in ast.walk(fn):
                    for attr in _store_targets(node):
                        if attr in guarded:
                            continue  # lock-discipline's jurisdiction
                        held = _held_locks(node, src, fn)
                        stores.setdefault(attr, []).append(
                            (fn.name, node, roles, held)
                        )
            for attr, sites in sorted(stores.items()):
                all_roles: set[str] = set()
                for _, _, roles, _ in sites:
                    all_roles |= roles
                if len(all_roles) < 2:
                    continue
                common = sites[0][3].copy()
                for _, _, _, held in sites[1:]:
                    common &= held
                if common:
                    continue  # every store shares a lexical lock
                first = min(sites, key=lambda s: s[1].lineno)
                methods = sorted({name for name, _, _, _ in sites})
                out.append(
                    Violation(
                        RACE_RULE,
                        f"{cls.name}.{attr}",
                        path,
                        first[1].lineno,
                        f"{cls.name}.{attr} is stored from thread roles "
                        f"{{{', '.join(sorted(all_roles))}}} (in "
                        f"{', '.join(methods)}) with no common lock held "
                        "and no `# guarded-by:` annotation — interleaved "
                        "writes race; lock it, confine it to one role, "
                        "or annotate the guard",
                    )
                )
    return out


# -- publish-discipline ----------------------------------------------------


def _declared_publish_attrs(project: Project):
    """attr declarations carrying ``# publish-on: <role,...>``:
    name -> (display name, declared roles, class, path, line)."""
    out: dict[str, tuple[str, set[str], str, str, int]] = {}
    for path, src in sorted(project.python.items()):
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                roles = _parse_marked_names(
                    _stmt_comment(src, node), _PUBLISH_MARK
                )
                if not roles:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    display = f"{cls.name}.{attr}"
                    value = node.value
                    if (
                        isinstance(value, ast.Call)
                        and call_name(value) in _METRIC_CTORS
                        and value.args
                    ):
                        fam = str_const(value.args[0])
                        if fam:
                            display = fam
                    out[attr] = (display, roles, cls.name, path, node.lineno)
    return out


def _mutated_attr(node: ast.Call) -> str | None:
    """``<recv>.X.set(...)`` -> ``X`` for the mutator methods."""
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in _MUTATORS:
        return None
    value = func.value
    # Peel `.labels(...)`: `<recv>.X.labels(a=b).set(v)` mutates X too.
    if (
        isinstance(value, ast.Call)
        and isinstance(value.func, ast.Attribute)
        and value.func.attr == "labels"
    ):
        value = value.func.value
    if isinstance(value, ast.Attribute):
        return value.attr
    return None


def _is_decl_site(node: ast.AST, src) -> bool:
    return _PUBLISH_MARK in _stmt_comment(src, node)


def check_publish(project: Project) -> list[Violation]:
    declared = _declared_publish_attrs(project)
    if not declared:
        return []
    analysis = analyze(project)
    out: list[Violation] = []
    for path, src in sorted(project.python.items()):
        if not path.startswith(SCOPE_PREFIXES):
            continue
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue  # seeding an initial value happens-before
            roles = analysis.roles_of(fn)
            # Mutation sites owned by this function.
            sites: list[tuple[str, int]] = []
            publish_line: int | None = None
            for node in ast.walk(fn):
                owner = next(
                    (
                        a
                        for a in src.ancestors(node)
                        if isinstance(
                            a, (ast.FunctionDef, ast.AsyncFunctionDef)
                        )
                    ),
                    None,
                )
                if owner is not fn:
                    continue
                if isinstance(node, ast.Call):
                    if call_name(node) == "publish":
                        if publish_line is None or node.lineno < publish_line:
                            publish_line = node.lineno
                    attr = _mutated_attr(node)
                    if attr in declared:
                        sites.append((attr, node.lineno))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for tgt in targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and tgt.attr in declared
                            and not _is_decl_site(node, src)
                        ):
                            sites.append((tgt.attr, node.lineno))
            for attr, line in sites:
                display, decl_roles, cls_name, dpath, dline = declared[attr]
                offending = roles - decl_roles
                if offending:
                    out.append(
                        Violation(
                            PUBLISH_RULE,
                            f"{display}:{fn.name}",
                            path,
                            line,
                            f"{display} (publish-on: "
                            f"{', '.join(sorted(decl_roles))} — declared "
                            f"at {dpath}:{dline}) is mutated in {fn.name}, "
                            "reachable from thread role(s) "
                            f"{{{', '.join(sorted(offending))}}}: the "
                            "published page can disagree with the rollup "
                            "it rides (the PR 19 "
                            "tpu_fleet_shard_targets class); move the "
                            "mutation to the publishing role's "
                            "post-publish step",
                        )
                    )
                elif (
                    roles
                    and publish_line is not None
                    and line < publish_line
                ):
                    out.append(
                        Violation(
                            PUBLISH_RULE,
                            f"{display}:before-publish:{fn.name}",
                            path,
                            line,
                            f"{display} (publish-on: "
                            f"{', '.join(sorted(decl_roles))}) is mutated "
                            f"in {fn.name} BEFORE the page publish on "
                            f"line {publish_line}: an interleaved scrape "
                            "reads the new value against the old page — "
                            "mutate after .publish() so the only "
                            "observable skew is the honest direction",
                        )
                    )
    return out
