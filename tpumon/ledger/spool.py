"""Ledger warm-restart spool: the week's history survives a reschedule.

Same write discipline as the fleet SnapshotSpool (tpumon/fleet/spool.py
— the journald/prometheus-WAL genre, scaled down): **atomic** (temp +
``os.replace``), **versioned** (unknown versions load empty instead of
exploding on a downgrade), **bounded** (the serialized document is
refused over ``max_bytes`` — the store's own tier budgets are what keep
it under), and **corrupt-tolerant** (any load failure quarantines the
file as ``.corrupt`` and returns empty: a bad spool costs the warm
start, never the aggregator), and **degrading on a full disk** (ENOSPC
/ EROFS / EDQUOT flips the spool memory-only until a retry probe every
``DEGRADED_RETRY_S`` writes clean — the caller counts the transition
as ``tpu_ledger_spool_errors_total{op="enospc"}`` once).

Payload: one JSON document ``{"store": <TieredSeriesStore.to_doc>,
"goodput": <GoodputLedger.to_doc>, "saved_at": ts}`` — sealed chunks
ride as base64. The plane uses ``saved_at`` to ledger the restart gap
(tpu_ledger_gap_seconds_total): downtime becomes *unaccounted*
chip-seconds and missing samples, never interpolated ones.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import tempfile
import time

from tpumon.fleet.spool import DEGRADE_ERRNOS, DEGRADED_RETRY_S

log = logging.getLogger(__name__)

LEDGER_SPOOL_VERSION = 1
LEDGER_SPOOL_NAME = "ledger-spool.json"


class LedgerSpool:
    """One shard's on-disk ledger journal. Single-writer (the collect
    loop's executor, one save in flight at a time — the plane's
    in-flight flag mirrors the aggregator snapshot spool)."""

    def __init__(
        self, directory: str, max_bytes: int = 134217728, clock=time.time
    ) -> None:
        self.directory = directory
        self.path = os.path.join(directory, LEDGER_SPOOL_NAME)
        self.max_bytes = max(4096, int(max_bytes))
        self._clock = clock
        self.last_write_ts = 0.0
        self.last_load_error: str | None = None
        #: True while the spool runs memory-only because the volume is
        #: full / read-only (DEGRADE_ERRNOS) — same discipline as the
        #: fleet SnapshotSpool: callers count the False->True
        #: transition once and gauge the state.
        self.degraded = False
        self.degraded_reason: str | None = None
        self._next_retry_ts = 0.0
        #: Test/chaos hook: when set, every save attempt fails with
        #: this errno before touching the filesystem.
        self.inject_errno: int | None = None

    def save(self, store_doc: dict, goodput_doc: dict) -> bool:
        now = self._clock()
        if self.degraded and now < self._next_retry_ts:
            return False  # memory-only: skipped, not attempted
        doc = {
            "version": LEDGER_SPOOL_VERSION,
            "saved_at": now,
            "store": store_doc,
            "goodput": goodput_doc,
        }
        try:
            body = json.dumps(doc, sort_keys=True).encode()
            if len(body) > self.max_bytes:
                # The tier byte budgets should make this unreachable;
                # if they didn't, refusing the write beats an unbounded
                # disk file on a shared emptyDir.
                log.warning(
                    "ledger spool body %d bytes exceeds %d cap; skipped",
                    len(body), self.max_bytes,
                )
                return False
            os.makedirs(self.directory, exist_ok=True)
            if self.inject_errno is not None:
                raise OSError(
                    self.inject_errno, os.strerror(self.inject_errno)
                )
            fd, tmp = tempfile.mkstemp(
                dir=self.directory, prefix=".ledger-", suffix=".tmp"
            )
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(body)
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    log.debug(
                        "ledger spool temp cleanup failed", exc_info=True
                    )
                raise
            self.last_write_ts = doc["saved_at"]
            if self.degraded:
                log.info(
                    "ledger spool recovered from %s; journaling resumed",
                    self.degraded_reason,
                )
                self.degraded = False
                self.degraded_reason = None
            return True
        except (OSError, TypeError, ValueError) as exc:
            self._note_write_failure(exc, now)
            return False

    def _note_write_failure(self, exc: Exception, now: float) -> None:
        """Volume-level errnos flip the spool to memory-only with a
        retry backoff; anything else stays a per-attempt failure."""
        code = getattr(exc, "errno", None)
        if code in DEGRADE_ERRNOS:
            self._next_retry_ts = now + DEGRADED_RETRY_S
            if not self.degraded:
                self.degraded = True
                self.degraded_reason = errno.errorcode.get(code, str(code))
                log.warning(
                    "ledger spool degraded to memory-only (%s): %s",
                    self.degraded_reason, exc,
                )
            return
        log.warning("ledger spool write failed: %s", exc)

    def load(self) -> dict:
        """``{"store": {...}, "goodput": {...}, "saved_at": ts}`` —
        empty shapes on absence, corruption, or version mismatch."""
        empty = {"store": {}, "goodput": {}, "saved_at": 0.0}
        self.last_load_error = None
        try:
            with open(self.path, "rb") as fh:
                raw = fh.read(self.max_bytes + 1)
        except FileNotFoundError:
            return empty
        except OSError as exc:
            log.warning("ledger spool unreadable: %s", exc)
            self.last_load_error = str(exc)
            return empty
        try:
            if len(raw) > self.max_bytes:
                raise ValueError("ledger spool exceeds max_bytes")
            doc = json.loads(raw.decode())
            if not isinstance(doc, dict):
                raise ValueError("ledger spool root is not an object")
            if doc.get("version") != LEDGER_SPOOL_VERSION:
                log.warning(
                    "ledger spool version %r != %d; ignoring",
                    doc.get("version"), LEDGER_SPOOL_VERSION,
                )
                return empty
            store = doc.get("store")
            goodput = doc.get("goodput")
            if not isinstance(store, dict) or not isinstance(goodput, dict):
                raise ValueError("ledger spool fields have wrong shapes")
            return {
                "store": store,
                "goodput": goodput,
                "saved_at": float(doc.get("saved_at") or 0.0),
            }
        except (ValueError, UnicodeDecodeError) as exc:
            quarantine = self.path + ".corrupt"
            log.warning(
                "ledger spool corrupt (%s); quarantining to %s",
                exc, quarantine,
            )
            self.last_load_error = str(exc)
            try:
                os.replace(self.path, quarantine)
            except OSError:
                log.debug("ledger spool quarantine failed", exc_info=True)
            return empty


__all__ = ["LedgerSpool", "LEDGER_SPOOL_NAME", "LEDGER_SPOOL_VERSION"]
