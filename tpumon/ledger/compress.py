"""Gorilla-style chunk codec: delta-of-delta timestamps + XOR values.

The ledger's storage unit is a sealed **chunk**: one series' samples
over a bounded window, encoded once and immutable afterwards. The
encoding is the Facebook Gorilla scheme (delta-of-delta integer
timestamps, XOR-with-previous IEEE doubles with a reusing
leading/trailing-zero window), chosen because fleet telemetry is
exactly its sweet spot — near-regular cadence (dod == 0 costs one bit)
and slowly moving gauges (repeat value costs one bit). A steady series
compresses to ~1.4 bits/sample; the 5-minute tier's aggregate points
each stand for 300 raw seconds, which is how the bytes-per-raw-sample
headline gets under 0.15 (bench.py ``ledger_compression``).

Two implementations, one wire format:

- the Python encoder/decoder below (always available), and
- ``tpumon/_native/_gorilla.c`` built on demand through the shared
  ``load_extension`` machinery.

They are pinned **byte-identical** (tests/test_ledger.py encodes the
same stream through both and compares bytes), so a chunk sealed by a
native aggregator reloads fine after a restart onto a compiler-less
node, and vice versa. ``TPUMON_NO_NATIVE`` forces the fallback.

Chunk grammar (everything big-endian bit order, byte-padded with zero
bits at the end)::

    varint n                      # sample count; n == 0 ends the chunk
    varint ts[0]                  # first timestamp, milliseconds
    8 bytes                       # first value, IEEE-754 double
    then per sample i in 1..n-1:
      dod = (ts[i]-ts[i-1]) - (ts[i-1]-ts[i-2])   # ts[-1]: delta 0
      '0'                                  when dod == 0
      '10'   + 7  bits (dod + 63)          when -63   <= dod <= 64
      '110'  + 9  bits (dod + 255)         when -255  <= dod <= 256
      '1110' + 12 bits (dod + 2047)        when -2047 <= dod <= 2048
      '1111' + 64 bits two's-complement    otherwise
      x = bits(val[i]) ^ bits(val[i-1])
      '0'                                  when x == 0
      '1' '0' + meaningful bits            when x fits the prev window
      '1' '1' + 5 bits leading-zero count (capped 31)
              + 6 bits (meaningful-length - 1) + meaningful bits

Timestamps are **integer milliseconds** — the ledger quantizes float
epoch seconds on the way in, which keeps the codec lossless and the
dod arithmetic exact.
"""

from __future__ import annotations

import logging
import struct

from tpumon._native import load_extension

log = logging.getLogger(__name__)

_NATIVE_STEM = "_gorilla"


def _encode_varint(value: int, out: bytearray) -> None:
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return


def _decode_varint(data: bytes, idx: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if idx >= len(data):
            raise ValueError("truncated varint")
        byte = data[idx]
        idx += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, idx
        shift += 7
        if shift > 70:
            raise ValueError("oversized varint")


class _BitWriter:
    """MSB-first bit accumulator over a bytearray."""

    __slots__ = ("out", "_acc", "_nbits")

    def __init__(self, out: bytearray) -> None:
        self.out = out
        self._acc = 0
        self._nbits = 0

    def write(self, value: int, nbits: int) -> None:
        self._acc = (self._acc << nbits) | (value & ((1 << nbits) - 1))
        self._nbits += nbits
        while self._nbits >= 8:
            self._nbits -= 8
            self.out.append((self._acc >> self._nbits) & 0xFF)
        self._acc &= (1 << self._nbits) - 1

    def flush(self) -> None:
        if self._nbits:
            self.out.append((self._acc << (8 - self._nbits)) & 0xFF)
            self._acc = 0
            self._nbits = 0


class _BitReader:
    """MSB-first bit reader over bytes."""

    __slots__ = ("data", "_idx", "_acc", "_nbits")

    def __init__(self, data: bytes, idx: int) -> None:
        self.data = data
        self._idx = idx
        self._acc = 0
        self._nbits = 0

    def read(self, nbits: int) -> int:
        while self._nbits < nbits:
            if self._idx >= len(self.data):
                raise ValueError("truncated chunk bitstream")
            self._acc = (self._acc << 8) | self.data[self._idx]
            self._idx += 1
            self._nbits += 8
        self._nbits -= nbits
        value = (self._acc >> self._nbits) & ((1 << nbits) - 1)
        self._acc &= (1 << self._nbits) - 1
        return value


_D64 = struct.Struct(">d")
_Q64 = struct.Struct(">Q")


def _bits_of(value: float) -> int:
    return _Q64.unpack(_D64.pack(value))[0]


def _value_of(bits: int) -> float:
    return _D64.unpack(_Q64.pack(bits))[0]


def _clz64(x: int) -> int:
    return 64 - x.bit_length()


def _ctz64(x: int) -> int:
    return (x & -x).bit_length() - 1


def encode_chunk_py(timestamps: list[int], values: list[float]) -> bytes:
    """Pure-Python chunk encoder (the portable reference; the native
    encoder is pinned byte-identical to THIS)."""
    n = len(timestamps)
    if n != len(values):
        raise ValueError("timestamp/value length mismatch")
    out = bytearray()
    _encode_varint(n, out)
    if n == 0:
        return bytes(out)
    ts0 = int(timestamps[0])
    if ts0 < 0:
        raise ValueError("negative timestamp")
    _encode_varint(ts0, out)
    out += _D64.pack(values[0])
    if n == 1:
        return bytes(out)
    bits = _BitWriter(out)
    prev_ts = ts0
    prev_delta = 0
    prev_bits = _bits_of(values[0])
    win_lead = -1
    win_len = 0
    for i in range(1, n):
        ts = int(timestamps[i])
        delta = ts - prev_ts
        dod = delta - prev_delta
        prev_ts = ts
        prev_delta = delta
        if dod == 0:
            bits.write(0, 1)
        elif -63 <= dod <= 64:
            bits.write(0b10, 2)
            bits.write(dod + 63, 7)
        elif -255 <= dod <= 256:
            bits.write(0b110, 3)
            bits.write(dod + 255, 9)
        elif -2047 <= dod <= 2048:
            bits.write(0b1110, 4)
            bits.write(dod + 2047, 12)
        else:
            bits.write(0b1111, 4)
            bits.write(dod & 0xFFFFFFFFFFFFFFFF, 64)
        vbits = _bits_of(values[i])
        xor = vbits ^ prev_bits
        prev_bits = vbits
        if xor == 0:
            bits.write(0, 1)
            continue
        bits.write(1, 1)
        lead = min(_clz64(xor), 31)
        trail = _ctz64(xor)
        if (
            win_lead >= 0
            and lead >= win_lead
            and trail >= 64 - win_lead - win_len
        ):
            bits.write(0, 1)
            bits.write(xor >> (64 - win_lead - win_len), win_len)
        else:
            length = 64 - lead - trail
            bits.write(1, 1)
            bits.write(lead, 5)
            bits.write(length - 1, 6)
            bits.write(xor >> trail, length)
            win_lead = lead
            win_len = length
    bits.flush()
    return bytes(out)


def decode_chunk_py(data: bytes) -> tuple[list[int], list[float]]:
    """Pure-Python inverse of :func:`encode_chunk_py`. Raises ValueError
    on a truncated or malformed chunk (the spool quarantines it)."""
    n, idx = _decode_varint(data, 0)
    if n == 0:
        return [], []
    if n < 0 or n > 1 << 30:
        raise ValueError("implausible chunk sample count")
    ts0, idx = _decode_varint(data, idx)
    if idx + 8 > len(data):
        raise ValueError("truncated chunk header")
    val0 = _D64.unpack_from(data, idx)[0]
    idx += 8
    timestamps = [ts0]
    values = [val0]
    if n == 1:
        return timestamps, values
    bits = _BitReader(data, idx)
    prev_ts = ts0
    prev_delta = 0
    prev_bits = _bits_of(val0)
    win_lead = -1
    win_len = 0
    for _ in range(1, n):
        if bits.read(1) == 0:
            dod = 0
        elif bits.read(1) == 0:
            dod = bits.read(7) - 63
        elif bits.read(1) == 0:
            dod = bits.read(9) - 255
        elif bits.read(1) == 0:
            dod = bits.read(12) - 2047
        else:
            raw = bits.read(64)
            dod = raw - (1 << 64) if raw >= 1 << 63 else raw
        prev_delta += dod
        prev_ts += prev_delta
        timestamps.append(prev_ts)
        if bits.read(1) == 0:
            values.append(_value_of(prev_bits))
            continue
        if bits.read(1) == 0:
            if win_lead < 0:
                raise ValueError("window reuse before any window")
            xor = bits.read(win_len) << (64 - win_lead - win_len)
        else:
            win_lead = bits.read(5)
            win_len = bits.read(6) + 1
            if win_lead + win_len > 64:
                raise ValueError("invalid XOR window")
            xor = bits.read(win_len) << (64 - win_lead - win_len)
        prev_bits ^= xor
        values.append(_value_of(prev_bits))
    return timestamps, values


def native_codec():
    """The compiled codec module, or None (fallback in use)."""
    return load_extension(_NATIVE_STEM)


def encode_chunk(timestamps: list[int], values: list[float]) -> bytes:
    """Encode one sealed chunk, native when the extension built.

    Output bytes are identical either way (pinned); callers never need
    to know which implementation sealed a chunk.
    """
    ext = native_codec()
    if ext is not None:
        try:
            return ext.encode(list(timestamps), list(values))
        except Exception:
            # A native hiccup degrades to the fallback, never loses data.
            log.warning(
                "native gorilla encode failed; using fallback",
                exc_info=True,
            )
    return encode_chunk_py(timestamps, values)


def decode_chunk(data: bytes) -> tuple[list[int], list[float]]:
    """Decode one sealed chunk (ValueError on malformed input)."""
    ext = native_codec()
    if ext is not None:
        try:
            ts, vals = ext.decode(bytes(data))
            return list(ts), list(vals)
        except ValueError:
            raise
        except Exception:
            log.warning(
                "native gorilla decode failed; using fallback",
                exc_info=True,
            )
    return decode_chunk_py(data)


__all__ = [
    "decode_chunk",
    "decode_chunk_py",
    "encode_chunk",
    "encode_chunk_py",
    "native_codec",
]
