"""Fleet efficiency ledger: long-horizon tiered time-series storage +
per-job goodput accounting inside the fleet aggregator (ROADMAP item 2;
PAPERS.md 2605.20799 "instant fleet efficiency visibility",
2504.10702 per-container accounting).

Everything below the aggregator is a last-good snapshot or a bounded
1 Hz ring; this package is what lets the tier answer *yesterday's*
questions — "what was this job's MFU at 3am?", "which pool wasted the
most chip-hours this week?" — without requiring an external TSDB:

- :mod:`tpumon.ledger.compress` — Gorilla-style delta-of-delta
  timestamp + XOR value chunk codec (native C in ``tpumon/_native/``,
  byte-identical Python fallback).
- :mod:`tpumon.ledger.store` — the tiered downsampling store
  (1 s → 10 s → 5 min) over the curated fleet family set, with
  bounded per-tier retention and byte budgets.
- :mod:`tpumon.ledger.goodput` — per-job chip-second accounting into
  productive / checkpoint / restore / preempted / idle / contended /
  unaccounted buckets with a conservation invariant.
- :mod:`tpumon.ledger.spool` — warm-restart journal (the PR 9
  SnapshotSpool write discipline applied to sealed chunks).
- :mod:`tpumon.ledger.remote_write` — optional Prometheus remote-write
  push (dependency-free protobuf + snappy framing), off by default.
- :mod:`tpumon.ledger.plane` — the aggregator-facing orchestration:
  one ``cycle()`` per collect cycle, ``tpu_ledger_*`` /
  ``tpu_fleet_goodput_*`` families, and the ``GET /ledger`` range
  query.
"""

from tpumon.ledger.goodput import BUCKETS, GoodputLedger
from tpumon.ledger.plane import LedgerPlane
from tpumon.ledger.store import LEDGER_FAMILY_SET, TierSpec, TieredSeriesStore

__all__ = [
    "BUCKETS",
    "GoodputLedger",
    "LEDGER_FAMILY_SET",
    "LedgerPlane",
    "TierSpec",
    "TieredSeriesStore",
]
