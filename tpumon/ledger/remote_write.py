"""Optional Prometheus remote-write push — an external TSDB stays
optional, never required.

Off by default (empty URL). When TPUMON_FLEET_LEDGER_REMOTE_WRITE_URL
names an endpoint, the ledger plane pushes the curated family samples
it just recorded on a bounded cadence, using the remote-write 1.0 wire
shape: a snappy-compressed protobuf ``WriteRequest`` POST. Both layers
are hand-rolled on the stdlib (the container bakes no snappy or
protobuf dependency):

- **protobuf**: ``WriteRequest{repeated TimeSeries{repeated Label,
  repeated Sample}}`` is nested length-delimited messages over the
  varint helpers tpumon.backends.reflection already owns — the same
  trick the gRPC PageRequest codec uses.
- **snappy**: the *block format* accepts a stream of literal elements
  with no back-references — a valid (merely uncompressed) snappy body.
  Prometheus's decoder inflates it like any other; the payload is
  small (tens of series) and the ledger's own Gorilla chunks are where
  real compression lives. Honesty over cleverness.

Every push outcome is counted (``tpu_ledger_remote_write_total``
{result=ok|error}); a dead endpoint costs one bounded timeout per
cadence tick and never touches the collect loop (the plane pushes on
the aggregator's fetch executor).
"""

from __future__ import annotations

import logging
import urllib.error
import urllib.request

from tpumon.backends.reflection import _encode_varint

log = logging.getLogger(__name__)

PUSH_ERRORS: tuple[type[BaseException], ...] = (
    urllib.error.URLError,
    OSError,
    ValueError,
)


def snappy_block(data: bytes) -> bytes:
    """``data`` as a valid snappy *block-format* body built from
    literal elements only (uncompressed-length preamble + literal
    chunks). Any conformant decoder round-trips it."""
    out = bytearray(_encode_varint(len(data)))
    idx = 0
    while idx < len(data):
        chunk = data[idx:idx + 65536]
        idx += len(chunk)
        n = len(chunk) - 1
        if n < 60:
            out.append(n << 2)
        elif n < 1 << 8:
            out.append(60 << 2)
            out.append(n)
        elif n < 1 << 16:
            out.append(61 << 2)
            out += n.to_bytes(2, "little")
        else:
            out.append(62 << 2)
            out += n.to_bytes(3, "little")
        out += chunk
    return bytes(out)


def _field(num: int, wire: int) -> bytes:
    return _encode_varint((num << 3) | wire)


def _len_delimited(num: int, payload: bytes) -> bytes:
    return _field(num, 2) + _encode_varint(len(payload)) + payload


def _label(name: str, value: str) -> bytes:
    return (
        _len_delimited(1, name.encode())
        + _len_delimited(2, value.encode())
    )


def _sample(value: float, ts_ms: int) -> bytes:
    import struct

    out = _field(1, 1) + struct.pack("<d", value)
    if ts_ms:
        out += _field(2, 0) + _encode_varint(ts_ms)
    return out


def encode_write_request(series: list[dict]) -> bytes:
    """``series``: ``[{"labels": {name: value}, "samples": [(ts_ms,
    value), ...]}, ...]`` -> serialized WriteRequest. Labels are sorted
    by name (the remote-write spec requires it; __name__ first falls
    out of plain byte order)."""
    body = bytearray()
    for row in series:
        ts_payload = bytearray()
        for name, value in sorted(row["labels"].items()):
            ts_payload += _len_delimited(1, _label(name, str(value)))
        for ts_ms, value in row["samples"]:
            ts_payload += _len_delimited(
                2, _sample(float(value), int(ts_ms))
            )
        body += _len_delimited(1, bytes(ts_payload))
    return bytes(body)


def push(url: str, series: list[dict], timeout: float = 5.0) -> None:
    """One remote-write POST (raises on failure — the caller counts).
    Deadline-bounded; 2xx is success, anything else raises."""
    payload = snappy_block(encode_write_request(series))
    request = urllib.request.Request(
        url,
        data=payload,
        headers={
            "Content-Type": "application/x-protobuf",
            "Content-Encoding": "snappy",
            "X-Prometheus-Remote-Write-Version": "0.1.0",
            "User-Agent": "tpumon-ledger/1.0",
        },
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=timeout) as resp:
        status = getattr(resp, "status", 200)
        if status // 100 != 2:
            raise ValueError(f"remote write status {status}")


__all__ = [
    "PUSH_ERRORS",
    "encode_write_request",
    "push",
    "snappy_block",
]
