"""Per-job goodput accounting: chip-seconds into honest buckets.

Every observed chip-second of every job (a job is a ``(pool, slice)``
identity — the granularity the hierarchy already rolls up at) is
assigned to exactly ONE bucket per accounting window:

- ``productive`` — steps advancing (or, for device-only nodes with no
  workload feed, duty above the idle floor: duty is then the only
  truth available, and the help text says so),
- ``checkpoint`` — a checkpoint-save span completed inside the window
  (tpu_lifecycle_checkpoints_total{op="save"} advanced),
- ``restore`` — a restore or elastic-resize transition window
  (reconfiguration time; resize rides this bucket by design),
- ``preempted`` — a preemption transition window,
- ``contended`` — collective-wait above the contention floor or an
  active straggler verdict: chips busy-waiting on the fabric, not
  computing,
- ``idle`` — visible, healthy, and doing nothing,
- ``unaccounted`` — the node was STALE or DARK for the window, or the
  aggregator itself was down (warm-restart gap): we could not see, so
  we say so. Partitions land HERE, never silently in ``idle`` — the
  same honesty stance as ``tpu_fleet_visibility_ratio``.

Conservation is the invariant everything else hangs on: per job,
``sum(buckets) == observed wall seconds × chips`` exactly, because
each feed's whole accounting window goes to one bucket and windows
tile the feed's observed lifetime (a per-feed watermark, no overlaps,
no holes). tests/test_ledger.py and ``soak.py --ledger`` both pin it.
"""

from __future__ import annotations

import threading

BUCKETS = (
    "productive",
    "checkpoint",
    "restore",
    "preempted",
    "idle",
    "contended",
    "unaccounted",
)

#: Transition kind -> bucket (tpu_lifecycle_events_total kinds).
_KIND_BUCKET = {
    "preemption": "preempted",
    "restore": "restore",
    "resize": "restore",
}


class _FeedState:
    __slots__ = (
        "watermark", "chips", "job", "events", "checkpoints",
        "last_kind",
    )

    def __init__(self, now: float) -> None:
        self.watermark = now
        self.chips = 0
        self.job: tuple[str, str] | None = None
        #: Last seen tpu_lifecycle_events_total counts by kind.
        self.events: dict[str, float] = {}
        #: Last seen tpu_lifecycle_checkpoints_total counts by op.
        self.checkpoints: dict[str, float] = {}
        #: Kind of the most recent transition counter advance — what an
        #: ACTIVE tpu_lifecycle_state window is attributed to.
        self.last_kind: str | None = None


class GoodputLedger:
    """Accumulates per-job bucket totals from fleet feed entries.

    Single-writer (the collect thread). ``account`` consumes the same
    ``(target, snap, state, ...)`` entries the incremental rollup
    reads, so the plane costs zero extra feed locks.
    """

    def __init__(
        self,
        contended_wait: float = 0.25,
        idle_duty_pct: float = 5.0,
        dollars_per_kwh: float = 0.0,
    ) -> None:
        self.contended_wait = contended_wait
        self.idle_duty_pct = idle_duty_pct
        #: Electricity price for the energy-dollars rows; 0 keeps every
        #: dollars surface ABSENT (a made-up price would be
        #: confidently-wrong cost accounting — the energy plane's
        #: stance, applied to the ledger).
        self.dollars_per_kwh = dollars_per_kwh
        #: One lock for the structural state: account() runs on the
        #: collect thread while jobs_doc()/totals() serve /ledger on
        #: HTTP threads — a new job appearing mid-iteration would
        #: otherwise RuntimeError the query.
        self._lock = threading.Lock()
        self._feeds: dict[str, _FeedState] = {}  # guarded-by: self._lock
        #: (pool, slice) -> {bucket: chip_seconds}.
        self._jobs: dict[tuple[str, str], dict[str, float]] = {}  # guarded-by: self._lock
        #: (pool, slice) -> [joules, modeled?] — node watts integrated
        #: over each feed's accounting windows (ROADMAP item 2
        #: follow-up: the energy plane's watts joined into the goodput
        #: rows). Kept BESIDE the bucket dict: joules are not
        #: chip-seconds and must never leak into conservation sums.
        self._job_energy: dict[tuple[str, str], list] = {}  # guarded-by: self._lock
        #: (pool, slice) -> workload class ("serve" | "train") — the
        #: percentile cohort key. Sticky once "serve": a serving job
        #: whose telemetry blips must not hop cohorts and reshuffle
        #: everyone else's percentile standing.
        self._job_class: dict[tuple[str, str], str] = {}  # guarded-by: self._lock
        #: Aggregator-blind seconds ledgered (warm-restart gaps).
        self.gap_seconds = 0.0  # guarded-by: self._lock

    # -- accounting ---------------------------------------------------------

    def account(self, entries: list[tuple], now: float) -> None:
        """One collect cycle: ``entries`` is ``[(target, snap|None,
        state, ...), ...]``. Each feed's window since its watermark is
        classified and charged to its job's bucket."""
        with self._lock:
            self._account_locked(entries, now)

    def _account_locked(self, entries: list[tuple], now: float) -> None:  # holds: self._lock
        seen = set()
        for entry in entries:
            target, snap, state = entry[0], entry[1], entry[2]
            seen.add(target)
            feed = self._feeds.get(target)
            if feed is None:
                feed = self._feeds[target] = _FeedState(now)
                self._observe_counters(feed, snap)
                self._update_identity(feed, snap)
                continue  # first sight anchors the watermark only
            dt = now - feed.watermark
            feed.watermark = now
            if dt <= 0:
                self._observe_counters(feed, snap)
                self._update_identity(feed, snap)
                continue
            bucket = self._classify(feed, snap, state)
            self._update_identity(feed, snap)
            if feed.job is not None and (snap or {}).get("serve"):
                self._job_class[feed.job] = "serve"
            if feed.job is not None and feed.chips > 0:
                job = self._jobs.setdefault(
                    feed.job, dict.fromkeys(BUCKETS, 0.0)
                )
                job[bucket] += dt * feed.chips
                # Energy join: the node's CURRENT watts integrate over
                # this window (visible windows only — an unaccounted
                # window invents no joules; that honesty already lives
                # in `state`). Worst-of provenance, like every energy
                # rollup.
                energy = (snap or {}).get("energy") if state == "up" else None
                if energy and energy.get("watts"):
                    row = self._job_energy.setdefault(
                        feed.job, [0.0, False]
                    )
                    row[0] += float(energy["watts"]) * dt
                    if energy.get("source") != "measured":
                        row[1] = True
        # Departed feeds (membership change / hand-back) stop accruing:
        # their job totals stay — the ledger is history, not state.
        for target in list(self._feeds):
            if target not in seen:
                del self._feeds[target]

    def _update_identity(self, feed: _FeedState, snap: dict | None) -> None:
        if not snap:
            return
        ident = snap.get("identity") or {}
        pool = ident.get("accelerator")
        slc = ident.get("slice")
        if pool or slc:
            feed.job = (pool or "unknown", slc or "?")
        chips = len(snap.get("chips") or ())
        if not chips:
            chips = int(snap.get("device_count") or 0)
        if chips:
            feed.chips = chips

    def _observe_counters(self, feed: _FeedState, snap: dict | None) -> None:
        """Track lifecycle/checkpoint counter advances; returns nothing
        — advances are recorded on the feed for _classify to read."""
        if not snap:
            return
        events = snap.get("lifecycle_events")
        if isinstance(events, dict):
            for kind, count in events.items():
                if count > feed.events.get(kind, 0.0):
                    feed.last_kind = kind
                feed.events[kind] = count
        ckpts = snap.get("checkpoints")
        if isinstance(ckpts, dict):
            feed.checkpoints = ckpts

    def _checkpoint_advanced(
        self, feed: _FeedState, snap: dict | None
    ) -> bool:
        if not snap:
            return False
        ckpts = snap.get("checkpoints")
        if not isinstance(ckpts, dict):
            return False
        prev = feed.checkpoints
        return ckpts.get("save", 0.0) > prev.get("save", 0.0)

    def _classify(
        self, feed: _FeedState, snap: dict | None, state: str
    ) -> str:
        """One feed window -> one bucket. Priority order IS the
        semantics: honesty first (can't see -> unaccounted), then
        explicit lifecycle windows, then checkpoint spans, then
        contention, then the productive/idle split."""
        if state != "up" or not snap:
            self._observe_counters(feed, snap)
            return "unaccounted"
        checkpoint = self._checkpoint_advanced(feed, snap)
        self._observe_counters(feed, snap)
        if snap.get("lifecycle_transition"):
            bucket = _KIND_BUCKET.get(feed.last_kind or "")
            if bucket is not None:
                return bucket
            # A transition window with no attributable kind (the feed
            # was adopted mid-window): reconfiguration-class.
            return "restore"
        if checkpoint:
            return "checkpoint"
        straggler = snap.get("straggler") or {}
        wait = snap.get("collective_wait")
        if straggler.get("active") or (
            wait is not None and wait >= self.contended_wait
        ):
            return "contended"
        duty = self._duty_mean(snap)
        step_rate = snap.get("step_rate")
        if step_rate is not None:
            if step_rate > 0.0:
                return "productive"
            return "idle" if (duty is None or duty < self.idle_duty_pct) \
                else "contended"
        if duty is not None and duty >= self.idle_duty_pct:
            # Device-only node (no workload feed): duty is the only
            # signal — busy chips count productive, and the family help
            # says so.
            return "productive"
        return "idle"

    @staticmethod
    def _duty_mean(snap: dict) -> float | None:
        total = 0.0
        n = 0
        for row in (snap.get("chips") or {}).values():
            duty = row.get("duty_pct")
            if duty is not None:
                total += duty
                n += 1
        return total / n if n else None

    def ledger_gap(self, seconds: float) -> None:
        """Aggregator-blind time (warm-restart gap): charged to every
        known job's ``unaccounted`` at its last-known chip count, and
        counted — gap seconds are ledgered, never interpolated away."""
        if seconds <= 0:
            return
        with self._lock:
            self.gap_seconds += seconds
            # Per-FEED charge (each feed contributes its own chips).
            for feed in self._feeds.values():
                if feed.job is None or feed.chips <= 0:
                    continue
                job = self._jobs.setdefault(
                    feed.job, dict.fromkeys(BUCKETS, 0.0)
                )
                job["unaccounted"] += seconds * feed.chips

    # -- read ---------------------------------------------------------------

    def jobs(self) -> dict[tuple[str, str], dict[str, float]]:
        """(pool, slice) -> bucket totals (chip-seconds). A shallow
        copy: the job set is iteration-safe for the caller; the inner
        bucket dicts are shared but key-stable (every bucket key is
        preset), so concurrent value updates read merely slightly
        stale, never torn."""
        with self._lock:
            return dict(self._jobs)

    def totals(self) -> dict[str, float]:
        out = dict.fromkeys(BUCKETS, 0.0)
        for buckets in self.jobs().values():
            for bucket, value in buckets.items():
                out[bucket] += value
        return out

    def job_energy(self) -> dict[tuple[str, str], tuple[float, bool]]:
        """(pool, slice) -> (joules, modeled?) — node watts integrated
        over the job's visible accounting windows."""
        with self._lock:
            return {
                job: (row[0], row[1])
                for job, row in self._job_energy.items()
            }

    def job_classes(self) -> dict[tuple[str, str], str]:
        """(pool, slice) -> workload class; jobs never seen serving
        default to "train" at read time (absent key, not stored)."""
        with self._lock:
            return dict(self._job_class)

    def dollars_of(self, joules: float) -> float | None:
        """Joules -> dollars at the configured $/kWh; None when no
        price is configured (dollars surfaces stay absent, never 0)."""
        if self.dollars_per_kwh <= 0:
            return None
        return joules / 3.6e6 * self.dollars_per_kwh

    def jobs_doc(self) -> list[dict]:
        """The /ledger?view=goodput rows: per-job splits with the
        conservation total spelled out, plus the energy join (joules
        always when observed; dollars only at a configured price)."""
        energy = self.job_energy()
        classes = self.job_classes()
        rows = []
        for (pool, slc), buckets in sorted(self.jobs().items()):
            total = sum(buckets.values())
            row = {
                "pool": pool,
                "slice": slc,
                "wclass": classes.get((pool, slc), "train"),
                "chip_seconds": total,
                "buckets": {k: buckets[k] for k in BUCKETS},
                "goodput_ratio": (
                    buckets["productive"] / total if total > 0 else None
                ),
            }
            joules_row = energy.get((pool, slc))
            if joules_row is not None:
                joules, modeled = joules_row
                row["energy_joules"] = joules
                row["energy_source"] = "modeled" if modeled else "measured"
                dollars = self.dollars_of(joules)
                if dollars is not None:
                    row["energy_dollars"] = dollars
            rows.append(row)
        return rows

    # -- spool round-trip ---------------------------------------------------

    def to_doc(self) -> dict:
        with self._lock:
            return {
                "jobs": [
                    {"pool": pool, "slice": slc, "buckets": dict(buckets)}
                    for (pool, slc), buckets in sorted(self._jobs.items())
                ],
                "energy": [
                    {"pool": pool, "slice": slc, "joules": row[0],
                     "modeled": bool(row[1])}
                    for (pool, slc), row in sorted(
                        self._job_energy.items()
                    )
                ],
                "classes": [
                    {"pool": pool, "slice": slc, "wclass": wclass}
                    for (pool, slc), wclass in sorted(
                        self._job_class.items()
                    )
                ],
                "feeds": {
                    target: {
                        "chips": feed.chips,
                        "job": list(feed.job) if feed.job else None,
                        "events": dict(feed.events),
                        "checkpoints": dict(feed.checkpoints),
                        "last_kind": feed.last_kind,
                    }
                    for target, feed in self._feeds.items()
                },
                "gap_seconds": self.gap_seconds,
            }

    def restore(self, doc: dict, now: float) -> None:
        """Rebuild totals + per-feed counter state from a spool doc.
        Watermarks restart at ``now`` — the plane separately ledgers
        the downtime gap via :meth:`ledger_gap`."""
        with self._lock:
            self._restore_locked(doc, now)

    def _restore_locked(self, doc: dict, now: float) -> None:  # holds: self._lock
        for row in doc.get("jobs", ()):
            try:
                job = (str(row["pool"]), str(row["slice"]))
                buckets = dict.fromkeys(BUCKETS, 0.0)
                for bucket, value in row["buckets"].items():
                    if bucket in buckets:
                        buckets[bucket] = float(value)
                self._jobs[job] = buckets
            except (KeyError, TypeError, ValueError):
                continue
        for row in doc.get("energy", ()):
            try:
                job = (str(row["pool"]), str(row["slice"]))
                self._job_energy[job] = [
                    float(row["joules"]), bool(row.get("modeled"))
                ]
            except (KeyError, TypeError, ValueError):
                continue
        for row in doc.get("classes", ()):
            try:
                job = (str(row["pool"]), str(row["slice"]))
                wclass = str(row["wclass"])
                if wclass == "serve":
                    self._job_class[job] = wclass
            except (KeyError, TypeError, ValueError):
                continue
        for target, row in (doc.get("feeds") or {}).items():
            try:
                feed = _FeedState(now)
                feed.chips = int(row.get("chips") or 0)
                job = row.get("job")
                if isinstance(job, list) and len(job) == 2:
                    feed.job = (str(job[0]), str(job[1]))
                if isinstance(row.get("events"), dict):
                    feed.events = {
                        str(k): float(v) for k, v in row["events"].items()
                    }
                if isinstance(row.get("checkpoints"), dict):
                    feed.checkpoints = {
                        str(k): float(v)
                        for k, v in row["checkpoints"].items()
                    }
                kind = row.get("last_kind")
                feed.last_kind = str(kind) if kind else None
                self._feeds[str(target)] = feed
            except (TypeError, ValueError):
                continue
        try:
            self.gap_seconds = float(doc.get("gap_seconds") or 0.0)
        except (TypeError, ValueError):
            self.gap_seconds = 0.0


__all__ = ["BUCKETS", "GoodputLedger"]
