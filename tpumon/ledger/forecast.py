"""Linear-trend capacity forecasting over the tiered ledger.

The capacity-planner question is "when does pool X run out of
headroom", and the honest answer is a least-squares line over the
coarse tier with a confidence band — or "insufficient history" when
the data cannot support a date. This module is pure math over
``(ts_s, value)`` point lists the :class:`TieredSeriesStore` already
serves; it never touches raw per-node series and never fabricates a
date: every gate that fails returns a status string instead of a
number.

Two signals per pool, each with its own saturation direction:

* ``hbm_headroom_ratio`` **falls** toward ``SATURATION_HEADROOM`` —
  memory pressure growing until allocations stop fitting.
* ``duty_cycle_percent`` **rises** toward ``SATURATION_DUTY`` — the
  pool compute-bound with no slack left for growth.

The pool's ``days_to_saturation`` is the minimum across signals that
produced a date (the first wall you hit is the one that matters).

Statuses are a closed vocabulary (tests pin it):

``ok``
    A date with a band: ``days_to_saturation`` plus ``days_lo`` /
    ``days_hi`` from the ±1.96·SE slope band.
``insufficient_history``
    Span or point count below the gate — the honest "come back later".
``stable``
    The fitted trend points AWAY from saturation (or is flat within
    the band): no date, and none should be alarmed into existence.
``saturated``
    The latest fitted value is already past the threshold: days 0.
"""

from __future__ import annotations

import math

__all__ = [
    "SATURATION_DUTY",
    "SATURATION_HEADROOM",
    "FORECAST_SIGNALS",
    "fit_trend",
    "forecast_signal",
    "forecast_pool",
]

#: Duty percent at which a pool counts as compute-saturated.
SATURATION_DUTY = 95.0
#: HBM headroom ratio at which a pool counts as memory-saturated.
SATURATION_HEADROOM = 0.05
#: 95% two-sided normal quantile for the slope confidence band.
_Z95 = 1.96
#: Forecasts further out than this are reported as ``stable`` — a
#: 10-year extrapolation from weeks of history is noise, not a date.
MAX_HORIZON_DAYS = 3650.0

#: family suffix -> (target value, direction toward saturation).
#: Direction +1 means the series rises into saturation, -1 falls.
FORECAST_SIGNALS: dict[str, tuple[float, int]] = {
    "tpu_fleet_duty_cycle_percent": (SATURATION_DUTY, +1),
    "tpu_fleet_hbm_headroom_ratio": (SATURATION_HEADROOM, -1),
}


def fit_trend(points: list) -> dict | None:
    """Ordinary least squares over ``(ts_s, value)`` points.

    Returns ``{"slope_per_s", "intercept", "t0", "stderr_slope",
    "residual_std", "n", "span_s"}`` with the intercept anchored at
    the first timestamp (``t0``), or ``None`` for fewer than 3 points
    or a degenerate (zero-span) time axis. ``stderr_slope`` is the
    standard error of the slope estimate — the band the caller widens
    a date with — and is 0.0 for a perfect fit.
    """
    n = len(points)
    if n < 3:
        return None
    t0 = points[0][0]
    xs = [p[0] - t0 for p in points]
    ys = [p[1] for p in points]
    span = xs[-1] - xs[0]
    if span <= 0.0:
        return None
    xbar = sum(xs) / n
    ybar = sum(ys) / n
    sxx = sum((x - xbar) ** 2 for x in xs)
    if sxx <= 0.0:
        return None
    sxy = sum((x - xbar) * (y - ybar) for x, y in zip(xs, ys))
    slope = sxy / sxx
    intercept = ybar - slope * xbar
    sse = sum((y - (intercept + slope * x)) ** 2
              for x, y in zip(xs, ys))
    if n > 2:
        residual_std = math.sqrt(max(sse, 0.0) / (n - 2))
    else:  # pragma: no cover - n >= 3 enforced above
        residual_std = 0.0
    stderr = residual_std / math.sqrt(sxx) if sxx > 0 else 0.0
    return {
        "slope_per_s": slope,
        "intercept": intercept,
        "t0": t0,
        "stderr_slope": stderr,
        "residual_std": residual_std,
        "n": n,
        "span_s": span,
    }


def _days_to_cross(
    current: float, target: float, slope_per_s: float, direction: int,
) -> float | None:
    """Days until the line from ``current`` crosses ``target`` moving
    in ``direction``, or None when the slope points the wrong way."""
    if direction > 0:
        if slope_per_s <= 0.0 or current >= target:
            return None
        gap = target - current
    else:
        if slope_per_s >= 0.0 or current <= target:
            return None
        gap = current - target
    seconds = gap / abs(slope_per_s)
    return seconds / 86400.0


def forecast_signal(
    points: list,
    *,
    target: float,
    direction: int,
    now_s: float,
    min_history_s: float,
    min_points: int = 8,
) -> dict:
    """Forecast one (pool, signal) series toward its saturation wall.

    ``points`` are (ts_s, value) in time order, normally the coarse
    tier's bucket means. The gates run in honesty order: history span
    first (never a date from sparse data), then fit viability, then
    direction. The returned dict always carries ``status``; numeric
    fields are present only when the status earns them.
    """
    doc: dict = {
        "status": "insufficient_history",
        "points": len(points),
        "history_s": round(points[-1][0] - points[0][0], 3)
        if len(points) >= 2 else 0.0,
        "target": target,
    }
    if len(points) < min_points or doc["history_s"] < min_history_s:
        return doc
    trend = fit_trend(points)
    if trend is None:
        return doc
    slope = trend["slope_per_s"]
    # Evaluate the LINE at now, not the last raw point: a noisy final
    # sample must not move the date the trend supports.
    current = trend["intercept"] + slope * (now_s - trend["t0"])
    doc.update(
        slope_per_day=slope * 86400.0,
        current=round(current, 6),
        stderr_slope_per_day=trend["stderr_slope"] * 86400.0,
        residual_std=round(trend["residual_std"], 6),
    )
    already = current >= target if direction > 0 else current <= target
    if already:
        doc["status"] = "saturated"
        doc["days_to_saturation"] = 0.0
        return doc
    days = _days_to_cross(current, target, slope, direction)
    if days is None or days > MAX_HORIZON_DAYS:
        doc["status"] = "stable"
        return doc
    # Confidence band: re-solve the crossing with the slope at each
    # edge of its ±1.96·SE interval. A slope whose interval includes
    # zero has an unbounded far edge — the band is honest about that
    # by leaving days_hi None ("could be never").
    lo_slope = slope - _Z95 * trend["stderr_slope"]
    hi_slope = slope + _Z95 * trend["stderr_slope"]
    steep, shallow = (hi_slope, lo_slope) if direction > 0 else (
        lo_slope, hi_slope)
    days_lo = _days_to_cross(current, target, steep, direction)
    days_hi = _days_to_cross(current, target, shallow, direction)
    doc["status"] = "ok"
    # 6 decimals of a day is ~0.1 s: precise enough that short-horizon
    # fits (soaks, tests) are not quantized into their own tolerance,
    # cheap enough to keep the JSON tidy.
    doc["days_to_saturation"] = round(days, 6)
    doc["days_lo"] = round(days_lo, 6) if days_lo is not None else round(
        days, 6)
    doc["days_hi"] = (
        round(days_hi, 6)
        if days_hi is not None and days_hi <= MAX_HORIZON_DAYS
        else None
    )
    return doc


def forecast_pool(
    series: dict,
    *,
    now_s: float,
    min_history_s: float,
    min_points: int = 8,
) -> dict:
    """Combine per-signal forecasts into one pool answer.

    ``series`` maps family name -> (ts_s, value) points for ONE pool.
    The pool's ``days_to_saturation`` is the minimum over signals
    whose status earned a date (``ok`` or ``saturated``); the pool
    status is ``ok`` when any signal produced a date,
    ``insufficient_history`` when every signal is gated (the honest
    aggregate), else ``stable``.
    """
    signals: dict[str, dict] = {}
    best: tuple[float, str] | None = None
    statuses = set()
    for family, (target, direction) in sorted(FORECAST_SIGNALS.items()):
        pts = series.get(family)
        if not pts:
            continue
        sig = forecast_signal(
            pts, target=target, direction=direction, now_s=now_s,
            min_history_s=min_history_s, min_points=min_points,
        )
        signals[family] = sig
        statuses.add(sig["status"])
        days = sig.get("days_to_saturation")
        if days is not None and (best is None or days < best[0]):
            best = (days, family)
    if not signals:
        return {"status": "insufficient_history", "signals": {}}
    if best is not None:
        lead = signals[best[1]]
        return {
            "status": "ok",
            "days_to_saturation": best[0],
            "days_lo": lead.get("days_lo", best[0]),
            "days_hi": lead.get("days_hi"),
            "leading_signal": best[1],
            "signals": signals,
        }
    if statuses == {"insufficient_history"}:
        return {"status": "insufficient_history", "signals": signals}
    return {"status": "stable", "signals": signals}
