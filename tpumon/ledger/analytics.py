"""The ledger's analytics read side: waste, percentiles, what-if.

Pure functions from documents the ledger already serves — goodput
rows (:meth:`GoodputLedger.jobs_doc`) and folded point lists
(:meth:`TieredSeriesStore.fold`) — into capacity-planner answers.
Nothing here touches raw samples or holds a lock; the plane calls
these under its own read path and the soak pins the invariants:

* **Conservation**: waste ranking redistributes the goodput rows'
  chip-seconds, so the sum over ALL groups equals the fleet total
  exactly (float-identical — same additions, reassociated per group),
  and the response carries both numbers so a client can assert it.
* **Absent, not zero**: what-if dollars exist only for rows with
  observed joules; a job with no energy join gets no dollars row.
* **Bounded**: top-k is a response bound by construction; the
  re-bucketing helpers operate on already-bounded fold pages.

Grammar tokens (shared with ``GET /ledger`` parsing):
``group_by=job|pool|slice``, ``bucket=1h|1d``, ``rank=topk:<n>``,
``stat=p50|p90|p99`` (percentile stats; the store's ``mean|min|max``
stay valid where they already were), and
``whatif=dollars_per_kwh:<v>``.
"""

from __future__ import annotations

__all__ = [
    "WASTE_BUCKETS",
    "GROUP_KEYS",
    "BUCKET_SPANS",
    "PCT_STATS",
    "percentile",
    "parse_rank",
    "parse_whatif",
    "rebucket",
    "rank_groups",
    "waste_doc",
    "percentiles_doc",
    "whatif_rows",
]

#: Goodput buckets that count as waste: chips held but not advancing
#: work — busy-waiting on the fabric or visibly doing nothing.
#: Unaccounted is NOT waste (we could not see; honesty bucket),
#: checkpoint/restore/preempted are lifecycle overhead, not waste a
#: job owner can act on the same way.
WASTE_BUCKETS = ("contended", "idle")

#: group_by vocabulary -> key function over a goodput row.
GROUP_KEYS = {
    "job": lambda row: f"{row['pool']}/{row['slice']}",
    "pool": lambda row: row["pool"],
    "slice": lambda row: row["slice"],
}

#: bucket vocabulary -> span in seconds.
BUCKET_SPANS = {"1h": 3600.0, "1d": 86400.0}

#: Percentile stats the grammar accepts (stat=p50 etc.).
PCT_STATS = {"p50": 50.0, "p90": 90.0, "p99": 99.0}


def percentile(values: list, q: float) -> float:
    """Linear-interpolated percentile over a non-empty value list
    (the numpy 'linear' method, hand-rolled: no numpy at runtime)."""
    ordered = sorted(values)
    if len(ordered) == 1:
        return ordered[0]
    rank = (q / 100.0) * (len(ordered) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(ordered) - 1)
    frac = rank - lo
    return ordered[lo] * (1.0 - frac) + ordered[hi] * frac


def parse_rank(raw: str) -> int | None:
    """``topk:<n>`` -> n (1..1000), else None (caller 400s)."""
    if not raw.startswith("topk:"):
        return None
    try:
        n = int(raw[len("topk:"):])
    except ValueError:
        return None
    return n if 1 <= n <= 1000 else None


def parse_whatif(raw: str) -> float | None:
    """``dollars_per_kwh:<v>`` -> v (> 0, finite), else None."""
    if not raw.startswith("dollars_per_kwh:"):
        return None
    try:
        price = float(raw[len("dollars_per_kwh:"):])
    except ValueError:
        return None
    if not (price > 0.0) or price != price or price == float("inf"):
        return None
    return price


def rebucket(
    points: list, span_s: float, stat: str,
) -> list:
    """Coarsen folded ``(ts_s, value)`` points into ``span_s`` buckets.

    ``stat`` is ``mean`` or a :data:`PCT_STATS` key, computed over the
    points landing in each bucket (bucket start = floor(ts / span)).
    Returns ``[(bucket_start_s, value, n), ...]`` in time order — n is
    the contributing point count, so a consumer can see a thin edge
    bucket for what it is instead of trusting it blindly.
    """
    cells: dict[float, list] = {}
    for ts, value in points:
        cells.setdefault(ts - ts % span_s, []).append(value)
    out = []
    for start in sorted(cells):
        vals = cells[start]
        if stat == "mean":
            value = sum(vals) / len(vals)
        else:
            value = percentile(vals, PCT_STATS[stat])
        out.append((start, value, len(vals)))
    return out


def rank_groups(series: dict, topk: int) -> list:
    """Order a fold's ``{group: [(ts, v), ...]}`` by mean value
    descending (ties broken by group key, so pages are stable) and
    keep the top ``topk`` group keys."""
    scored = []
    for group, points in series.items():
        if points:
            scored.append(
                (-(sum(v for _, v in points) / len(points)), group)
            )
    scored.sort()
    return [group for _, group in scored[:topk]]


def waste_doc(
    rows: list, group_by: str, topk: int, price: float | None = None,
) -> dict:
    """Top-k waste ranking over goodput rows.

    Waste = contended + idle chip-seconds, grouped by ``group_by`` and
    ranked descending. The conservation block sums chip-seconds over
    EVERY group (not just the page): by construction it equals the
    fleet total, and both numbers are in the response so the caller
    can hold the ledger to it. With ``price`` set, each group's
    observed joules are re-priced (what-if) — absent when no group
    member carried an energy join.
    """
    key_of = GROUP_KEYS[group_by]
    groups: dict[str, dict] = {}
    total_chip_seconds = 0.0
    for row in rows:
        acc = groups.setdefault(key_of(row), {
            "wasted_chip_seconds": 0.0, "chip_seconds": 0.0,
            "by_bucket": dict.fromkeys(WASTE_BUCKETS, 0.0),
            "energy_joules": None,
        })
        acc["chip_seconds"] += row["chip_seconds"]
        total_chip_seconds += row["chip_seconds"]
        for bucket in WASTE_BUCKETS:
            wasted = row["buckets"][bucket]
            acc["by_bucket"][bucket] += wasted
            acc["wasted_chip_seconds"] += wasted
        joules = row.get("energy_joules")
        if joules is not None:
            acc["energy_joules"] = (acc["energy_joules"] or 0.0) + joules
    ranked = sorted(
        groups.items(),
        key=lambda item: (-item[1]["wasted_chip_seconds"], item[0]),
    )
    out_rows = []
    for key, acc in ranked[:topk]:
        entry = {
            "key": key,
            "wasted_chip_seconds": acc["wasted_chip_seconds"],
            "wasted_chip_hours": acc["wasted_chip_seconds"] / 3600.0,
            "chip_seconds": acc["chip_seconds"],
            "waste_fraction": (
                acc["wasted_chip_seconds"] / acc["chip_seconds"]
                if acc["chip_seconds"] > 0 else None
            ),
            "by_bucket": acc["by_bucket"],
        }
        if acc["energy_joules"] is not None:
            entry["energy_joules"] = acc["energy_joules"]
            if price is not None:
                entry["whatif_dollars"] = (
                    acc["energy_joules"] / 3.6e6 * price
                )
        out_rows.append(entry)
    doc = {
        "group_by": group_by,
        "rank": f"topk:{topk}",
        "rows": out_rows,
        "groups_total": len(groups),
        "conservation": {
            "sum_groups_chip_seconds": sum(
                acc["chip_seconds"] for acc in groups.values()
            ),
            "total_chip_seconds": total_chip_seconds,
        },
    }
    if price is not None:
        doc["whatif"] = {"dollars_per_kwh": price}
    return doc


def percentiles_doc(rows: list, stats: list) -> dict:
    """Fleet-wide efficiency percentiles by workload class.

    Class = ``pool/wclass`` (the pool plus the serve/train preset
    label): a serving job is only ever compared against serving jobs
    on its own hardware. Each class reports the requested waste-
    fraction quantiles; each job reports its own waste fraction and
    its percentile standing within its class ("you are p90-wasteful"
    == ``pct_rank >= 90``). Jobs with zero observed chip-seconds are
    excluded — no standing can be honest about an empty denominator.
    """
    classes: dict[str, list] = {}
    job_rows = []
    for row in rows:
        if row["chip_seconds"] <= 0:
            continue
        wasted = sum(row["buckets"][b] for b in WASTE_BUCKETS)
        fraction = wasted / row["chip_seconds"]
        wclass = f"{row['pool']}/{row.get('wclass', 'train')}"
        classes.setdefault(wclass, []).append(fraction)
        job_rows.append({
            "pool": row["pool"],
            "slice": row["slice"],
            "class": wclass,
            "waste_fraction": fraction,
        })
    class_docs = {}
    for wclass, fractions in sorted(classes.items()):
        class_docs[wclass] = {
            "jobs": len(fractions),
            **{
                stat: percentile(fractions, PCT_STATS[stat])
                for stat in stats
            },
        }
    for job in job_rows:
        cohort = classes[job["class"]]
        # Percentile standing: the fraction of the cohort at or below
        # this job's waste (inclusive of self — a lone job is p100).
        at_or_below = sum(
            1 for f in cohort if f <= job["waste_fraction"]
        )
        job["pct_rank"] = 100.0 * at_or_below / len(cohort)
    job_rows.sort(key=lambda j: (-j["waste_fraction"], j["class"],
                                 j["slice"]))
    return {
        "stats": list(stats),
        "classes": class_docs,
        "jobs": job_rows,
    }


def whatif_rows(rows: list, price: float) -> list:
    """Re-price goodput rows' stored joules at ``price`` $/kWh
    without touching the configured price or any raw sample: each row
    with an energy join gains ``whatif_dollars``; rows without one
    are passed through untouched (absent, not zero)."""
    out = []
    for row in rows:
        joules = row.get("energy_joules")
        if joules is None:
            out.append(row)
            continue
        priced = dict(row)
        priced["whatif_dollars"] = joules / 3.6e6 * price
        out.append(priced)
    return out
