"""The aggregator-facing ledger plane: one ``cycle()`` per collect
cycle, exposition families, the ``GET /ledger`` range query, and the
warm-restart / remote-write plumbing.

Cost stance: the plane rides state the collect cycle already built —
the rollup doc (curated samples) and the feed entries (goodput
classification) — so it adds zero feed locks and zero upstream
fetches. Disk (spool save) and network (remote write) happen on the
aggregator's fetch executor, never on the collect thread, one in
flight at a time.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse

from tpumon.ledger.goodput import BUCKETS, GoodputLedger
from tpumon.ledger.store import (
    AGGS,
    LEDGER_FAMILY_SET,
    STATS,
    TieredSeriesStore,
    TierSpec,
    default_tiers,
)

#: ?by= grouping for aggregated range queries: the label(s) each output
#: series keeps. ``job`` is the (pool, slice) identity — the goodput
#: ledger's job key — so ``by=job`` and ``by=slice`` group identically
#: but read differently at call sites; ``none`` collapses everything
#: matched into one series.
GROUP_BYS = ("pool", "slice", "job", "none")

log = logging.getLogger(__name__)

#: Hard per-response point bound for /ledger (continuation tokens page
#: beyond it — the PR 4 bounded-replay stance applied to range reads).
QUERY_MAX_POINTS = 2000
QUERY_MAX_POINTS_CEILING = 20000


def _json_bytes(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True).encode()


class LedgerPlane:
    """Tiered store + goodput ledger wired for one aggregator shard."""

    def __init__(
        self,
        tiers: tuple[TierSpec, ...] | None = None,
        spool_dir: str = "",
        spool_max_bytes: int = 134217728,
        spool_every_s: float = 30.0,
        remote_write_url: str = "",
        remote_write_every_s: float = 30.0,
        remote_write_timeout: float = 5.0,
        contended_wait: float = 0.25,
        idle_duty_pct: float = 5.0,
        dollars_per_kwh: float = 0.0,
        clock=time.time,
    ) -> None:
        self._clock = clock
        self.tiers = tuple(tiers) if tiers else default_tiers()
        self.goodput = GoodputLedger(
            contended_wait=contended_wait, idle_duty_pct=idle_duty_pct,
            dollars_per_kwh=dollars_per_kwh,
        )
        self.spool = None
        self.spool_every_s = spool_every_s
        self._spool_last_save = 0.0
        #: True while a journal write is in flight (collect thread sets,
        #: executor worker clears — same one-bool discipline as the
        #: aggregator's snapshot spool).
        self._spool_saving = False
        self.spool_errors = {"load": 0, "write": 0}
        self.restored = False
        now = clock()
        if spool_dir:
            from tpumon.ledger.spool import LedgerSpool

            self.spool = LedgerSpool(
                spool_dir, max_bytes=spool_max_bytes, clock=clock
            )
            loaded = self.spool.load()
            if self.spool.last_load_error is not None:
                self.spool_errors["load"] += 1
            if loaded["saved_at"] > 0:
                self.store = TieredSeriesStore.from_doc(
                    loaded["store"], self.tiers
                )
                self.goodput.restore(loaded["goodput"], now)
                self.restored = True
                gap = now - loaded["saved_at"]
                if gap > 0:
                    # Downtime is LEDGERED: unaccounted chip-seconds
                    # for every known job, a counted gap — and no
                    # samples: the tiers simply hold nothing for the
                    # window (gaps are never interpolated).
                    self.goodput.ledger_gap(gap)
            else:
                self.store = TieredSeriesStore(self.tiers)
        else:
            self.store = TieredSeriesStore(self.tiers)
        self.remote_write_url = remote_write_url
        self.remote_write_every_s = remote_write_every_s
        self.remote_write_timeout = remote_write_timeout
        self._rw_last_push = 0.0
        self._rw_inflight = False
        self.remote_write_counts = {"ok": 0, "error": 0}
        #: Samples accumulated since the last remote-write push:
        #: {series_key: [(ts_ms, value), ...]} — bounded by dropping
        #: oldest entries past the cadence backlog cap.
        self._rw_pending: dict[tuple, list] = {}  # guarded-by: self._rw_lock
        self._rw_lock = threading.Lock()
        self.queries_total = 0
        self.last_cycle_samples = 0

    # -- collect-cycle hook -------------------------------------------------

    def cycle(self, now: float, doc: dict, entries: list, submit=None) -> None:
        """One collect cycle: account goodput over the feed entries,
        record the curated samples from the rollup doc, then (on their
        cadences, off-thread via ``submit``) journal and push."""
        self.goodput.account(entries, now)
        samples: dict[tuple, float] = {}
        for labels, bucket in self._rows(doc):
            for family, extract in LEDGER_FAMILY_SET.items():
                value = extract(bucket)
                if value is None:
                    continue
                samples[(family, *labels)] = float(value)
        self.store.record(now, samples)
        self.last_cycle_samples = len(samples)
        if self.remote_write_url:
            ts_ms = int(round(now * 1000.0))
            with self._rw_lock:
                for key, value in samples.items():
                    pending = self._rw_pending.setdefault(key, [])
                    pending.append((ts_ms, value))
                    # Backlog bound: a dead endpoint must not grow RSS.
                    if len(pending) > 600:
                        del pending[: len(pending) - 600]
            self._maybe_push(now, submit)
        self._maybe_spool(now, submit)

    @staticmethod
    def _rows(doc: dict):
        """(scope, pool, slice) rows of a rollup doc — slice, pool, and
        fleet scopes (the cross-shard global row is a per-shard VIEW of
        other shards' data; persisting it here would double-count on
        every shard)."""
        for (pool, slc), bucket in sorted(doc.get("slices", {}).items()):
            yield ("slice", pool, slc), bucket
        for pool, bucket in sorted(doc.get("pools", {}).items()):
            yield ("pool", pool, ""), bucket
        if doc.get("fleet"):
            yield ("fleet", "", ""), doc["fleet"]

    def _maybe_spool(self, now: float, submit=None) -> None:
        if self.spool is None:
            return
        if now - self._spool_last_save < self.spool_every_s:
            return
        if self._spool_saving:
            return
        self._spool_saving = True
        self._spool_last_save = now
        # Docs build on the collect thread (the store is single-writer
        # there — building on the executor would race appends); the
        # serialize+fsync goes off-thread.
        store_doc = self.store.to_doc()
        goodput_doc = self.goodput.to_doc()

        def save() -> None:
            try:
                if not self.spool.save(store_doc, goodput_doc):
                    self.spool_errors["write"] += 1
            except Exception:
                log.exception("ledger spool save failed")
                self.spool_errors["write"] += 1
            finally:
                self._spool_saving = False

        if submit is not None:
            submit(save)
        else:
            save()

    def _maybe_push(self, now: float, submit=None) -> None:
        if now - self._rw_last_push < self.remote_write_every_s:
            return
        if self._rw_inflight:
            return
        with self._rw_lock:
            pending = self._rw_pending
            self._rw_pending = {}
        self._rw_last_push = now
        if not pending:
            # Nothing accumulated: no POST happens, so no outcome is
            # counted — the ok/error counters reflect real pushes only.
            return
        self._rw_inflight = True
        series = [
            {
                "labels": {
                    "__name__": key[0],
                    "scope": key[1],
                    "pool": key[2],
                    "slice": key[3],
                },
                "samples": points,
            }
            for key, points in sorted(pending.items())
        ]

        def do_push() -> None:
            from tpumon.ledger.remote_write import PUSH_ERRORS, push

            try:
                push(
                    self.remote_write_url, series,
                    timeout=self.remote_write_timeout,
                )
                self.remote_write_counts["ok"] += 1
            except PUSH_ERRORS as exc:
                self.remote_write_counts["error"] += 1
                log.warning("ledger remote write failed: %s", exc)
            finally:
                self._rw_inflight = False

        if submit is not None:
            submit(do_push)
        else:
            do_push()

    def close(self) -> None:
        """Final synchronous journal (the aggregator drains its
        executor first, same as the snapshot spool)."""
        if self.spool is None:
            return
        try:
            if not self.spool.save(
                self.store.to_doc(), self.goodput.to_doc()
            ):
                self.spool_errors["write"] += 1
        except Exception:
            log.exception("final ledger spool save failed")
            self.spool_errors["write"] += 1

    # -- exposition ---------------------------------------------------------

    def families(self) -> list:
        """The ledger's exposition rows, rebuilt per collect cycle like
        every other fleet family."""
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        goodput = CounterMetricFamily(
            "tpu_fleet_goodput_chip_seconds",
            "Chip-seconds accounted per job (slice scope) and fleet-wide "
            "by goodput bucket: productive (steps advancing, or duty "
            "above the idle floor on device-only nodes), checkpoint, "
            "restore (incl. elastic resize), preempted, idle, contended "
            "(collective-wait/straggler), unaccounted (node stale/dark "
            "or aggregator blind — partitions land here, never in "
            "idle). Buckets sum to observed wall-clock x chips per job.",
            labels=("scope", "pool", "slice", "bucket"),
        )
        for (pool, slc), buckets in sorted(self.goodput.jobs().items()):
            for bucket in BUCKETS:
                goodput.add_metric(
                    ("slice", pool, slc, bucket), buckets[bucket]
                )
        for bucket, value in self.goodput.totals().items():
            goodput.add_metric(("fleet", "", "", bucket), value)
        energy_fams: list = []
        job_energy = self.goodput.job_energy()
        if job_energy:
            joules = CounterMetricFamily(
                "tpu_fleet_goodput_energy_joules",
                "Node energy attributed per job (scope=slice) and "
                "fleet-wide: watts integrated over each feed's visible "
                "goodput accounting windows (unaccounted windows "
                "invent no joules); source=measured only when every "
                "contributing window's power was device-reported.",
                labels=("scope", "pool", "slice", "source"),
            )
            fleet_joules = 0.0
            fleet_modeled = False
            for (pool, slc), (value, modeled) in sorted(
                job_energy.items()
            ):
                joules.add_metric(
                    ("slice", pool, slc,
                     "modeled" if modeled else "measured"),
                    value,
                )
                fleet_joules += value
                fleet_modeled = fleet_modeled or modeled
            joules.add_metric(
                ("fleet", "", "",
                 "modeled" if fleet_modeled else "measured"),
                fleet_joules,
            )
            energy_fams.append(joules)
            if self.goodput.dollars_per_kwh > 0:
                dollars = CounterMetricFamily(
                    "tpu_fleet_goodput_energy_dollars",
                    "Per-job energy cost at the configured "
                    "TPUMON_FLEET_LEDGER_DOLLARS_PER_KWH price; absent "
                    "(never 0) when no price is configured — a made-up "
                    "price would be confidently-wrong cost accounting.",
                    labels=("scope", "pool", "slice"),
                )
                for (pool, slc), (value, _modeled) in sorted(
                    job_energy.items()
                ):
                    dollars.add_metric(
                        ("slice", pool, slc),
                        self.goodput.dollars_of(value),
                    )
                dollars.add_metric(
                    ("fleet", "", ""),
                    self.goodput.dollars_of(fleet_joules),
                )
                energy_fams.append(dollars)
        stats = self.store.stats()
        series = GaugeMetricFamily(
            "tpu_ledger_series",
            "Distinct series stored per ledger tier.",
            labels=("tier",),
        )
        samples = CounterMetricFamily(
            "tpu_ledger_samples",
            "Samples recorded into each ledger tier since start "
            "(aggregate tiers count finalized buckets).",
            labels=("tier",),
        )
        nbytes = GaugeMetricFamily(
            "tpu_ledger_bytes",
            "Sealed compressed bytes held per ledger tier (open buffers "
            "excluded; the bench's bytes-per-sample headline divides "
            "this by the raw samples the tier's window covers).",
            labels=("tier",),
        )
        for idx, tier in enumerate(stats["tiers"]):
            series.add_metric((tier["name"],), float(tier["series"]))
            samples.add_metric(
                (tier["name"],), float(self.store.samples_total[idx])
            )
            nbytes.add_metric((tier["name"],), float(tier["sealed_bytes"]))
        dropped = CounterMetricFamily(
            "tpu_ledger_dropped_chunks",
            "Sealed chunks dropped by bound (retention age / tier byte "
            "budget) — the ledger is bounded by construction, and drops "
            "are counted, never silent.",
            labels=("reason",),
        )
        for reason, count in sorted(stats["dropped_chunks"].items()):
            dropped.add_metric((reason,), float(count))
        gap = CounterMetricFamily(
            "tpu_ledger_gap_seconds",
            "Wall seconds the ledger could not observe (aggregator "
            "restarts between spool saves): ledgered into the "
            "unaccounted goodput bucket, never interpolated into "
            "samples.",
            labels=(),
        )
        gap.add_metric((), self.goodput.gap_seconds)
        queries = CounterMetricFamily(
            "tpu_ledger_queries",
            "GET /ledger range queries served.",
            labels=(),
        )
        queries.add_metric((), float(self.queries_total))
        out = [goodput, *energy_fams, series, samples, nbytes, dropped,
               gap, queries]
        if self.spool is not None:
            spool_errors = CounterMetricFamily(
                "tpu_ledger_spool_errors",
                "Ledger spool failures by op (load / write); the plane "
                "runs on, memory-only.",
                labels=("op",),
            )
            for op, count in sorted(self.spool_errors.items()):
                spool_errors.add_metric((op,), float(count))
            out.append(spool_errors)
        if self.remote_write_url:
            rw = CounterMetricFamily(
                "tpu_ledger_remote_write",
                "Remote-write push outcomes (result ∈ ok/error); absent "
                "unless TPUMON_FLEET_LEDGER_REMOTE_WRITE_URL is set.",
                labels=("result",),
            )
            for result, count in sorted(self.remote_write_counts.items()):
                rw.add_metric((result,), float(count))
            out.append(rw)
        return out

    # -- /ledger ------------------------------------------------------------

    def query_response(self, query_string: str) -> tuple[bytes, str]:
        """(body, status) for one GET /ledger. Three shapes:

        - no parameters: the index (families, tiers, occupancy,
          goodput totals);
        - ``?view=goodput``: per-job bucket splits + conservation
          (plus the energy joules/dollars join when observed);
        - ``?family=...``: a range query — ``scope`` (slice/pool/fleet),
          optional ``pool``/``slice`` filters, ``start``/``end`` epoch
          seconds (default: the last hour), ``step`` seconds (tier
          selection hint), ``stat`` (mean/min/max at aggregate tiers),
          ``max_points`` (server-capped). Bounded responses carry
          ``next_start`` continuation cursors.
        - ``?family=...&agg=sum|mean|max[&by=pool|slice|job|none]``:
          SERVER-SIDE aggregation — the matched series fold across
          each other inside the read path (decode → aggregate →
          re-emit; the raw range is never materialized), one output
          series per ``by`` group. Byte-stable vs aggregating the raw
          range client-side (tests pin it), so consumers stop shipping
          per-slice series to compute a per-pool number.
        """
        self.queries_total += 1
        try:
            params = dict(urllib.parse.parse_qsl(query_string))
        except ValueError:
            return _json_bytes({"error": "unparseable query"}), "400 Bad Request"
        if params.get("view") == "goodput":
            return _json_bytes({
                "now": self._clock(),
                "buckets": list(BUCKETS),
                "jobs": self.goodput.jobs_doc(),
                "totals": self.goodput.totals(),
                "gap_seconds": self.goodput.gap_seconds,
                "dollars_per_kwh": self.goodput.dollars_per_kwh,
            }), "200 OK"
        family = params.get("family")
        if not family:
            return _json_bytes(self._index_doc()), "200 OK"
        if family not in LEDGER_FAMILY_SET:
            return _json_bytes({
                "error": f"unknown family {family!r}",
                "families": sorted(LEDGER_FAMILY_SET),
            }), "400 Bad Request"
        now = self._clock()
        try:
            end = float(params.get("end", now))
            start = float(params.get("start", end - 3600.0))
            step = float(params["step"]) if "step" in params else None
            max_points = int(params.get("max_points", QUERY_MAX_POINTS))
        except ValueError:
            return _json_bytes(
                {"error": "malformed numeric parameter"}
            ), "400 Bad Request"
        if start >= end:
            return _json_bytes(
                {"error": "start must be before end"}
            ), "400 Bad Request"
        stat = params.get("stat", "mean")
        if stat not in STATS:
            return _json_bytes(
                {"error": f"stat must be one of {STATS}"}
            ), "400 Bad Request"
        max_points = max(1, min(max_points, QUERY_MAX_POINTS_CEILING))
        scope = params.get("scope", "fleet")
        tier_idx = self.store.pick_tier(start, now, step)
        spec = self.store.tiers[tier_idx]
        keys = [
            key for key in self.store.series_keys()
            if key[0] == family and key[1] == scope
            and ("pool" not in params or key[2] == params["pool"])
            and ("slice" not in params or key[3] == params["slice"])
        ]
        agg = params.get("agg")
        if agg is not None:
            if agg not in AGGS:
                return _json_bytes(
                    {"error": f"agg must be one of {AGGS}"}
                ), "400 Bad Request"
            by = params.get("by", "none")
            if by not in GROUP_BYS:
                return _json_bytes(
                    {"error": f"by must be one of {GROUP_BYS}"}
                ), "400 Bad Request"
            if by == "pool":
                def group_of(key):
                    return (key[2], "")
            elif by in ("slice", "job"):
                def group_of(key):
                    return (key[2], key[3])
            else:
                def group_of(key):
                    return ("", "")
            groups, agg_next = self.store.fold(
                keys, tier_idx, start, end,
                stat=stat, agg=agg, group_of=group_of,
                max_points=max_points,
            )
            doc = {
                "family": family,
                "tier": spec.name,
                "resolution_s": spec.resolution_s,
                "agg": agg,
                "by": by,
                "start": start,
                "end": end,
                "series": [
                    {
                        "pool": pool,
                        "slice": slc,
                        "stat": "raw" if tier_idx == 0 else stat,
                        "agg": agg,
                        "points": [
                            [round(ts, 3), value] for ts, value in points
                        ],
                    }
                    for (pool, slc), points in sorted(groups.items())
                ],
            }
            if agg_next is not None:
                doc["truncated"] = True
                doc["next_start"] = agg_next
            return _json_bytes(doc), "200 OK"
        series = []
        remaining = max_points
        next_start = None
        for key in keys:
            if remaining <= 0:
                # Whole-series truncation: continuation resumes at the
                # window start for the series we never reached.
                next_start = start if next_start is None else min(
                    next_start, start
                )
                break
            points, cursor = self.store.query(
                key, tier_idx, start, end, stat=stat, max_points=remaining
            )
            remaining -= len(points)
            if cursor is not None:
                next_start = cursor if next_start is None else min(
                    next_start, cursor
                )
            series.append({
                "scope": key[1],
                "pool": key[2],
                "slice": key[3],
                "stat": "raw" if tier_idx == 0 else stat,
                "points": [[round(ts, 3), value] for ts, value in points],
            })
        doc = {
            "family": family,
            "tier": spec.name,
            "resolution_s": spec.resolution_s,
            "start": start,
            "end": end,
            "series": series,
        }
        if next_start is not None:
            doc["truncated"] = True
            doc["next_start"] = next_start
        return _json_bytes(doc), "200 OK"

    def _index_doc(self) -> dict:
        stats = self.store.stats()
        return {
            "now": self._clock(),
            "families": sorted(LEDGER_FAMILY_SET),
            "tiers": stats["tiers"],
            "dropped_chunks": stats["dropped_chunks"],
            "goodput_totals": self.goodput.totals(),
            "gap_seconds": self.goodput.gap_seconds,
            "restored": self.restored,
        }

    def debug_block(self) -> dict:
        stats = self.store.stats()
        block = {
            "tiers": stats["tiers"],
            "dropped_chunks": stats["dropped_chunks"],
            "last_cycle_samples": self.last_cycle_samples,
            "gap_seconds": self.goodput.gap_seconds,
            "jobs": len(self.goodput.jobs()),
            "queries": self.queries_total,
            "restored": self.restored,
        }
        if self.spool is not None:
            block["spool"] = {
                "path": self.spool.path,
                "last_write_ts": self.spool.last_write_ts,
                "errors": dict(self.spool_errors),
            }
        if self.remote_write_url:
            block["remote_write"] = dict(self.remote_write_counts)
        return block


__all__ = ["LedgerPlane", "QUERY_MAX_POINTS"]
