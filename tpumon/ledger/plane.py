"""The aggregator-facing ledger plane: one ``cycle()`` per collect
cycle, exposition families, the ``GET /ledger`` range query, and the
warm-restart / remote-write plumbing.

Cost stance: the plane rides state the collect cycle already built —
the rollup doc (curated samples) and the feed entries (goodput
classification) — so it adds zero feed locks and zero upstream
fetches. Disk (spool save) and network (remote write) happen on the
aggregator's fetch executor, never on the collect thread, one in
flight at a time.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse

from tpumon.ledger import analytics
from tpumon.ledger.forecast import FORECAST_SIGNALS, forecast_pool
from tpumon.ledger.goodput import BUCKETS, GoodputLedger
from tpumon.ledger.store import (
    AGGS,
    LEDGER_FAMILY_SET,
    STATS,
    TieredSeriesStore,
    TierSpec,
    default_tiers,
)

#: ?by= grouping for aggregated range queries: the label(s) each output
#: series keeps. ``job`` is the (pool, slice) identity — the goodput
#: ledger's job key — so ``by=job`` and ``by=slice`` group identically
#: but read differently at call sites; ``none`` collapses everything
#: matched into one series.
GROUP_BYS = ("pool", "slice", "job", "none")

log = logging.getLogger(__name__)

#: Hard per-response point bound for /ledger (continuation tokens page
#: beyond it — the PR 4 bounded-replay stance applied to range reads).
QUERY_MAX_POINTS = 2000
QUERY_MAX_POINTS_CEILING = 20000

#: /ledger view vocabulary (anything else 400s with this list).
VIEWS = ("goodput", "waste", "percentiles", "forecast")

#: Points fed to one (pool, signal) least-squares fit — 14 days of
#: 5-minute buckets is 4032, well inside this.
FORECAST_MAX_POINTS = 8192


def _json_bytes(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True).encode()


class LedgerPlane:
    """Tiered store + goodput ledger wired for one aggregator shard."""

    def __init__(
        self,
        tiers: tuple[TierSpec, ...] | None = None,
        spool_dir: str = "",
        spool_max_bytes: int = 134217728,
        spool_every_s: float = 30.0,
        remote_write_url: str = "",
        remote_write_every_s: float = 30.0,
        remote_write_timeout: float = 5.0,
        contended_wait: float = 0.25,
        idle_duty_pct: float = 5.0,
        dollars_per_kwh: float = 0.0,
        forecast_min_history_s: float = 21600.0,
        forecast_every_s: float = 60.0,
        forecast_min_points: int = 8,
        clock=time.time,
    ) -> None:
        self._clock = clock
        self.tiers = tuple(tiers) if tiers else default_tiers()
        self.goodput = GoodputLedger(
            contended_wait=contended_wait, idle_duty_pct=idle_duty_pct,
            dollars_per_kwh=dollars_per_kwh,
        )
        self.spool = None
        self.spool_every_s = spool_every_s
        self._spool_last_save = 0.0
        #: True while a journal write is in flight (collect thread sets,
        #: executor worker clears — same one-bool discipline as the
        #: aggregator's snapshot spool).
        self._spool_saving = False
        self.spool_errors = {"load": 0, "write": 0, "enospc": 0}
        self.restored = False
        now = clock()
        if spool_dir:
            from tpumon.ledger.spool import LedgerSpool

            self.spool = LedgerSpool(
                spool_dir, max_bytes=spool_max_bytes, clock=clock
            )
            loaded = self.spool.load()
            if self.spool.last_load_error is not None:
                self.spool_errors["load"] += 1
            if loaded["saved_at"] > 0:
                self.store = TieredSeriesStore.from_doc(
                    loaded["store"], self.tiers
                )
                self.goodput.restore(loaded["goodput"], now)
                self.restored = True
                gap = now - loaded["saved_at"]
                if gap > 0:
                    # Downtime is LEDGERED: unaccounted chip-seconds
                    # for every known job, a counted gap — and no
                    # samples: the tiers simply hold nothing for the
                    # window (gaps are never interpolated).
                    self.goodput.ledger_gap(gap)
            else:
                self.store = TieredSeriesStore(self.tiers)
        else:
            self.store = TieredSeriesStore(self.tiers)
        self.remote_write_url = remote_write_url
        self.remote_write_every_s = remote_write_every_s
        self.remote_write_timeout = remote_write_timeout
        self._rw_last_push = 0.0
        self._rw_inflight = False
        self.remote_write_counts = {"ok": 0, "error": 0}
        #: Samples accumulated since the last remote-write push:
        #: {series_key: [(ts_ms, value), ...]} — bounded by dropping
        #: oldest entries past the cadence backlog cap.
        self._rw_pending: dict[tuple, list] = {}  # guarded-by: self._rw_lock
        self._rw_lock = threading.Lock()
        self.queries_total = 0
        self.last_cycle_samples = 0
        #: Capacity forecasting (tpumon/ledger/forecast.py): recomputed
        #: on its own cadence inside cycle(), read lock-free by /ledger,
        #: families(), and the External Metrics provider — the dict is
        #: rebuilt and swapped atomically, never mutated in place.
        self.forecast_min_history_s = forecast_min_history_s
        self.forecast_every_s = forecast_every_s
        self.forecast_min_points = forecast_min_points
        self._forecasts: dict[str, dict] = {}
        self._forecast_ts = 0.0

    # -- collect-cycle hook -------------------------------------------------

    def cycle(self, now: float, doc: dict, entries: list, submit=None) -> None:
        """One collect cycle: account goodput over the feed entries,
        record the curated samples from the rollup doc, then (on their
        cadences, off-thread via ``submit``) journal and push."""
        self.goodput.account(entries, now)
        samples: dict[tuple, float] = {}
        for labels, bucket in self._rows(doc):
            for family, extract in LEDGER_FAMILY_SET.items():
                value = extract(bucket)
                if value is None:
                    continue
                samples[(family, *labels)] = float(value)
        self.store.record(now, samples)
        self.last_cycle_samples = len(samples)
        if self.remote_write_url:
            ts_ms = int(round(now * 1000.0))
            with self._rw_lock:
                for key, value in samples.items():
                    pending = self._rw_pending.setdefault(key, [])
                    pending.append((ts_ms, value))
                    # Backlog bound: a dead endpoint must not grow RSS.
                    if len(pending) > 600:
                        del pending[: len(pending) - 600]
            self._maybe_push(now, submit)
        self._maybe_spool(now, submit)
        if now - self._forecast_ts >= self.forecast_every_s:
            self._forecast_ts = now
            self._forecasts = self._compute_forecasts(now)

    # -- forecasting --------------------------------------------------------

    def _compute_forecasts(self, now: float) -> dict[str, dict]:
        """Per-pool saturation forecasts off the tiered store.

        The fit window is 8× the minimum-history gate, so the tier the
        fit reads follows history depth: a fleet with weeks of history
        fits the 5-minute tier; one below the gate answers
        "insufficient history" from whatever the fine tiers hold —
        never a fabricated date.
        """
        start = now - 8.0 * self.forecast_min_history_s
        tier_idx = self.store.pick_tier(start, now, None)
        pools = sorted({
            key[2] for key in self.store.series_keys()
            if key[1] == "pool" and key[0] in FORECAST_SIGNALS
        })
        out: dict[str, dict] = {}
        for pool in pools:
            series: dict[str, list] = {}
            for family in FORECAST_SIGNALS:
                points, _cursor = self.store.query(
                    (family, "pool", pool, ""), tier_idx, start, now,
                    stat="mean", max_points=FORECAST_MAX_POINTS,
                )
                if points:
                    series[family] = points
            if series:
                out[pool] = forecast_pool(
                    series, now_s=now,
                    min_history_s=self.forecast_min_history_s,
                    min_points=self.forecast_min_points,
                )
        return out

    def forecasts(self) -> dict[str, dict]:
        """pool -> forecast doc (see :func:`forecast_pool`), as of the
        last forecast cadence tick; the External Metrics adapter and
        /ledger?view=forecast both read this."""
        return self._forecasts

    def forecast_snapshot(self) -> tuple[dict[str, dict], float]:
        """(forecasts, computed_at) — the External Metrics adapter's
        provider shape, so items carry the compute timestamp rather
        than re-stamping served values as current."""
        return self._forecasts, self._forecast_ts

    @staticmethod
    def _rows(doc: dict):
        """(scope, pool, slice) rows of a rollup doc — slice, pool, and
        fleet scopes (the cross-shard global row is a per-shard VIEW of
        other shards' data; persisting it here would double-count on
        every shard)."""
        for (pool, slc), bucket in sorted(doc.get("slices", {}).items()):
            yield ("slice", pool, slc), bucket
        for pool, bucket in sorted(doc.get("pools", {}).items()):
            yield ("pool", pool, ""), bucket
        if doc.get("fleet"):
            yield ("fleet", "", ""), doc["fleet"]

    def _maybe_spool(self, now: float, submit=None) -> None:
        if self.spool is None:
            return
        if now - self._spool_last_save < self.spool_every_s:
            return
        if self._spool_saving:
            return
        self._spool_saving = True
        self._spool_last_save = now
        # Docs build on the collect thread (the store is single-writer
        # there — building on the executor would race appends); the
        # serialize+fsync goes off-thread.
        store_doc = self.store.to_doc()
        goodput_doc = self.goodput.to_doc()

        def save() -> None:
            try:
                was_degraded = self.spool.degraded
                ok = self.spool.save(store_doc, goodput_doc)
                if self.spool.degraded and not was_degraded:
                    # Once per degradation transition, not per skipped
                    # memory-only tick (mirrors the snapshot spool).
                    self.spool_errors["enospc"] += 1
                elif not ok and not self.spool.degraded:
                    self.spool_errors["write"] += 1
            except Exception:
                log.exception("ledger spool save failed")
                self.spool_errors["write"] += 1
            finally:
                self._spool_saving = False

        if submit is not None:
            submit(save)
        else:
            save()

    def _maybe_push(self, now: float, submit=None) -> None:
        if now - self._rw_last_push < self.remote_write_every_s:
            return
        if self._rw_inflight:
            return
        with self._rw_lock:
            pending = self._rw_pending
            self._rw_pending = {}
        self._rw_last_push = now
        if not pending:
            # Nothing accumulated: no POST happens, so no outcome is
            # counted — the ok/error counters reflect real pushes only.
            return
        self._rw_inflight = True
        series = [
            {
                "labels": {
                    "__name__": key[0],
                    "scope": key[1],
                    "pool": key[2],
                    "slice": key[3],
                },
                "samples": points,
            }
            for key, points in sorted(pending.items())
        ]

        def do_push() -> None:
            from tpumon.ledger.remote_write import PUSH_ERRORS, push

            try:
                push(
                    self.remote_write_url, series,
                    timeout=self.remote_write_timeout,
                )
                self.remote_write_counts["ok"] += 1
            except PUSH_ERRORS as exc:
                self.remote_write_counts["error"] += 1
                log.warning("ledger remote write failed: %s", exc)
            finally:
                self._rw_inflight = False

        if submit is not None:
            submit(do_push)
        else:
            do_push()

    def close(self) -> None:
        """Final synchronous journal (the aggregator drains its
        executor first, same as the snapshot spool)."""
        if self.spool is None:
            return
        try:
            was_degraded = self.spool.degraded
            ok = self.spool.save(self.store.to_doc(), self.goodput.to_doc())
            if self.spool.degraded and not was_degraded:
                self.spool_errors["enospc"] += 1
            elif not ok and not self.spool.degraded:
                self.spool_errors["write"] += 1
        except Exception:
            log.exception("final ledger spool save failed")
            self.spool_errors["write"] += 1

    # -- exposition ---------------------------------------------------------

    def families(self) -> list:
        """The ledger's exposition rows, rebuilt per collect cycle like
        every other fleet family."""
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        goodput = CounterMetricFamily(
            "tpu_fleet_goodput_chip_seconds",
            "Chip-seconds accounted per job (slice scope) and fleet-wide "
            "by goodput bucket: productive (steps advancing, or duty "
            "above the idle floor on device-only nodes), checkpoint, "
            "restore (incl. elastic resize), preempted, idle, contended "
            "(collective-wait/straggler), unaccounted (node stale/dark "
            "or aggregator blind — partitions land here, never in "
            "idle). Buckets sum to observed wall-clock x chips per job.",
            labels=("scope", "pool", "slice", "bucket"),
        )
        for (pool, slc), buckets in sorted(self.goodput.jobs().items()):
            for bucket in BUCKETS:
                goodput.add_metric(
                    ("slice", pool, slc, bucket), buckets[bucket]
                )
        for bucket, value in self.goodput.totals().items():
            goodput.add_metric(("fleet", "", "", bucket), value)
        energy_fams: list = []
        job_energy = self.goodput.job_energy()
        if job_energy:
            joules = CounterMetricFamily(
                "tpu_fleet_goodput_energy_joules",
                "Node energy attributed per job (scope=slice) and "
                "fleet-wide: watts integrated over each feed's visible "
                "goodput accounting windows (unaccounted windows "
                "invent no joules); source=measured only when every "
                "contributing window's power was device-reported.",
                labels=("scope", "pool", "slice", "source"),
            )
            fleet_joules = 0.0
            fleet_modeled = False
            for (pool, slc), (value, modeled) in sorted(
                job_energy.items()
            ):
                joules.add_metric(
                    ("slice", pool, slc,
                     "modeled" if modeled else "measured"),
                    value,
                )
                fleet_joules += value
                fleet_modeled = fleet_modeled or modeled
            joules.add_metric(
                ("fleet", "", "",
                 "modeled" if fleet_modeled else "measured"),
                fleet_joules,
            )
            energy_fams.append(joules)
            if self.goodput.dollars_per_kwh > 0:
                dollars = CounterMetricFamily(
                    "tpu_fleet_goodput_energy_dollars",
                    "Per-job energy cost at the configured "
                    "TPUMON_FLEET_LEDGER_DOLLARS_PER_KWH price; absent "
                    "(never 0) when no price is configured — a made-up "
                    "price would be confidently-wrong cost accounting.",
                    labels=("scope", "pool", "slice"),
                )
                for (pool, slc), (value, _modeled) in sorted(
                    job_energy.items()
                ):
                    dollars.add_metric(
                        ("slice", pool, slc),
                        self.goodput.dollars_of(value),
                    )
                dollars.add_metric(
                    ("fleet", "", ""),
                    self.goodput.dollars_of(fleet_joules),
                )
                energy_fams.append(dollars)
        stats = self.store.stats()
        series = GaugeMetricFamily(
            "tpu_ledger_series",
            "Distinct series stored per ledger tier.",
            labels=("tier",),
        )
        samples = CounterMetricFamily(
            "tpu_ledger_samples",
            "Samples recorded into each ledger tier since start "
            "(aggregate tiers count finalized buckets).",
            labels=("tier",),
        )
        nbytes = GaugeMetricFamily(
            "tpu_ledger_bytes",
            "Sealed compressed bytes held per ledger tier (open buffers "
            "excluded; the bench's bytes-per-sample headline divides "
            "this by the raw samples the tier's window covers).",
            labels=("tier",),
        )
        for idx, tier in enumerate(stats["tiers"]):
            series.add_metric((tier["name"],), float(tier["series"]))
            samples.add_metric(
                (tier["name"],), float(self.store.samples_total[idx])
            )
            nbytes.add_metric((tier["name"],), float(tier["sealed_bytes"]))
        dropped = CounterMetricFamily(
            "tpu_ledger_dropped_chunks",
            "Sealed chunks dropped by bound (retention age / tier byte "
            "budget) — the ledger is bounded by construction, and drops "
            "are counted, never silent.",
            labels=("reason",),
        )
        for reason, count in sorted(stats["dropped_chunks"].items()):
            dropped.add_metric((reason,), float(count))
        gap = CounterMetricFamily(
            "tpu_ledger_gap_seconds",
            "Wall seconds the ledger could not observe (aggregator "
            "restarts between spool saves): ledgered into the "
            "unaccounted goodput bucket, never interpolated into "
            "samples.",
            labels=(),
        )
        gap.add_metric((), self.goodput.gap_seconds)
        queries = CounterMetricFamily(
            "tpu_ledger_queries",
            "GET /ledger range queries served.",
            labels=(),
        )
        queries.add_metric((), float(self.queries_total))
        analytics_fams: list = []
        jobs = self.goodput.jobs()
        if jobs:
            waste = CounterMetricFamily(
                "tpu_fleet_waste_chip_seconds",
                "Wasted chip-seconds per job (scope=slice) and "
                "fleet-wide: the contended + idle goodput buckets — "
                "chips held but not advancing work. A strict subset of "
                "tpu_fleet_goodput_chip_seconds, so it conserves "
                "against the same totals.",
                labels=("scope", "pool", "slice"),
            )
            fleet_waste = 0.0
            for (pool, slc), buckets in sorted(jobs.items()):
                wasted = sum(
                    buckets[b] for b in analytics.WASTE_BUCKETS
                )
                waste.add_metric(("slice", pool, slc), wasted)
                fleet_waste += wasted
            waste.add_metric(("fleet", "", ""), fleet_waste)
            analytics_fams.append(waste)
            pct = analytics.percentiles_doc(
                self.goodput.jobs_doc(), list(analytics.PCT_STATS)
            )
            if pct["classes"]:
                quantiles = GaugeMetricFamily(
                    "tpu_fleet_waste_fraction_quantile",
                    "Waste-fraction quantiles (p50/p90/p99) per "
                    "workload class (pool/serve-or-train): the cohort "
                    "a job's percentile standing is computed against "
                    "in /ledger?view=percentiles.",
                    labels=("wclass", "quantile"),
                )
                for wclass, row in sorted(pct["classes"].items()):
                    for stat in analytics.PCT_STATS:
                        quantiles.add_metric((wclass, stat), row[stat])
                analytics_fams.append(quantiles)
        forecasts = self.forecasts()
        if forecasts:
            days = GaugeMetricFamily(
                "tpu_fleet_forecast_days_to_saturation",
                "Days until the pool saturates (duty rising to 95% or "
                "HBM headroom falling to 5%), least-squares over the "
                "ledger's coarse tier; ABSENT for pools whose history "
                "or trend cannot support a date — never a fabricated "
                "one. 0 means already saturated.",
                labels=("pool",),
            )
            slope = GaugeMetricFamily(
                "tpu_fleet_forecast_slope_per_day",
                "Fitted per-day trend slope per pool and signal "
                "(signal is the stored family the fit ran over).",
                labels=("pool", "signal"),
            )
            gated = GaugeMetricFamily(
                "tpu_fleet_forecast_insufficient_history",
                "1 when the pool's history span is below the "
                "minimum-history gate (TPUMON_FLEET_LEDGER_FORECAST_"
                "MIN_HISTORY_S) and no date is served, else 0 — the "
                "honesty surface capacity alerts can gate on.",
                labels=("pool",),
            )
            for pool, doc in sorted(forecasts.items()):
                eta = doc.get("days_to_saturation")
                if eta is not None:
                    days.add_metric((pool,), eta)
                gated.add_metric(
                    (pool,),
                    1.0 if doc["status"] == "insufficient_history"
                    else 0.0,
                )
                for signal, sig in sorted(
                    doc.get("signals", {}).items()
                ):
                    if "slope_per_day" in sig:
                        slope.add_metric(
                            (pool, signal), sig["slope_per_day"]
                        )
            analytics_fams.extend([days, slope, gated])
        out = [goodput, *energy_fams, *analytics_fams, series, samples,
               nbytes, dropped, gap, queries]
        if self.spool is not None:
            spool_errors = CounterMetricFamily(
                "tpu_ledger_spool_errors",
                "Ledger spool failures by op (load / write, plus "
                "enospc counted once per degradation transition); the "
                "plane runs on, memory-only.",
                labels=("op",),
            )
            for op, count in sorted(self.spool_errors.items()):
                spool_errors.add_metric((op,), float(count))
            out.append(spool_errors)
            degraded = GaugeMetricFamily(
                "tpu_ledger_spool_degraded",
                "1 while the ledger spool runs memory-only because the "
                "volume is full / read-only (ENOSPC/EROFS/EDQUOT).",
            )
            degraded.add_metric((), 1.0 if self.spool.degraded else 0.0)
            out.append(degraded)
        if self.remote_write_url:
            rw = CounterMetricFamily(
                "tpu_ledger_remote_write",
                "Remote-write push outcomes (result ∈ ok/error); absent "
                "unless TPUMON_FLEET_LEDGER_REMOTE_WRITE_URL is set.",
                labels=("result",),
            )
            for result, count in sorted(self.remote_write_counts.items()):
                rw.add_metric((result,), float(count))
            out.append(rw)
        return out

    # -- /ledger ------------------------------------------------------------

    def query_response(self, query_string: str) -> tuple[bytes, str]:
        """(body, status) for one GET /ledger. The shapes:

        - no parameters: the index (families, views, tiers, occupancy,
          goodput totals);
        - ``?view=goodput``: per-job bucket splits + conservation
          (plus the energy joules/dollars join when observed);
        - ``?view=waste``: top-k waste ranking
          (``group_by=job|pool|slice``, ``rank=topk:<n>``) with the
          conservation block spelled out;
        - ``?view=percentiles``: waste-fraction quantiles per workload
          class (pool + serve/train) and each job's percentile
          standing (``stat=p50|p90|p99`` narrows to one quantile);
        - ``?view=forecast``: per-pool saturation forecasts
          (optional ``pool=`` filter) — pools below the history gate
          answer status "insufficient_history", never a date;
        - ``?whatif=dollars_per_kwh:<v>`` on goodput/waste views:
          re-prices stored joules at v without touching raw samples
          or the configured price;
        - ``?family=...``: a range query — ``scope`` (slice/pool/fleet),
          optional ``pool``/``slice`` filters, ``start``/``end`` epoch
          seconds (default: the last hour), ``step`` seconds (tier
          selection hint), ``stat`` (mean/min/max at aggregate tiers),
          ``max_points`` (server-capped). Bounded responses carry
          ``next_start`` continuation cursors.
        - ``?family=...&agg=sum|mean|max[&by=pool|slice|job|none]``:
          SERVER-SIDE aggregation — the matched series fold across
          each other inside the read path (decode → aggregate →
          re-emit; the raw range is never materialized), one output
          series per ``by`` group (``group_by=`` is accepted as an
          alias). Byte-stable vs aggregating the raw range
          client-side (tests pin it), so consumers stop shipping
          per-slice series to compute a per-pool number. The fold
          composes with ``bucket=1h|1d`` (coarse re-bucketing;
          ``stat`` may then be ``mean`` or ``p50|p90|p99`` over each
          coarse bucket's points, emitted as [ts, value, n] triples
          so thin edge buckets are visible) and ``rank=topk:<n>``
          (series ordered by mean value, top n kept).
        """
        self.queries_total += 1
        try:
            params = dict(urllib.parse.parse_qsl(query_string))
        except ValueError:
            return _json_bytes({"error": "unparseable query"}), "400 Bad Request"
        whatif = None
        if "whatif" in params:
            whatif = analytics.parse_whatif(params["whatif"])
            if whatif is None:
                return _json_bytes({
                    "error": "whatif must be dollars_per_kwh:<positive "
                             "number>",
                }), "400 Bad Request"
        view = params.get("view")
        if view is not None and view not in VIEWS:
            return _json_bytes({
                "error": f"unknown view {view!r}",
                "views": list(VIEWS),
            }), "400 Bad Request"
        if view == "goodput":
            rows = self.goodput.jobs_doc()
            doc = {
                "now": self._clock(),
                "buckets": list(BUCKETS),
                "jobs": (
                    analytics.whatif_rows(rows, whatif)
                    if whatif is not None else rows
                ),
                "totals": self.goodput.totals(),
                "gap_seconds": self.goodput.gap_seconds,
                "dollars_per_kwh": self.goodput.dollars_per_kwh,
            }
            if whatif is not None:
                doc["whatif"] = {"dollars_per_kwh": whatif}
            return _json_bytes(doc), "200 OK"
        if view == "waste":
            group_by = params.get("group_by", "job")
            if group_by not in analytics.GROUP_KEYS:
                return _json_bytes({
                    "error": "group_by must be one of "
                             f"{sorted(analytics.GROUP_KEYS)}",
                }), "400 Bad Request"
            topk = analytics.parse_rank(params.get("rank", "topk:10"))
            if topk is None:
                return _json_bytes(
                    {"error": "rank must be topk:<1..1000>"}
                ), "400 Bad Request"
            doc = analytics.waste_doc(
                self.goodput.jobs_doc(), group_by, topk, price=whatif
            )
            doc["now"] = self._clock()
            doc["view"] = "waste"
            return _json_bytes(doc), "200 OK"
        if view == "percentiles":
            stat = params.get("stat")
            if stat is not None and stat not in analytics.PCT_STATS:
                return _json_bytes({
                    "error": "stat must be one of "
                             f"{sorted(analytics.PCT_STATS)}",
                }), "400 Bad Request"
            doc = analytics.percentiles_doc(
                self.goodput.jobs_doc(),
                [stat] if stat else list(analytics.PCT_STATS),
            )
            doc["now"] = self._clock()
            doc["view"] = "percentiles"
            return _json_bytes(doc), "200 OK"
        if view == "forecast":
            pools = self.forecasts()
            if "pool" in params:
                pool = params["pool"]
                pools = {pool: pools[pool]} if pool in pools else {}
            return _json_bytes({
                "now": self._clock(),
                "view": "forecast",
                "min_history_s": self.forecast_min_history_s,
                "computed_at": self._forecast_ts,
                "pools": pools,
            }), "200 OK"
        family = params.get("family")
        if not family:
            return _json_bytes(self._index_doc()), "200 OK"
        if family not in LEDGER_FAMILY_SET:
            return _json_bytes({
                "error": f"unknown family {family!r}",
                "families": sorted(LEDGER_FAMILY_SET),
            }), "400 Bad Request"
        now = self._clock()
        try:
            end = float(params.get("end", now))
            start = float(params.get("start", end - 3600.0))
            step = float(params["step"]) if "step" in params else None
            max_points = int(params.get("max_points", QUERY_MAX_POINTS))
        except ValueError:
            return _json_bytes(
                {"error": "malformed numeric parameter"}
            ), "400 Bad Request"
        if start >= end:
            return _json_bytes(
                {"error": "start must be before end"}
            ), "400 Bad Request"
        span_s = None
        if "bucket" in params:
            span_s = analytics.BUCKET_SPANS.get(params["bucket"])
            if span_s is None:
                return _json_bytes({
                    "error": "bucket must be one of "
                             f"{sorted(analytics.BUCKET_SPANS)}",
                }), "400 Bad Request"
        topk = None
        if "rank" in params:
            topk = analytics.parse_rank(params["rank"])
            if topk is None:
                return _json_bytes(
                    {"error": "rank must be topk:<1..1000>"}
                ), "400 Bad Request"
        stat = params.get("stat", "mean")
        pct_stat = stat if stat in analytics.PCT_STATS else None
        if pct_stat is not None and span_s is None:
            return _json_bytes({
                "error": f"stat={stat} requires bucket=1h|1d "
                         "(percentiles are computed over coarse "
                         "bucket contents)",
            }), "400 Bad Request"
        if span_s is not None and stat not in (
            "mean", *analytics.PCT_STATS
        ):
            return _json_bytes({
                "error": "bucket supports stat mean|p50|p90|p99",
            }), "400 Bad Request"
        if pct_stat is None and stat not in STATS:
            return _json_bytes({
                "error": f"stat must be one of {STATS} "
                         "(or p50|p90|p99 with bucket=)",
            }), "400 Bad Request"
        max_points = max(1, min(max_points, QUERY_MAX_POINTS_CEILING))
        scope = params.get("scope", "fleet")
        tier_idx = self.store.pick_tier(start, now, step)
        spec = self.store.tiers[tier_idx]
        keys = [
            key for key in self.store.series_keys()
            if key[0] == family and key[1] == scope
            and ("pool" not in params or key[2] == params["pool"])
            and ("slice" not in params or key[3] == params["slice"])
        ]
        agg = params.get("agg")
        if agg is None and (span_s is not None or topk is not None):
            return _json_bytes({
                "error": "bucket/rank require agg=sum|mean|max",
            }), "400 Bad Request"
        if agg is not None:
            if agg not in AGGS:
                return _json_bytes(
                    {"error": f"agg must be one of {AGGS}"}
                ), "400 Bad Request"
            by = params.get("by", params.get("group_by", "none"))
            if by not in GROUP_BYS:
                return _json_bytes(
                    {"error": f"by must be one of {GROUP_BYS}"}
                ), "400 Bad Request"
            if by == "pool":
                def group_of(key):
                    return (key[2], "")
            elif by in ("slice", "job"):
                def group_of(key):
                    return (key[2], key[3])
            else:
                def group_of(key):
                    return ("", "")
            groups, agg_next = self.store.fold(
                keys, tier_idx, start, end,
                stat="mean" if pct_stat else stat, agg=agg,
                group_of=group_of, max_points=max_points,
            )
            if span_s is not None and agg_next is not None:
                # Align the time cutoff DOWN to a coarse-bucket
                # boundary so no 1h/1d bucket is split across pages —
                # a split bucket's percentile would be silently wrong,
                # not partial. When the whole page fits inside one
                # coarse bucket no boundary can make progress; the
                # bucket is then served partial with its point count
                # visible (the documented edge-bucket error).
                boundary = agg_next - (agg_next % span_s)
                if boundary > start:
                    agg_next = boundary
                    groups = {
                        group: kept
                        for group, points in groups.items()
                        if (kept := [
                            p for p in points if p[0] < boundary
                        ])
                    }
            ordered = sorted(groups.items())
            if topk is not None:
                keep = analytics.rank_groups(groups, topk)
                ordered = [(group, groups[group]) for group in keep]
            series = []
            for (pool, slc), points in ordered:
                row = {
                    "pool": pool,
                    "slice": slc,
                    "stat": "raw" if tier_idx == 0 else stat,
                    "agg": agg,
                }
                if span_s is not None:
                    row["points"] = [
                        [bucket_ts, value, n]
                        for bucket_ts, value, n in analytics.rebucket(
                            points, span_s, pct_stat or "mean"
                        )
                    ]
                else:
                    row["points"] = [
                        [round(ts, 3), value] for ts, value in points
                    ]
                series.append(row)
            doc = {
                "family": family,
                "tier": spec.name,
                "resolution_s": spec.resolution_s,
                "agg": agg,
                "by": by,
                "start": start,
                "end": end,
                "series": series,
            }
            if span_s is not None:
                doc["bucket"] = params["bucket"]
            if topk is not None:
                doc["rank"] = f"topk:{topk}"
            if agg_next is not None:
                doc["truncated"] = True
                doc["next_start"] = agg_next
            return _json_bytes(doc), "200 OK"
        series = []
        remaining = max_points
        next_start = None
        for key in keys:
            if remaining <= 0:
                # Whole-series truncation: continuation resumes at the
                # window start for the series we never reached.
                next_start = start if next_start is None else min(
                    next_start, start
                )
                break
            points, cursor = self.store.query(
                key, tier_idx, start, end, stat=stat, max_points=remaining
            )
            remaining -= len(points)
            if cursor is not None:
                next_start = cursor if next_start is None else min(
                    next_start, cursor
                )
            series.append({
                "scope": key[1],
                "pool": key[2],
                "slice": key[3],
                "stat": "raw" if tier_idx == 0 else stat,
                "points": [[round(ts, 3), value] for ts, value in points],
            })
        doc = {
            "family": family,
            "tier": spec.name,
            "resolution_s": spec.resolution_s,
            "start": start,
            "end": end,
            "series": series,
        }
        if next_start is not None:
            doc["truncated"] = True
            doc["next_start"] = next_start
        return _json_bytes(doc), "200 OK"

    def _index_doc(self) -> dict:
        stats = self.store.stats()
        return {
            "now": self._clock(),
            "families": sorted(LEDGER_FAMILY_SET),
            "views": list(VIEWS),
            "tiers": stats["tiers"],
            "dropped_chunks": stats["dropped_chunks"],
            "goodput_totals": self.goodput.totals(),
            "gap_seconds": self.goodput.gap_seconds,
            "forecast": {
                pool: doc["status"]
                for pool, doc in sorted(self.forecasts().items())
            },
            "restored": self.restored,
        }

    def debug_block(self) -> dict:
        stats = self.store.stats()
        block = {
            "tiers": stats["tiers"],
            "dropped_chunks": stats["dropped_chunks"],
            "last_cycle_samples": self.last_cycle_samples,
            "gap_seconds": self.goodput.gap_seconds,
            "jobs": len(self.goodput.jobs()),
            "queries": self.queries_total,
            "forecast_pools": len(self._forecasts),
            "restored": self.restored,
        }
        if self.spool is not None:
            block["spool"] = {
                "path": self.spool.path,
                "last_write_ts": self.spool.last_write_ts,
                "errors": dict(self.spool_errors),
            }
        if self.remote_write_url:
            block["remote_write"] = dict(self.remote_write_counts)
        return block


__all__ = ["LedgerPlane", "QUERY_MAX_POINTS"]
