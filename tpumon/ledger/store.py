"""Tiered downsampling time-series store over the curated fleet
family set.

Three tiers by default — raw collect-cadence samples, 10-second
buckets, 5-minute buckets — each with its own retention window and
byte budget. Samples land in the raw tier; every finalized raw-tier
chunk boundary ALSO feeds per-series downsample accumulators, so a
coarser tier's bucket is (min, max, count-weighted mean) of the finer
tier's points. Nothing is ever interpolated: a collect-loop gap simply
has no samples in any tier (the plane ledgers known gap seconds as a
counter — absent honestly, never invented).

Storage unit: the immutable sealed chunk (tpumon/ledger/compress.py
Gorilla codec) plus one bounded open buffer per stream. Aggregate
tiers keep three parallel streams per series (stat ∈ mean/min/max)
sharing sample timestamps, so the one codec serves every tier.

Bounding is two-sided per tier: age (``retention_s`` — sealed chunks
whose newest sample fell out of the window drop) and bytes
(``max_bytes`` — oldest chunks drop tier-wide first, counted by
reason). Downsample error is documented, not hidden: a coarse bucket
whose source window straddles a retention or budget drop aggregates
the samples that survived; min/max remain true minima/maxima of the
aggregated points, the mean is weighted by the contributing count.

Pure in-memory + pure functions over time values passed in (no
clock reads) — the plane owns wall time, the spool owns disk.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass

from tpumon.ledger.compress import decode_chunk, encode_chunk

#: Chunk seal threshold (samples). 512 raw samples ≈ 8.5 min at 1 Hz;
#: small enough that retention drops are granular, large enough that
#: per-chunk overhead (~14 bytes header) amortizes away.
CHUNK_SAMPLES = 512

#: curated family -> rollup-bucket extractor. THE ledger family set:
#: what `/ledger` can answer about, what the bench compresses, what
#: OPERATIONS.md documents. Extractors return None for absent signals
#: (absent-not-zero, same stance as the live families) — except
#: stragglers, where 0 active stragglers is a real, meaningful value.
LEDGER_FAMILY_SET = {
    "tpu_fleet_duty_cycle_percent": (
        lambda b: (b.get("duty") or {}).get("mean")
    ),
    "tpu_fleet_mfu_ratio": lambda b: b.get("mfu"),
    "tpu_fleet_step_rate": lambda b: b.get("step_rate"),
    "tpu_fleet_hbm_headroom_ratio": lambda b: b.get("hbm_headroom_ratio"),
    "tpu_fleet_stragglers": (
        lambda b: float(sum(b.get("stragglers", {}).values()))
    ),
    "tpu_fleet_energy_watts": lambda b: b.get("energy_watts"),
    "tpu_fleet_tokens_per_joule": lambda b: b.get("tokens_per_joule"),
}

#: Aggregate-tier statistic streams.
STATS = ("mean", "min", "max")
RAW_STAT = "raw"

#: Server-side cross-series aggregation operators (GET /ledger?agg=).
AGGS = ("sum", "mean", "max")


@dataclass(frozen=True)
class TierSpec:
    """One tier: display name, bucket resolution, retention, bytes."""

    name: str
    resolution_s: float
    retention_s: float
    max_bytes: int


def default_tiers(
    retention_csv: str = "", max_bytes_total: int = 67108864
) -> tuple[TierSpec, ...]:
    """The 1 s → 10 s → 5 min ladder. ``retention_csv`` overrides the
    per-tier retention seconds (TPUMON_FLEET_LEDGER_RETENTION_S, three
    comma-separated values); a malformed entry keeps its default —
    config-typo tolerance, the tpumon.config stance. The byte budget
    splits 25/25/50: the coarse tier is the long-memory one and gets
    half."""
    retentions = [7200.0, 93600.0, 1209600.0]
    if retention_csv.strip():
        parts = retention_csv.split(",")
        for i, part in enumerate(parts[:3]):
            try:
                value = float(part)
                if value > 0:
                    retentions[i] = value
            except ValueError:
                pass
    shares = (0.25, 0.25, 0.5)
    return (
        TierSpec("1s", 1.0, retentions[0],
                 max(4096, int(max_bytes_total * shares[0]))),
        TierSpec("10s", 10.0, retentions[1],
                 max(4096, int(max_bytes_total * shares[1]))),
        TierSpec("5m", 300.0, retentions[2],
                 max(4096, int(max_bytes_total * shares[2]))),
    )


class _Stream:
    """One series' storage within one tier for one stat: sealed chunks
    plus the open buffer."""

    __slots__ = ("chunks", "open_ts", "open_vals")

    def __init__(self) -> None:
        #: [(start_ms, end_ms, n_samples, encoded)] — start-ordered.
        self.chunks: list[tuple[int, int, int, bytes]] = []
        self.open_ts: list[int] = []
        self.open_vals: list[float] = []

    def append(self, ts_ms: int, value: float) -> bool:
        """Append one sample; seals (returns True) at CHUNK_SAMPLES."""
        if self.open_ts and ts_ms <= self.open_ts[-1]:
            return False  # out-of-order/duplicate: first write wins
        self.open_ts.append(ts_ms)
        self.open_vals.append(value)
        if len(self.open_ts) >= CHUNK_SAMPLES:
            self.seal()
            return True
        return False

    def seal(self) -> int:
        """Encode + append the open buffer as a chunk; bytes added."""
        if not self.open_ts:
            return 0
        data = encode_chunk(self.open_ts, self.open_vals)
        self.chunks.append(
            (self.open_ts[0], self.open_ts[-1], len(self.open_ts), data)
        )
        self.open_ts = []
        self.open_vals = []
        return len(data)

    def bytes_sealed(self) -> int:
        return sum(len(c[3]) for c in self.chunks)

    def samples(self) -> int:
        return sum(c[2] for c in self.chunks) + len(self.open_ts)

    def points(self, start_ms: int, end_ms: int):
        """Yield (ts_ms, value) within [start_ms, end_ms] in order."""
        for c_start, c_end, _n, data in self.chunks:
            if c_end < start_ms or c_start > end_ms:
                continue
            ts, vals = decode_chunk(data)
            lo = bisect.bisect_left(ts, start_ms)
            hi = bisect.bisect_right(ts, end_ms)
            for i in range(lo, hi):
                yield ts[i], vals[i]
        lo = bisect.bisect_left(self.open_ts, start_ms)
        hi = bisect.bisect_right(self.open_ts, end_ms)
        for i in range(lo, hi):
            yield self.open_ts[i], self.open_vals[i]


class _Downsample:
    """One series' in-progress coarse bucket (min/max/weighted mean)."""

    __slots__ = ("bucket_start", "vmin", "vmax", "vsum", "n")

    def __init__(self) -> None:
        self.bucket_start = -1
        self.vmin = 0.0
        self.vmax = 0.0
        self.vsum = 0.0
        self.n = 0

    def add(self, value: float, weight: int = 1) -> None:
        if self.n == 0:
            self.vmin = self.vmax = value
        else:
            self.vmin = min(self.vmin, value)
            self.vmax = max(self.vmax, value)
        self.vsum += value * weight
        self.n += weight

    def finalize(self) -> tuple[float, float, float, int]:
        out = (self.vsum / self.n, self.vmin, self.vmax, self.n)
        self.bucket_start = -1
        self.vmin = self.vmax = self.vsum = 0.0
        self.n = 0
        return out


class TieredSeriesStore:
    """The multi-tier store. Single-writer (the collect thread), but
    READ from serving threads (/ledger range queries, /debug/vars,
    stats): one lock guards every structural access — streams dict,
    chunk lists, open buffers — because a seal swaps the open buffer
    and a retention drop mutates chunk lists mid-iteration otherwise.
    Writes hold it for one cycle's appends; reads hold it for one
    bounded query's decode (debug-class traffic)."""

    def __init__(self, tiers: tuple[TierSpec, ...] | None = None) -> None:
        self.tiers = tuple(tiers) if tiers else default_tiers()
        self._lock = threading.Lock()
        #: (series_key, tier_idx, stat) -> _Stream; series_key is the
        #: (family, scope, pool, slice) tuple.
        self._streams: dict[tuple, _Stream] = {}  # guarded-by: self._lock
        #: (series_key, tier_idx) -> _Downsample accumulator feeding
        #: tier_idx (from tier_idx-1's finalized buckets / raw samples).
        self._accums: dict[tuple, _Downsample] = {}  # guarded-by: self._lock
        #: Per-tier sealed byte totals (budget accounting).
        self._tier_bytes = [0] * len(self.tiers)  # guarded-by: self._lock
        self.samples_total = [0] * len(self.tiers)  # guarded-by: self._lock
        self.dropped_chunks = {"retention": 0, "budget": 0}  # guarded-by: self._lock
        self.last_record_ms = 0  # guarded-by: self._lock
        #: Records since bounds were last enforced (the full-scan sweep
        #: is amortized — see record()).
        self._records_since_enforce = 0  # guarded-by: self._lock

    # -- write -------------------------------------------------------------

    def _stream(self, key: tuple, tier: int, stat: str) -> _Stream:  # holds: self._lock
        slot = (key, tier, stat)
        stream = self._streams.get(slot)
        if stream is None:
            stream = self._streams[slot] = _Stream()
        return stream

    #: Bounds-sweep cadence (records): the retention/budget scan walks
    #: every stream, so it runs amortized — every N records or whenever
    #: a chunk sealed — instead of per collect cycle.
    ENFORCE_EVERY = 256

    def record(self, now_s: float, samples: dict[tuple, float]) -> None:
        """One collect cycle's curated samples: ``{(family, scope, pool,
        slice): value}`` at wall time ``now_s``. Values land in the raw
        tier and cascade into every coarser tier's accumulator."""
        ts_ms = int(round(now_s * 1000.0))
        with self._lock:
            if ts_ms <= self.last_record_ms:
                return  # a clock step backwards must not corrupt dod state
            self.last_record_ms = ts_ms
            sealed = False
            for key, value in samples.items():
                if value is None:
                    continue
                value = float(value)
                stream = self._stream(key, 0, RAW_STAT)
                if stream.append(ts_ms, value):
                    self._tier_bytes[0] += len(stream.chunks[-1][3])
                    sealed = True
                self.samples_total[0] += 1
                self._cascade(key, 1, ts_ms, value, value, value, 1)
            self._records_since_enforce += 1
            if sealed or self._records_since_enforce >= self.ENFORCE_EVERY:
                self._records_since_enforce = 0
                self._enforce_bounds(ts_ms)

    def _cascade(  # holds: self._lock
        self, key: tuple, tier: int, ts_ms: int,
        mean: float, vmin: float, vmax: float, weight: int,
    ) -> None:
        """Feed one finer-tier point/bucket into ``tier``'s accumulator;
        on bucket roll-over, emit the finalized bucket into the tier's
        streams and recurse one tier coarser."""
        if tier >= len(self.tiers):
            return
        res_ms = int(self.tiers[tier].resolution_s * 1000.0)
        bucket = (ts_ms // res_ms) * res_ms
        slot = (key, tier)
        acc = self._accums.get(slot)
        if acc is None:
            acc = self._accums[slot] = _Downsample()
        if acc.bucket_start >= 0 and bucket != acc.bucket_start:
            self._emit_bucket(key, tier, acc)
        if acc.bucket_start < 0:
            acc.bucket_start = bucket
        # min/max survive aggregation exactly; the mean is weighted by
        # the finer tier's contributing counts.
        if acc.n == 0:
            acc.vmin, acc.vmax = vmin, vmax
        else:
            acc.vmin = min(acc.vmin, vmin)
            acc.vmax = max(acc.vmax, vmax)
        acc.vsum += mean * weight
        acc.n += weight

    def _emit_bucket(self, key: tuple, tier: int, acc: _Downsample) -> None:  # holds: self._lock
        bucket_ts = acc.bucket_start
        mean, vmin, vmax, n = acc.finalize()
        for stat, value in (("mean", mean), ("min", vmin), ("max", vmax)):
            stream = self._stream(key, tier, stat)
            if stream.append(bucket_ts, value):
                self._tier_bytes[tier] += len(stream.chunks[-1][3])
        self.samples_total[tier] += 1
        self._cascade(key, tier + 1, bucket_ts, mean, vmin, vmax, n)

    def _enforce_bounds(self, now_ms: int) -> None:  # holds: self._lock
        for tier_idx, spec in enumerate(self.tiers):
            horizon = now_ms - int(spec.retention_s * 1000.0)
            freed = 0
            for (key, t, _stat), stream in self._streams.items():
                if t != tier_idx:
                    continue
                while stream.chunks and stream.chunks[0][1] < horizon:
                    freed += len(stream.chunks[0][3])
                    self.dropped_chunks["retention"] += 1
                    del stream.chunks[0]
            self._tier_bytes[tier_idx] -= freed
            while self._tier_bytes[tier_idx] > spec.max_bytes:
                # Over budget: drop the tier's OLDEST sealed chunk.
                oldest_slot = None
                oldest_start = None
                for slot, stream in self._streams.items():
                    if slot[1] != tier_idx or not stream.chunks:
                        continue
                    start = stream.chunks[0][0]
                    if oldest_start is None or start < oldest_start:
                        oldest_start = start
                        oldest_slot = slot
                if oldest_slot is None:
                    break
                stream = self._streams[oldest_slot]
                self._tier_bytes[tier_idx] -= len(stream.chunks[0][3])
                self.dropped_chunks["budget"] += 1
                del stream.chunks[0]

    def flush(self) -> None:
        """Seal every open buffer (bench/occupancy measurement).
        Accumulators stay open — they persist via :meth:`to_doc` and
        keep filling after a warm restart, which is what 'resumes
        mid-tier without double-counting' means."""
        with self._lock:
            for (_key, tier, _stat), stream in self._streams.items():
                if stream.open_ts:
                    self._tier_bytes[tier] += stream.seal()

    # -- read --------------------------------------------------------------

    def series_keys(self) -> list[tuple]:
        with self._lock:
            return sorted({slot[0] for slot in self._streams})

    def pick_tier(self, start_s: float, now_s: float,
                  step_s: float | None) -> int:
        """Tier for a range query: the finest tier that (a) still
        retains ``start_s`` and (b) is not finer than the asked step.
        A start older than every retention serves from the coarsest
        tier — bounded-answer-honestly, never an error."""
        for idx, spec in enumerate(self.tiers):
            if step_s is not None and spec.resolution_s < step_s * 0.999:
                continue
            if now_s - spec.retention_s <= start_s:
                return idx
        return len(self.tiers) - 1

    def query(
        self, key: tuple, tier: int, start_s: float, end_s: float,
        stat: str = "mean", max_points: int = 2000,
    ) -> tuple[list[tuple[float, float]], float | None]:
        """Points for one series from one tier over [start, end].

        Returns ``(points, next_start)``: points as (epoch seconds,
        value) capped at ``max_points``; ``next_start`` is the
        continuation cursor (seconds) when the range was truncated —
        the PR 4 bounded-replay discipline applied to range reads.
        """
        use_stat = RAW_STAT if tier == 0 else stat
        # round(), not truncation: continuation cursors are emitted as
        # ts_ms / 1000.0, and a float round-trip that lands a hair
        # below the integer would re-admit the already-emitted edge
        # point on resume (double count). record() rounds the same way.
        start_ms = int(round(start_s * 1000.0))
        end_ms = int(round(end_s * 1000.0))
        out: list[tuple[float, float]] = []
        # Under the lock end to end: points() walks chunk lists and the
        # open buffer, both of which the collect thread mutates (seal
        # swaps the buffer, retention pops chunks). The hold is bounded
        # by max_points on debug-class traffic.
        with self._lock:
            stream = self._streams.get((key, tier, use_stat))
            if stream is None:
                return [], None
            for ts_ms, value in stream.points(start_ms, end_ms):
                if len(out) >= max_points:
                    return out, ts_ms / 1000.0
                out.append((ts_ms / 1000.0, value))
        return out, None

    def fold(
        self,
        keys: list[tuple],
        tier: int,
        start_s: float,
        end_s: float,
        *,
        stat: str = "mean",
        agg: str = "sum",
        group_of,
        max_points: int = 2000,
    ) -> tuple[dict, float | None]:
        """Cross-series aggregation INSIDE the read path (the
        ``GET /ledger?agg=`` evaluator): chunks decode one at a time
        and fold straight into per-``(group, timestamp)`` accumulators
        — the full raw range is never materialized as per-series point
        lists, so a 10k-slice consumer stops shipping (and re-decoding)
        every slice's series client-side.

        Fold order is part of the byte-stability contract: series are
        visited in SORTED key order (enforced here, whatever order the
        caller passes — the same order the raw query emits), points in
        time order
        within each series, and the operators are ``sum`` (running
        float sum in visit order), ``mean`` (that sum divided by the
        contributing-series count — unweighted across series, exactly
        what client-side aggregation of the raw range computes), and
        ``max`` (first-wins on ties). A client folding the raw
        response the same way reproduces these bytes exactly
        (tests/test_ledger.py pins it).

        Truncation is BY TIME, never by cell: when the fold would
        exceed ``max_points`` total output points, a timestamp cutoff
        is chosen so every kept bucket still aggregates every series
        (a partially-folded bucket would be silently wrong, not
        partial), and ``next_start`` carries the continuation cursor.

        Returns ``({group: [(ts_s, value), ...]}, next_start|None)``.
        """
        use_stat = RAW_STAT if tier == 0 else stat
        # Same rounding contract as query(): the cutoff cursor is
        # cutoff_ms / 1000.0, and resuming from it must start AT the
        # first un-emitted bucket — truncation here would re-fold a
        # group's edge bucket into the next page.
        start_ms = int(round(start_s * 1000.0))
        end_ms = int(round(end_s * 1000.0))
        groups: dict[tuple, dict[int, list]] = {}
        total = 0
        cutoff_ms: int | None = None
        with self._lock:
            for key in sorted(keys):
                stream = self._streams.get((key, tier, use_stat))
                if stream is None:
                    continue
                acc = groups.setdefault(group_of(key), {})
                for ts_ms, value in stream.points(start_ms, end_ms):
                    if cutoff_ms is not None and ts_ms >= cutoff_ms:
                        # points() yields ascending per series: nothing
                        # after this survives the cutoff either, and
                        # decoding it just to skip it would hold the
                        # store lock against the collect thread.
                        break
                    cell = acc.get(ts_ms)
                    if cell is None:
                        acc[ts_ms] = [value, 1, value]
                        total += 1
                        if total > max_points:
                            cutoff_ms = self._fold_trim(
                                groups, max_points
                            )
                            total = sum(len(a) for a in groups.values())
                    else:
                        cell[0] += value
                        cell[1] += 1
                        if value > cell[2]:
                            cell[2] = value
        out: dict[tuple, list] = {}
        for group, acc in groups.items():
            points = []
            for ts_ms in sorted(acc):
                s, n, vmax = acc[ts_ms]
                if agg == "sum":
                    value = s
                elif agg == "mean":
                    value = s / n
                else:
                    value = vmax
                points.append((ts_ms / 1000.0, value))
            if points:
                out[group] = points
        return out, (cutoff_ms / 1000.0 if cutoff_ms is not None else None)

    @staticmethod
    def _fold_trim(groups: dict, max_points: int) -> int:
        """Pick the time cutoff that keeps at most ``max_points``
        folded points, and drop everything at or past it — bounding
        fold memory to ~the response size however wide the range is."""
        counts: dict[int, int] = {}
        for acc in groups.values():
            for ts_ms in acc:
                counts[ts_ms] = counts.get(ts_ms, 0) + 1
        ordered = sorted(counts)
        kept = 0
        cutoff = ordered[-1] + 1
        for ts_ms in ordered:
            kept += counts[ts_ms]
            if kept > max_points:
                cutoff = ts_ms
                break
        if cutoff == ordered[0]:
            # Degenerate: the first bucket alone exceeds the cap (more
            # groups than max_points). Keep it anyway — an empty
            # response with a cursor pointing at itself could never
            # advance.
            cutoff = ordered[1] if len(ordered) > 1 else ordered[0] + 1
        for acc in groups.values():
            for ts_ms in [t for t in acc if t >= cutoff]:
                del acc[ts_ms]
        return cutoff

    def stats(self) -> dict:
        """Per-tier occupancy for the tpu_ledger_* self-metrics and the
        bench's bytes-per-raw-sample headline."""
        tiers = []
        with self._lock:
            per_tier = [
                (set(), [0], [0], [0]) for _ in self.tiers
            ]
            for (key, t, stat), stream in self._streams.items():
                series, sealed_b, sealed_n, open_n = per_tier[t]
                series.add(key)
                if tier_primary_stat(t) == stat:
                    sealed_n[0] += sum(c[2] for c in stream.chunks)
                    open_n[0] += len(stream.open_ts)
                sealed_b[0] += stream.bytes_sealed()
            dropped = dict(self.dropped_chunks)
        for idx, spec in enumerate(self.tiers):
            series, sealed_b, sealed_n, open_n = per_tier[idx]
            sealed_bytes = sealed_b[0]
            sealed_samples = sealed_n[0]
            open_samples = open_n[0]
            tiers.append({
                "name": spec.name,
                "resolution_s": spec.resolution_s,
                "retention_s": spec.retention_s,
                "max_bytes": spec.max_bytes,
                "series": len(series),
                "sealed_bytes": sealed_bytes,
                "sealed_samples": sealed_samples,
                "open_samples": open_samples,
            })
        return {
            "tiers": tiers,
            "dropped_chunks": dropped,
        }

    # -- spool round-trip ---------------------------------------------------

    def to_doc(self) -> dict:
        """JSON-able state for the ledger spool: sealed chunks plus the
        open buffers AS PLAIN LISTS (force-sealing per journal cadence
        would fragment coarse-tier chunks down to a few samples each
        and wreck the bytes-per-sample density the tiers exist for)
        plus downsample accumulators so a restart resumes MID-BUCKET
        instead of emitting a short duplicate bucket."""
        import base64

        with self._lock:
            streams = []
            for (key, tier, stat), stream in sorted(
                self._streams.items(), key=lambda kv: (kv[0][1], kv[0][0])
            ):
                if not stream.chunks and not stream.open_ts:
                    continue
                streams.append({
                    "key": list(key),
                    "tier": tier,
                    "stat": stat,
                    "chunks": [
                        [c[0], c[1], c[2],
                         base64.b64encode(c[3]).decode("ascii")]
                        for c in stream.chunks
                    ],
                    "open": [list(stream.open_ts), list(stream.open_vals)],
                })
            accums = []
            for (key, tier), acc in sorted(
                self._accums.items(), key=lambda kv: (kv[0][1], kv[0][0])
            ):
                if acc.bucket_start < 0 or acc.n == 0:
                    continue
                accums.append({
                    "key": list(key), "tier": tier,
                    "bucket_start": acc.bucket_start,
                    "min": acc.vmin, "max": acc.vmax,
                    "sum": acc.vsum, "n": acc.n,
                })
            return {
                "streams": streams,
                "accums": accums,
                "last_record_ms": self.last_record_ms,
                "samples_total": list(self.samples_total),
            }

    @classmethod
    def from_doc(
        cls, doc: dict, tiers: tuple[TierSpec, ...] | None = None
    ) -> "TieredSeriesStore":
        """Rebuild from a spool doc; malformed entries are skipped
        individually (a partially corrupt spool restores what it can)."""
        import base64

        store = cls(tiers)
        # The fresh store is unpublished (single-threaded here); the
        # lock is held anyway so the discipline is uniform.
        with store._lock:
            return cls._restore_into(store, doc)

    @staticmethod
    def _restore_into(
        store: "TieredSeriesStore", doc: dict
    ) -> "TieredSeriesStore":
        # holds: store._lock
        import base64

        for row in doc.get("streams", ()):
            try:
                key = tuple(row["key"])
                tier = int(row["tier"])
                stat = str(row["stat"])
                if tier >= len(store.tiers):
                    continue
                stream = store._stream(key, tier, stat)
                for start, end, n, b64 in row["chunks"]:
                    data = base64.b64decode(b64)
                    stream.chunks.append(
                        (int(start), int(end), int(n), data)
                    )
                    store._tier_bytes[tier] += len(data)
                open_buf = row.get("open")
                if (
                    isinstance(open_buf, list) and len(open_buf) == 2
                    and isinstance(open_buf[0], list)
                    and isinstance(open_buf[1], list)
                    and len(open_buf[0]) == len(open_buf[1])
                ):
                    stream.open_ts = [int(t) for t in open_buf[0]]
                    stream.open_vals = [float(v) for v in open_buf[1]]
            except (KeyError, TypeError, ValueError):
                continue
        for row in doc.get("accums", ()):
            try:
                key = tuple(row["key"])
                tier = int(row["tier"])
                if tier < 1 or tier >= len(store.tiers):
                    continue
                acc = _Downsample()
                acc.bucket_start = int(row["bucket_start"])
                acc.vmin = float(row["min"])
                acc.vmax = float(row["max"])
                acc.vsum = float(row["sum"])
                acc.n = int(row["n"])
                store._accums[(key, tier)] = acc
            except (KeyError, TypeError, ValueError):
                continue
        store.last_record_ms = int(doc.get("last_record_ms") or 0)
        totals = doc.get("samples_total")
        if isinstance(totals, list) and len(totals) == len(
            store.samples_total
        ):
            try:
                store.samples_total = [int(v) for v in totals]
            except (TypeError, ValueError):
                pass
        return store


def tier_primary_stat(tier: int) -> str:
    """The stat stream whose sample count IS the tier's sample count
    (raw for tier 0, mean above — min/max share its timestamps)."""
    return RAW_STAT if tier == 0 else "mean"


__all__ = [
    "AGGS",
    "CHUNK_SAMPLES",
    "LEDGER_FAMILY_SET",
    "RAW_STAT",
    "STATS",
    "TierSpec",
    "TieredSeriesStore",
    "default_tiers",
    "tier_primary_stat",
]
