"""Backend factory (SURVEY.md §1 L1, §5.6 backend selection).

``auto`` resolution order: libtpu SDK importable and reporting a device →
libtpu; otherwise stub. The gRPC, fake, and NVML-compat backends are explicit
opt-ins (``--backend grpc|fake|nvml``).
"""

from __future__ import annotations

import logging

from tpumon.backends.base import Backend, BackendError, RawMetric
from tpumon.config import Config

log = logging.getLogger(__name__)

__all__ = ["Backend", "BackendError", "RawMetric", "create_backend"]


def create_backend(cfg: Config) -> Backend:
    kind = cfg.backend
    if kind == "auto":
        kind = _autodetect()
        log.info("backend auto-detected: %s", kind)

    if kind == "stub":
        from tpumon.backends.stub import StubBackend

        return StubBackend()
    if kind == "libtpu":
        from tpumon.backends.libtpu_backend import LibtpuBackend

        return LibtpuBackend(topology_file=cfg.topology_file)
    if kind == "grpc":
        from tpumon.backends.grpc_backend import GrpcMonitoringBackend

        return GrpcMonitoringBackend(
            addr=cfg.grpc_addr,
            timeout=cfg.grpc_timeout,
            topology_file=cfg.topology_file,
            service=cfg.grpc_service,
            watch=cfg.grpc_watch,
        )
    if kind == "fake":
        from tpumon.backends.fake import FakeTpuBackend

        return FakeTpuBackend.preset(cfg.fake_topology)
    if kind == "nvml":
        from tpumon.backends.nvml_backend import NvmlBackend

        return NvmlBackend()
    raise ValueError(f"unknown backend {kind!r}")


def _autodetect() -> str:
    # Decide on the monitoring SDK itself, not on chip discovery: the
    # metrics surface keeps working even when the compute runtime is
    # wedged or detached (observed live), and discovery may then report
    # zero chips.
    try:
        from libtpu.sdk import tpumonitoring

        if tpumonitoring.list_supported_metrics():
            return "libtpu"
        log.info("libtpu reports no supported metrics; using stub")
        return "stub"
    except Exception as exc:
        log.info("libtpu unavailable (%s); using stub", exc)
        return "stub"
