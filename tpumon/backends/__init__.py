"""Backend factory (SURVEY.md §1 L1, §5.6 backend selection).

``auto`` resolution order: libtpu SDK importable and reporting a device →
libtpu; otherwise stub. The gRPC, fake, and NVML-compat backends are explicit
opt-ins (``--backend grpc|fake|nvml``).
"""

from __future__ import annotations

import logging

from tpumon.backends.base import Backend, BackendError, RawMetric
from tpumon.config import Config

log = logging.getLogger(__name__)

__all__ = ["Backend", "BackendError", "RawMetric", "create_backend"]


def _retry_policy(cfg: Config):
    """Transport-level retry policy from config (tpumon/resilience)."""
    from tpumon.resilience import RetryPolicy

    base = Config()
    return RetryPolicy(
        # Clamp, don't substitute: TPUMON_RETRY_ATTEMPTS=0 means "no
        # retry" (same as 1), matching doctor's reported policy.
        attempts=max(1, cfg.retry_attempts),
        base_s=cfg.retry_base_s if cfg.retry_base_s > 0 else base.retry_base_s,
        max_s=cfg.retry_max_s if cfg.retry_max_s > 0 else base.retry_max_s,
    )


def create_backend(cfg: Config) -> Backend:
    kind = cfg.backend
    if kind == "auto":
        kind = _autodetect()
        log.info("backend auto-detected: %s", kind)

    backend: Backend
    if kind == "stub":
        from tpumon.backends.stub import StubBackend

        backend = StubBackend()
    elif kind == "libtpu":
        from tpumon.backends.libtpu_backend import LibtpuBackend

        backend = LibtpuBackend(
            topology_file=cfg.topology_file, retry=_retry_policy(cfg)
        )
    elif kind == "grpc":
        from tpumon.backends.grpc_backend import GrpcMonitoringBackend

        backend = GrpcMonitoringBackend(
            addr=cfg.grpc_addr,
            timeout=cfg.grpc_timeout,
            topology_file=cfg.topology_file,
            service=cfg.grpc_service,
            watch=cfg.grpc_watch,
            retry=_retry_policy(cfg),
        )
    elif kind == "fake":
        from tpumon.backends.fake import FakeTpuBackend

        backend = FakeTpuBackend.preset(cfg.fake_topology)
    elif kind == "nvml":
        from tpumon.backends.nvml_backend import NvmlBackend

        backend = NvmlBackend()
    else:
        raise ValueError(f"unknown backend {kind!r}")

    if cfg.faults:
        # Chaos mode (TPUMON_FAULTS): deterministic fault injection
        # around whichever backend was selected, so the resilience plane
        # is exercisable end to end without real device failures.
        from tpumon.resilience import FaultInjectingBackend, FaultSpec

        spec = FaultSpec.parse(cfg.faults)
        log.warning(
            "fault injection ACTIVE (TPUMON_FAULTS): %s", spec.describe()
        )
        # The fault layer carries the same transport-retry policy a real
        # flaky transport would sit beneath, so injected errors exercise
        # the retry plane too (not just breakers + stale serving).
        backend = FaultInjectingBackend(backend, spec, retry=_retry_policy(cfg))
    return backend


def _autodetect() -> str:
    # Decide on the monitoring SDK itself, not on chip discovery: the
    # metrics surface keeps working even when the compute runtime is
    # wedged or detached (observed live), and discovery may then report
    # zero chips.
    try:
        from libtpu.sdk import tpumonitoring

        if tpumonitoring.list_supported_metrics():
            return "libtpu"
        log.info("libtpu reports no supported metrics; using stub")
        return "stub"
    except Exception as exc:
        log.info("libtpu unavailable (%s); using stub", exc)
        return "stub"
