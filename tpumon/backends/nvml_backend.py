"""NVML schema-compat backend (BASELINE.json config 5: mixed GPU+TPU pool).

A thin GPU path so one DaemonSet + one Grafana dashboard serves a mixed
node pool: NVML device queries are re-emitted in the **libtpu wire formats**
(per-device string vectors) under the same source-metric names, so the
existing parser and the unified ``accelerator_*`` schema apply unchanged:

- GPU utilization      → ``duty_cycle_pct``   → accelerator_duty_cycle_percent
- SM occupancy proxy   → ``tensorcore_util``  → accelerator_core_utilization_percent
- framebuffer total    → ``hbm_capacity_total`` → accelerator_memory_total_bytes
- framebuffer used     → ``hbm_capacity_usage`` → accelerator_memory_used_bytes
- clock-throttle state → ``tpu_throttle_score`` → accelerator_throttle_score

``pynvml`` is not part of this image; the backend is import-gated and
raises BackendError at construction when NVML is absent (SURVEY.md §2.3).
"""

from __future__ import annotations

import logging
import socket

from tpumon.backends.base import BackendError, RawMetric
from tpumon.discovery.topology import Chip, Topology

log = logging.getLogger(__name__)

#: libtpu-style source names this backend can emit (subset of the schema).
NVML_METRICS: tuple[str, ...] = (
    "duty_cycle_pct",
    "tensorcore_util",
    "hbm_capacity_total",
    "hbm_capacity_usage",
    "tpu_throttle_score",
)


class NvmlBackend:
    name = "nvml"

    def __init__(self) -> None:
        try:
            import pynvml
        except ImportError as exc:
            raise BackendError(
                "pynvml not installed — the nvml backend only applies to "
                "GPU nodes of a mixed pool"
            ) from exc
        try:
            pynvml.nvmlInit()
        except Exception as exc:
            raise BackendError(f"nvmlInit failed: {exc}") from exc
        self._nv = pynvml
        self._handles = []
        count = pynvml.nvmlDeviceGetCount()
        for i in range(count):
            self._handles.append(pynvml.nvmlDeviceGetHandleByIndex(i))

    def list_metrics(self) -> tuple[str, ...]:
        return NVML_METRICS

    def sample(self, name: str) -> RawMetric:
        nv = self._nv
        try:
            if name == "duty_cycle_pct":
                data = tuple(
                    f"{nv.nvmlDeviceGetUtilizationRates(h).gpu:.2f}"
                    for h in self._handles
                )
            elif name == "tensorcore_util":
                data = tuple(
                    f"{nv.nvmlDeviceGetUtilizationRates(h).gpu:.2f}"
                    for h in self._handles
                )
            elif name == "hbm_capacity_total":
                data = tuple(
                    str(nv.nvmlDeviceGetMemoryInfo(h).total) for h in self._handles
                )
            elif name == "hbm_capacity_usage":
                data = tuple(
                    str(nv.nvmlDeviceGetMemoryInfo(h).used) for h in self._handles
                )
            elif name == "tpu_throttle_score":
                data = tuple(
                    str(self._throttle_score(h)) for h in self._handles
                )
            else:
                raise BackendError(f"unsupported metric {name}")
        except BackendError:
            raise
        except Exception as exc:
            raise BackendError(f"NVML query {name} failed: {exc}") from exc
        return RawMetric(name, data)

    def _throttle_score(self, handle) -> int:
        """Map NVML clock-throttle reasons onto the 0-10 throttle scale."""
        nv = self._nv
        try:
            reasons = nv.nvmlDeviceGetCurrentClocksThrottleReasons(handle)
        except Exception as exc:
            log.debug("throttle-reason query failed: %s", exc)
            return 0
        benign = getattr(nv, "nvmlClocksThrottleReasonGpuIdle", 0) | getattr(
            nv, "nvmlClocksThrottleReasonApplicationsClocksSetting", 0
        )
        return 10 if (reasons & ~benign) else 0

    def topology(self) -> Topology:
        nv = self._nv
        chips = []
        for i, h in enumerate(self._handles):
            uuid = ""
            try:
                raw = nv.nvmlDeviceGetUUID(h)
                uuid = raw.decode() if isinstance(raw, bytes) else str(raw)
            except Exception as exc:
                log.debug("UUID query failed for device %d: %s", i, exc)
            chips.append(Chip(index=i, num_cores=1, device_id=uuid))
        try:
            raw_name = nv.nvmlDeviceGetName(self._handles[0]) if chips else "gpu"
            accel = raw_name.decode() if isinstance(raw_name, bytes) else str(raw_name)
        except Exception as exc:
            log.debug("device-name query failed: %s", exc)
            accel = "gpu"
        return Topology(
            accelerator_type=accel,
            hostname=socket.gethostname(),
            chips=tuple(chips),
        )

    def version(self) -> str:
        try:
            raw = self._nv.nvmlSystemGetDriverVersion()
            return raw.decode() if isinstance(raw, bytes) else str(raw)
        except Exception as exc:
            log.debug("driver-version query failed: %s", exc)
            return "unknown"

    def close(self) -> None:
        try:
            self._nv.nvmlShutdown()
        except Exception as exc:
            log.debug("nvmlShutdown failed: %s", exc)
