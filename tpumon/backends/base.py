"""Device-backend protocol (SURVEY.md §1 L1).

The reference genre talks to NVML/DCGM; every TPU path here goes through this
protocol instead, so the exporter core (L3) never imports libtpu directly and
the fake backend is a drop-in (SURVEY.md §4.1).

Semantics distilled from the live probes (SURVEY.md §2.2):

- ``sample()`` returns the metric's raw per-chip/per-row **string vector**
  exactly as the device library reports it; parsing lives in
  :mod:`tpumon.parsing`, not in backends.
- An **empty vector means "no sample"** (the libtpu monitoring service only
  populates data while a runtime/workload is attached). It is NOT zero and
  must surface as an absent metric.
- Backend errors raise :class:`BackendError`; the poll loop converts them to
  ``collector_errors_total`` increments and keeps serving (SURVEY.md §5.3).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol, runtime_checkable

from tpumon.discovery.topology import Topology


class BackendError(RuntimeError):
    """A device query failed; the sample is dropped, the server lives on."""


@dataclass(frozen=True)
class RawMetric:
    """One raw sample of one device metric.

    ``data`` is the untouched string vector from the device library
    (e.g. ``("0.00", "20.00")`` or ``("tray1.chip3.ici0.int: 0",)``).
    Empty tuple == runtime detached / no data, never zero.
    """

    name: str
    data: tuple[str, ...]

    @property
    def empty(self) -> bool:
        return len(self.data) == 0


@runtime_checkable
class Backend(Protocol):
    """What every device backend (libtpu, grpc, fake, stub, nvml) implements."""

    #: Short name used in logs and the exporter_backend_info gauge.
    name: str

    def list_metrics(self) -> tuple[str, ...]:
        """Device-library metric names this backend can sample."""
        ...

    def sample(self, name: str) -> RawMetric:
        """Query one metric. Raises BackendError on device failure."""
        ...

    def topology(self) -> Topology:
        """Accelerator identity for label construction."""
        ...

    def version(self) -> str:
        """Version of the underlying device library (for backend_info)."""
        ...

    def close(self) -> None:
        """Release device handles (idempotent)."""
        ...
