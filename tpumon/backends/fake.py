"""FakeTpuBackend — a first-class test double (SURVEY.md §4.1).

Emits data in the exact libtpu wire formats captured live in SURVEY.md §2.2
(per-chip string vectors, ``key: value`` strings, comma-joined percentile
rows), over the topology ladder of BASELINE.json configs 1-4:

- ``none``   — 0 chips (CPU-only node)
- ``v4-8``   — single host, 4 chips × 2 cores
- ``v5e-16`` — 4 hosts × 4 chips × 1 core
- ``v5p-64`` — 16 hosts × 4 chips × 2 cores

Failure modes are explicit knobs because they were observed for real:

- ``attached=False`` → every metric returns an **empty vector**, the
  'runtime not attached' state the live probe hit (§2.2) — absent, not zero.
- ``fail_metrics`` → those metrics raise BackendError (libtpu call failure).
- ``malformed_metrics`` → those metrics emit garbage entries, which the
  parser must skip-and-count (SURVEY.md §4.2).

Data is deterministic in ``(seed, step, metric, chip)`` so golden tests are
stable; call :meth:`advance` to move time forward.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from tpumon.backends.base import BackendError, RawMetric
from tpumon.discovery.topology import Chip, Topology

#: All 14 libtpu 0.0.34 runtime metrics (SURVEY.md §2.2, live probe).
LIBTPU_METRICS: tuple[str, ...] = (
    "tensorcore_util",
    "ici_link_health",
    "tpu_throttle_score",
    "duty_cycle_pct",
    "buffer_transfer_latency",
    "collective_e2e_latency",
    "hbm_capacity_total",
    "hbm_capacity_usage",
    "hlo_execution_timing",
    "hlo_queue_size",
    "tcp_min_rtt",
    "tcp_delivery_rate",
    "host_to_device_transfer_latency",
    "device_to_host_transfer_latency",
)

_COLLECTIVES = ("ALL_REDUCE", "ALL_GATHER", "REDUCE_SCATTER", "ALL_TO_ALL")
_BUFFER_SIZES = ("0-8MB", "8MB+")
_ICI_PORTS = 4


@dataclass(frozen=True)
class Preset:
    accelerator_type: str
    num_hosts: int
    chips_per_host: int
    cores_per_chip: int
    hbm_bytes: int


TOPOLOGIES: dict[str, Preset] = {
    "none": Preset("none", 1, 0, 0, 0),
    "v4-8": Preset("v4-8", 1, 4, 2, 34_359_738_368),
    "v5e-16": Preset("v5litepod-16", 4, 4, 1, 17_179_869_184),
    "v5p-64": Preset("v5p-64", 16, 4, 2, 103_079_215_104),
    # Cardinality stress shape for the scrape-latency bench: twice the
    # per-host chip count of any real host so the exposition page clears
    # 1000 series (BENCH_r06 acceptance), not a hardware SKU.
    "bench-1k": Preset("bench-1k", 16, 12, 2, 103_079_215_104),
}


def _noise(seed: int, step: int, *key: object) -> float:
    """Deterministic uniform [0, 1) from a hash — stable across runs."""
    payload = f"{seed}|{step}|{'|'.join(str(k) for k in key)}".encode()
    digest = hashlib.blake2b(payload, digest_size=8).digest()
    return int.from_bytes(digest, "big") / 2**64


class FakeTpuBackend:
    name = "fake"

    def __init__(
        self,
        topology: Topology,
        *,
        hbm_bytes: int = 17_179_869_184,
        attached: bool = True,
        seed: int = 0,
        fail_metrics: tuple[str, ...] = (),
        malformed_metrics: tuple[str, ...] = (),
        ici_flake: float = 0.03,
        power_metric: bool = False,
    ) -> None:
        self._topology = topology
        self._hbm = hbm_bytes
        self.attached = attached
        self._seed = seed
        self._step = 0
        self.fail_metrics = set(fail_metrics)
        self.malformed_metrics = set(malformed_metrics)
        #: Per-step probability that a given ICI link reports unusable (10).
        #: 0.0 gives an always-healthy fabric (doctor/health OK-path tests).
        self.ici_flake = ici_flake
        #: Opt-in "device_power" metric (newer-runtime power telemetry):
        #: off by default so the 14-metric libtpu 0.0.34 shape stays the
        #: golden-test baseline; on, per-chip watts correlate with the
        #: same noise stream as duty_cycle_pct, so measured-vs-modeled
        #: comparisons are deterministic (tests/test_energy.py).
        self.power_metric = power_metric

    # -- construction -----------------------------------------------------

    @classmethod
    def preset(
        cls, name: str, *, worker_id: int = 0, hostname: str | None = None, **kwargs
    ) -> "FakeTpuBackend":
        try:
            p = TOPOLOGIES[name]
        except KeyError:
            raise ValueError(
                f"unknown fake topology {name!r}; choose from {sorted(TOPOLOGIES)}"
            ) from None
        slice_name = f"fake-{name}"
        host = hostname or f"{slice_name}-w{worker_id}"
        chips = tuple(
            Chip(
                index=i,
                coords=(i % 2, (i // 2) % 2, worker_id),
                num_cores=p.cores_per_chip,
                device_id=f"{slice_name}/{worker_id}/{i}",
            )
            for i in range(p.chips_per_host)
        )
        topo = Topology(
            accelerator_type=p.accelerator_type,
            slice_name=slice_name,
            hostname=host,
            worker_id=worker_id,
            num_hosts=p.num_hosts,
            chips=chips,
        )
        return cls(topo, hbm_bytes=p.hbm_bytes, **kwargs)

    # -- time -------------------------------------------------------------

    def advance(self, steps: int = 1) -> None:
        self._step += steps

    # -- Backend protocol -------------------------------------------------

    def list_metrics(self) -> tuple[str, ...]:
        if self.power_metric:
            return LIBTPU_METRICS + ("device_power",)
        return LIBTPU_METRICS

    def topology(self) -> Topology:
        return self._topology

    def version(self) -> str:
        from tpumon import __version__

        return f"fake-{__version__}"

    def core_states(self) -> dict[str, str]:
        """tpuz-analogue per-core state (SURVEY.md §2.2)."""
        if not self.attached or self._topology.num_chips == 0:
            return {}
        return {
            str(c): ("RUNNING" if self._u("state", c) < 0.95 else "HALTED")
            for c in range(self._topology.num_cores)
        }

    def close(self) -> None:
        pass

    def sample(self, name: str) -> RawMetric:
        if name in self.fail_metrics:
            raise BackendError(f"injected failure for {name}")
        # Membership is checked against the static sets, NOT via
        # list_metrics(): resilience tests wedge the enumeration call on
        # purpose, and sampling from the remembered list must keep
        # working through exactly that outage.
        if name not in LIBTPU_METRICS and not (
            self.power_metric and name == "device_power"
        ):
            raise BackendError(f"unsupported metric {name}")
        if not self.attached or self._topology.num_chips == 0:
            return RawMetric(name, ())
        data = self._generate(name)
        if name in self.malformed_metrics:
            data = ("not-a-number",) + data[1:] + ("trailing: garbage: x",)
        return RawMetric(name, data)

    # -- wire-format generation -------------------------------------------

    def _u(self, *key: object) -> float:
        return _noise(self._seed, self._step, *key)

    def _generate(self, name: str) -> tuple[str, ...]:
        topo = self._topology
        chips = range(topo.num_chips)
        cores = range(topo.num_cores)

        if name == "duty_cycle_pct":
            return tuple(f"{100 * self._u('duty', c):.2f}" for c in chips)
        if name == "device_power":
            # Watts tracking the SAME noise stream as duty_cycle_pct:
            # idle floor + duty-proportional draw, so measured-vs-
            # modeled comparisons are deterministic per (seed, step).
            return tuple(
                f"{200.0 * (0.15 + 0.85 * self._u('duty', c)):.2f}"
                for c in chips
            )
        if name == "tensorcore_util":
            return tuple(f"{100 * self._u('tc', c):.2f}" for c in cores)
        if name == "hbm_capacity_total":
            return tuple(str(self._hbm) for _ in chips)
        if name == "hbm_capacity_usage":
            return tuple(
                str(int(self._hbm * 0.9 * self._u("hbm", c))) for c in chips
            )
        if name == "tpu_throttle_score":
            return tuple(
                str(int(10 * max(0.0, self._u("thr", c) - 0.9) * 10)) for c in chips
            )
        if name == "ici_link_health":
            out = []
            for c in chips:
                tray = c // 4 + 1
                for port in range(_ICI_PORTS):
                    health = 0 if self._u("ici", c, port) < 1 - self.ici_flake else 10
                    out.append(f"tray{tray}.chip{c}.ici{port}.int: {health}")
            return tuple(out)
        if name == "hlo_queue_size":
            return tuple(
                f"tensorcore_{c}: {int(32 * self._u('queue', c))}" for c in cores
            )
        if name == "hlo_execution_timing":
            return tuple(self._pctl_row(f"tensorcore_{c}", "hlo", 500.0) for c in cores)
        if name == "collective_e2e_latency":
            return tuple(
                self._pctl_row(f"{size}-{op}", f"coll-{op}", 800.0)
                for size in _BUFFER_SIZES
                for op in _COLLECTIVES
            )
        if name in (
            "buffer_transfer_latency",
            "host_to_device_transfer_latency",
            "device_to_host_transfer_latency",
        ):
            return tuple(
                self._pctl_row(size, name, 300.0) for size in _BUFFER_SIZES
            )
        if name == "tcp_min_rtt":
            return (self._pctl_row(None, "rtt", 150.0),)
        if name == "tcp_delivery_rate":
            return (self._pctl_row(None, "rate", 4000.0),)
        raise AssertionError(name)

    def _pctl_row(self, key: str | None, salt: str, scale: float) -> str:
        base = scale * (0.5 + self._u(salt, key))
        vals = [
            base,
            base * 1.1,
            base * 1.8,
            base * 2.2,
            base * 3.5,
        ]  # mean, p50, p90, p95, p999
        row = ", ".join(f"{v:.2f}" for v in vals)
        return f"{key}, {row}" if key is not None else row
